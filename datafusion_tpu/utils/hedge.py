"""Hedged-dispatch policy: when to speculatively re-send a fragment.

Tail latency in a scatter-gather engine is set by the *slowest*
replica, not the median — one alive-but-slow worker (gray failure:
heartbeats renew, fragments crawl) stalls every query that routes a
fragment at it.  Hedging is the standard counter-measure (the
"tail at scale" defense): when a dispatched fragment has outrun what
its peers routinely achieve, send a duplicate to a different live
worker and take whichever valid response lands first.  Duplicates are
safe by construction here — fragments carry idempotent
``(query_id, shard)`` ids, workers serve replays from the fragment
cache, and the coordinator's merge loops drop duplicate responses.

`HedgeTracker` is the coordinator's evidence and throttle:

- **per-worker latency**: an EWMA and a mergeable log2
  `LatencyHistogram` per worker (the PR 8 histogram machinery), fed by
  every successful dispatch, plus a fleet-wide histogram;
- **the hedge threshold**: ``max(floor, quantile(p) * factor)`` from
  the dispatched worker's own history (what *it* routinely achieves),
  falling back to the fleet histogram below ``min_samples``, and to
  the bare floor with no history at all;
- **a hedge budget**: a `utils/retry.TokenBucket` accruing ``ratio``
  tokens per primary dispatch and spending one per hedge, so hedges
  stay a bounded fraction of real traffic — a fleet-wide slowdown
  (overload, not one straggler) must not double its own load.

The observe/threshold path is deliberately **lock-free** (dict stores
and GIL-atomic bucket increments): it runs inside the dispatch path
beside spans and metrics, under the same DF005 contract as the flight
recorder — `analysis/lint.py` enforces it.

Default **off** (`DATAFUSION_TPU_HEDGE=1` arms it; `from_env()`
returns None otherwise, and a None policy leaves the dispatch path
byte-identical).

Tunables (env, read by `from_env`):
  DATAFUSION_TPU_HEDGE_FACTOR       threshold = quantile * this (3.0)
  DATAFUSION_TPU_HEDGE_FLOOR_S      threshold floor, seconds (0.25)
  DATAFUSION_TPU_HEDGE_QUANTILE     history quantile (0.95)
  DATAFUSION_TPU_HEDGE_MIN_SAMPLES  history required per tier (4)
  DATAFUSION_TPU_HEDGE_RATIO        hedge tokens per dispatch (0.25)
  DATAFUSION_TPU_HEDGE_BURST        token-bucket cap (4.0)
"""

from __future__ import annotations

from typing import Optional

from datafusion_tpu.obs.aggregate import LatencyHistogram
from datafusion_tpu.utils.retry import TokenBucket, _env_bool, _env_float


class HedgeTracker:
    """Per-coordinator hedging evidence + budget (see module doc)."""

    def __init__(self, factor: float = 3.0, floor_s: float = 0.25,
                 quantile: float = 0.95, min_samples: int = 4,
                 ratio: float = 0.25, burst: float = 4.0,
                 tenant_buckets=None):
        self.factor = float(factor)
        self.floor_s = float(floor_s)
        self.quantile = float(quantile)
        self.min_samples = int(min_samples)
        self.ratio = float(ratio)
        self.burst = max(1.0, float(burst))
        # per-worker histograms + EWMAs and the fleet-wide histogram.
        # Written lock-free from dispatch threads (dict store, list
        # increment); a racing first-observe may drop one sample
        self._hists: dict[str, LatencyHistogram] = {}
        self.ewma: dict[str, float] = {}
        self._fleet = LatencyHistogram()
        # one initial token: the very first straggler can hedge
        self._bucket = TokenBucket(self.ratio, self.burst, initial=1.0)
        # multi-tenant QoS (datafusion_tpu/qos): per-tenant child
        # buckets drawing on the global one — a spend passes the
        # requesting tenant's child FIRST, and a child denial never
        # drains the global reserve.  None (QoS off) = byte-identical
        if tenant_buckets is None:
            from datafusion_tpu import qos

            tenant_buckets = qos.tenant_buckets_from_env(
                self.ratio, self.burst
            )
        self._tenants = tenant_buckets

    # -- evidence (lock-free: rides the dispatch path, DF005) --
    def observe(self, target: str, seconds: float) -> None:
        """One successful fragment round trip against `target`."""
        h = self._hists.get(target)
        if h is None:
            h = self._hists.setdefault(target, LatencyHistogram())
        h.observe(seconds)
        self._fleet.observe(seconds)
        prev = self.ewma.get(target)
        self.ewma[target] = seconds if prev is None \
            else 0.8 * prev + 0.2 * seconds

    def observe_dispatch(self, client: "str | None" = None) -> None:
        """One primary dispatch: accrue hedge credit (ratio tokens) —
        globally and, under QoS, in the dispatching tenant's child."""
        self._bucket.earn()
        if self._tenants is not None and client is not None:
            self._tenants.earn(client)

    def threshold_s(self, target: str) -> float:
        """How long `target`'s in-flight fragment may run before a
        hedge fires: its own history's quantile x factor, the fleet's
        below min_samples, the bare floor with no history."""
        h = self._hists.get(target)
        if h is None or h.count < self.min_samples:
            h = self._fleet
        if h.count < self.min_samples:
            return self.floor_s
        q = h.quantile(self.quantile)
        if q is None:
            return self.floor_s
        return max(self.floor_s, q * self.factor)

    def try_hedge(self, client: "str | None" = None) -> bool:
        """Spend one hedge token; False = budget exhausted, don't
        hedge.  Under QoS the requesting tenant's child bucket is
        spent FIRST: a tenant that burned its own hedge budget is
        denied without the global bucket being consulted or drained
        (``tenant.<id>.hedge_denied`` meter, ``hedge.tenant_denied``
        flight event), so its storm cannot spend the fleet's
        speculative-recovery reserve."""
        if self._tenants is not None and client is not None:
            if not self._tenants.spend(client):
                from datafusion_tpu.obs.attribution import METER
                from datafusion_tpu.obs.recorder import record
                from datafusion_tpu.utils.metrics import METRICS

                METRICS.add("hedge.tenant_denied")
                METER.charge(client, "hedge_denied", 1.0)
                record("hedge.tenant_denied", client=client)
                return False
            if not self._bucket.spend():
                # global denial: the child token was never acted on
                self._tenants.refund(client)
                return False
            return True
        return self._bucket.spend()

    def refund(self, client: "str | None" = None) -> None:
        """Return a spent token (the hedge was approved but never
        launched — e.g. no alternative worker existed)."""
        self._bucket.refund()
        if self._tenants is not None and client is not None:
            self._tenants.refund(client)

    # -- introspection --
    def gauges(self) -> dict:
        out = {"hedge.tokens": round(self._bucket.tokens, 3)}
        if self._tenants is not None:
            out.update(self._tenants.gauges("hedge"))
        # .copy(): dispatch threads insert new workers mid-scrape
        for target, v in sorted(self.ewma.copy().items()):
            out[f"hedge.ewma_s.{target}"] = round(v, 6)
        return out


def from_env() -> Optional[HedgeTracker]:
    """A tracker per the env knobs, or None when hedging is off (the
    default) — a None policy is the byte-identical dispatch path."""
    if not _env_bool("DATAFUSION_TPU_HEDGE"):
        return None
    return HedgeTracker(
        factor=_env_float("DATAFUSION_TPU_HEDGE_FACTOR", 3.0),
        floor_s=_env_float("DATAFUSION_TPU_HEDGE_FLOOR_S", 0.25),
        quantile=_env_float("DATAFUSION_TPU_HEDGE_QUANTILE", 0.95),
        min_samples=int(_env_float("DATAFUSION_TPU_HEDGE_MIN_SAMPLES", 4)),
        ratio=_env_float("DATAFUSION_TPU_HEDGE_RATIO", 0.25),
        burst=_env_float("DATAFUSION_TPU_HEDGE_BURST", 4.0),
    )
