"""Per-stage timing and counters.

The reference's only observability is a wall-clock `Instant` in the
console (`src/bin/console/main.rs:133`) and a `println!` of the plan
(`context.rs:104`).  Here every query records parse/plan/optimize/
compile/execute stage timings plus engine counters (rows scanned,
bytes H2D, jit cache activity) — queryable via
`ExecutionContext.metrics()` and printed by the CLI's `\\timing` mode.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager


class Metrics:
    def __init__(self):
        self.timings: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.gauges: dict[str, float] = {}
        self._declared: set[str] = set()

    def reset(self):
        self.timings.clear()
        self.counts.clear()
        self.gauges.clear()
        for name in self._declared:  # declared names survive resets
            self.counts[name] += 0

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timings[name] += time.perf_counter() - t0

    def add(self, name: str, n: int = 1):
        self.counts[name] += n

    def declare(self, *names: str) -> None:
        """Materialize counters at zero so their names render in every
        snapshot/scrape from process start.  The contract for metric
        names downstream dashboards depend on BEFORE the code that
        increments them lands (the serving path's admission counters
        are declared this way).  Declared names survive `reset()`."""
        self._declared.update(names)
        for name in names:
            self.counts[name] += 0

    def observe(self, name: str, seconds: float):
        """Fold an externally-measured duration into a stage timing
        (the obs subsystem's XLA-compile listener lands here — this
        registry is the single counter backend; see obs/export.py's
        `prometheus_text` for the scrape format)."""
        self.timings[name] += seconds

    def timed_iter(self, name: str, it):
        """Wrap a generator so time spent *producing* items (host parse,
        encode) accrues to `name`, while consumer time doesn't."""
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            finally:
                self.timings[name] += time.perf_counter() - t0
            yield item

    def gauge(self, name: str, value: float) -> None:
        """Set a point-in-time gauge (last-write-wins): per-query facts
        like `query.launches_per_pass` that counters can't express."""
        self.gauges[name] = value

    def snapshot(self) -> dict:
        return {
            "timings_s": dict(self.timings),
            "counts": dict(self.counts),
            "gauges": dict(self.gauges),
        }


# process-wide registry (a query engine, not a training loop: contention
# is nil and the reference used a global println anyway)
METRICS = Metrics()
