"""Per-stage timing and counters.

The reference's only observability is a wall-clock `Instant` in the
console (`src/bin/console/main.rs:133`) and a `println!` of the plan
(`context.rs:104`).  Here every query records parse/plan/optimize/
compile/execute stage timings plus engine counters (rows scanned,
bytes H2D, jit cache activity) — queryable via
`ExecutionContext.metrics()` and printed by the CLI's `\\timing` mode.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager

# -- profiler publication tables (obs/profiler.py) --------------------
# While the sampling profiler has at least one active capture, these
# hold {thread_ident: current stage timer name} and {thread_ident:
# current trace_id}; the sampler thread reads them to attribute each
# stack sample to a phase and a query.  They live HERE (not in the
# profiler) so the publishers — `Metrics.timer`, the device-put seam,
# `obs/trace.adopt` — need no new imports and pay exactly one module-
# global read + None check when profiling is off.  All accesses are
# plain dict ops (lock-free per the DF005 contract: publication runs
# inside other subsystems' critical sections).  A table swapped out
# mid-scope means a stale restore writes into an orphaned dict — a
# benign race the profiler tolerates (the next timer entry republishes).
PROFILE_STAGES = None  # type: ignore[var-annotated]
PROFILE_TRACES = None  # type: ignore[var-annotated]

# -- per-client charge scopes (obs/attribution.py) --------------------
# {thread_ident: scope payload} — which client's work this thread is
# doing, published by the serving front door (attribution.client_scope
# / shared_scope) and read by the cost hooks on other subsystems' hot
# paths (utils/retry.device_call launch walls, the obs/device.py H2D
# seam).  Same contract as the profiler tables above: plain dict ops,
# lock-free (DF005), one global read + .get miss when serving is off.
# Always a dict (not None-gated): the readers are per-launch, not
# per-sample, and a dict miss is cheaper than a None dance at every
# publisher.
CLIENT_SCOPES: dict = {}


def set_profile_tables(stages, traces) -> None:
    """Install (or clear, with None/None) the publication tables —
    called by the profiler on first-capture start / last-capture end."""
    global PROFILE_STAGES, PROFILE_TRACES
    PROFILE_STAGES = stages
    PROFILE_TRACES = traces


def stage_enter(name: str):
    """Publish `name` as this thread's active stage for the sampling
    profiler.  Returns a restore token for `stage_exit` (None when no
    profiler is capturing — the disabled cost is one global read)."""
    tbl = PROFILE_STAGES
    if tbl is None:
        return None
    tid = threading.get_ident()
    prev = tbl.get(tid)
    tbl[tid] = name
    return (tbl, tid, prev)


def stage_exit(token) -> None:
    if token is None:
        return
    tbl, tid, prev = token
    if prev is None:
        tbl.pop(tid, None)
    else:
        tbl[tid] = prev


class Metrics:
    def __init__(self):
        self.timings: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.gauges: dict[str, float] = {}
        self._declared: set[str] = set()

    def reset(self):
        self.timings.clear()
        self.counts.clear()
        self.gauges.clear()
        for name in self._declared:  # declared names survive resets
            self.counts[name] += 0

    @contextmanager
    def timer(self, name: str):
        tok = stage_enter(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timings[name] += time.perf_counter() - t0
            stage_exit(tok)

    def add(self, name: str, n: int = 1):
        self.counts[name] += n

    def declare(self, *names: str) -> None:
        """Materialize counters at zero so their names render in every
        snapshot/scrape from process start.  The contract for metric
        names downstream dashboards depend on BEFORE the code that
        increments them lands (the serving path's admission counters
        are declared this way).  Declared names survive `reset()`."""
        self._declared.update(names)
        for name in names:
            self.counts[name] += 0

    def observe(self, name: str, seconds: float):
        """Fold an externally-measured duration into a stage timing
        (the obs subsystem's XLA-compile listener lands here — this
        registry is the single counter backend; see obs/export.py's
        `prometheus_text` for the scrape format)."""
        self.timings[name] += seconds

    def timed_iter(self, name: str, it):
        """Wrap a generator so time spent *producing* items (host parse,
        encode) accrues to `name`, while consumer time doesn't."""
        while True:
            tok = stage_enter(name)
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            finally:
                self.timings[name] += time.perf_counter() - t0
                stage_exit(tok)
            yield item

    def gauge(self, name: str, value: float) -> None:
        """Set a point-in-time gauge (last-write-wins): per-query facts
        like `query.launches_per_pass` that counters can't express."""
        self.gauges[name] = value

    def snapshot(self) -> dict:
        return {
            "timings_s": dict(self.timings),
            "counts": dict(self.counts),
            "gauges": dict(self.gauges),
        }


# process-wide registry (a query engine, not a training loop: contention
# is nil and the reference used a global println anyway)
METRICS = Metrics()
