"""ctypes bindings for the C++ native runtime (`native/`).

The reference engine is fully native (Rust); the rebuild's host-side
runtime components are C++ with a C ABI, loaded here via ctypes
(pybind11 is not available in this environment).  Everything degrades
gracefully: when the shared library is absent and cannot be built, the
engine falls back to the pyarrow-backed readers.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
# search order: a library shipped INSIDE the installed package (wheel
# builds copy it here — scripts/release.sh), then the repo-root
# native/ build tree (development checkouts)
_PKG_LIB = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "libdatafusion_native.so"
)
_LIB_PATH = (
    _PKG_LIB
    if os.path.exists(_PKG_LIB)
    else os.path.join(_NATIVE_DIR, "libdatafusion_native.so")
)

_lib = None
_load_failed = False


def _configure(lib) -> None:
    lib.dtf_csv_open.restype = ctypes.c_void_p
    lib.dtf_csv_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.dtf_csv_error.restype = ctypes.c_char_p
    lib.dtf_csv_error.argtypes = [ctypes.c_void_p]
    lib.dtf_csv_next.restype = ctypes.c_int64
    lib.dtf_csv_next.argtypes = [ctypes.c_void_p]
    lib.dtf_csv_col_data.restype = ctypes.c_void_p
    lib.dtf_csv_col_data.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dtf_csv_col_validity.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.dtf_csv_col_validity.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dtf_csv_dict_size.restype = ctypes.c_int32
    lib.dtf_csv_dict_size.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dtf_csv_dict_value.restype = ctypes.c_void_p
    lib.dtf_csv_dict_value.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.dtf_csv_close.restype = None
    lib.dtf_csv_close.argtypes = [ctypes.c_void_p]
    # SQL front-end + plan IR (native/sql_frontend.cpp).  restype is
    # c_void_p (not c_char_p) so the malloc'd pointer survives for
    # string_at + dtf_free instead of being auto-converted and leaked.
    for fn in ("dtf_parse_sql", "dtf_plan_roundtrip", "dtf_plan_repr"):
        f = getattr(lib, fn)
        f.restype = ctypes.c_void_p
        f.argtypes = [ctypes.c_char_p]
    lib.dtf_free.restype = None
    lib.dtf_free.argtypes = [ctypes.c_void_p]


def build_library() -> bool:
    """Compile the shared library (idempotent); True on success."""
    srcs = [
        os.path.join(_NATIVE_DIR, f)
        for f in ("datafusion_native.cpp", "sql_frontend.cpp")
        if os.path.exists(os.path.join(_NATIVE_DIR, f))
    ]
    if not srcs:
        return False
    if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= max(
        os.path.getmtime(s) for s in srcs
    ):
        return True
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR], check=True,
            capture_output=True, timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except (OSError, subprocess.SubprocessError):
        # no make / compiler missing / build error or timeout: callers
        # fall back to the pure-Python front-end
        return False


def load_library(build: bool = True):
    """The loaded ctypes library, or None when unavailable.

    Disable entirely with DATAFUSION_TPU_NATIVE=0.
    """
    global _lib, _load_failed
    if os.environ.get("DATAFUSION_TPU_NATIVE", "1") == "0":
        return None
    if _lib is not None or _load_failed:
        return _lib
    if build:
        # always consult the build (idempotent mtime check): a stale .so
        # from an older source set would otherwise load but fail symbol
        # configuration and silently disable every native component
        build_library()
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        _configure(lib)
        _lib = lib
    except (OSError, AttributeError):
        # missing .so, or a stale build missing symbols: fall back to
        # the pyarrow readers rather than crashing datasource setup
        _load_failed = True
    return _lib


def native_available() -> bool:
    return load_library() is not None
