"""Shared closed-loop load generator for the serving front door.

Both consumers of the serving benchmark protocol — the `concurrency`
bench config (`benchmarks/suite.config_concurrency`) and the CI gate
(`scripts/serve_smoke.py`) — drive the same harness pieces from here,
so the measurement methodology cannot drift between them:

- `launch_floor_plan(ms)`: the injected per-launch latency floor (a
  seeded `device.call` delay rule).  Host-CPU dispatch is ~0.2 ms and
  models no link at all; the floor reproduces the launch round trip
  PR 6 / BENCH_r04 measured on tunneled transports (10-15 ms).  BOTH
  legs (serialized and served) run under the same floor.
- `closed_loop(...)`: N client threads, each submitting its slice of
  distinct-literal queries back-to-back; returns the round's wall.
- `warm_rungs(...)`: precompiles every megabatch query-count rung a
  fragmented window can produce, so a timed phase is compile-free.
- `phase_quantiles(...)`: timed-phase-only p50/p99 from the
  cumulative `serve.latency` histogram by subtracting its pre-phase
  snapshot (bucket-wise negative merge).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


def launch_floor_plan(floor_ms: float) -> dict:
    """Fault-plan JSON injecting `floor_ms` of latency per device
    launch (every `device.call` site hit, unlimited count)."""
    return {"seed": 7, "rules": [{
        "site": "device.call", "op": "delay",
        "seconds": floor_ms / 1e3, "count": 0,
    }]}


def closed_loop(srv, q: Callable[[float], str], clients: int,
                per_client: int, lit_of: Callable[[int], float],
                sink: dict, errors: list,
                timeout_s: float = 300.0,
                client_prefix: str = "c") -> float:
    """One closed-loop round: `clients` threads each submit
    `per_client` queries (literal = `lit_of(global_index)`), blocking
    on each result.  Results land in `sink[(client, i)]`; failures
    append to `errors`.  Returns the round's wall seconds.  Each
    thread submits under its own ``client_id``
    (``<client_prefix><index>``) so per-client metering
    (obs/attribution.py) attributes the round's costs — the smoke's
    conservation gate and the bench's metering record both read them
    back."""

    def client(ci: int):
        cid = f"{client_prefix}{ci}"
        for qi in range(per_client):
            try:
                sink[(ci, qi)] = srv.submit(
                    q(lit_of(ci * per_client + qi)), client_id=cid,
                ).result(timeout=timeout_s)
            except Exception as e:  # noqa: BLE001 — callers gate on `errors`
                errors.append((ci, qi, e))

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(clients)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return time.perf_counter() - t0


def warm_rungs(srv, q: Callable[[float], str], clients: int,
               timeout_s: float = 300.0) -> None:
    """Precompile every megabatch query-count rung a window can
    produce (a straggling client can fragment a round into any group
    size <= clients), so a later timed phase is deterministically
    compile-free."""
    from datafusion_tpu.exec.fused import bucket_group

    for sz in sorted({bucket_group(k) for k in range(1, clients + 1)}):
        tickets = [srv.submit(q(0.84 + sz * 1e-3 + j * 1e-4),
                              client_id="warmup")
                   for j in range(sz)]
        for t in tickets:
            t.result(timeout=timeout_s)


def phase_quantiles(hist, before_snapshot: Optional[dict]):
    """(p50, p99) of the observations a cumulative histogram gained
    since `before_snapshot` (None = since birth): merge the snapshot
    in negated so warm-up/compile latencies don't pollute the timed
    phase."""
    from datafusion_tpu.obs.aggregate import LatencyHistogram

    if hist is None:
        return None, None
    phase = LatencyHistogram.empty_like(hist).merge(hist)
    if before_snapshot is not None:
        phase.merge({
            **before_snapshot,
            "buckets": [-b for b in before_snapshot["buckets"]],
            "count": -before_snapshot["count"],
            "sum_s": -before_snapshot["sum_s"],
        })
    return phase.quantile(0.5), phase.quantile(0.99)
