"""The five mandated benchmark configs (BASELINE.md).

The reference's own bench list is commented out and publishes no
numbers (`/root/reference/Cargo.toml:50-68`, `.travis.yml:30-33`), so
the baseline for every config is this engine's own single-thread CPU
path on identical inputs, and `vs_baseline` is the TPU speedup over it.
"""
