"""Benchmark input generation, cached on disk under test/data/bench/.

TPC-H lineitem (the Q1 column subset) is generated at a given scale
factor and written as Parquet — the input BASELINE.md config 3
mandates; the reference never got a Parquet reader (`README.md:22`).
Generation is seeded and chunked so SF-10 (~60M rows) streams through
a bounded footprint.
"""

from __future__ import annotations

import os

import numpy as np

BENCH_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "test", "data", "bench",
)

LINEITEM_ROWS_PER_SF = 6_000_000
_CHUNK = 1_000_000


def lineitem_parquet(sf: float) -> str:
    """Path to the cached lineitem Parquet for scale factor `sf`;
    generates it on first use."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(BENCH_DIR, exist_ok=True)
    tag = str(sf).replace(".", "_")
    path = os.path.join(BENCH_DIR, f"lineitem_sf{tag}.parquet")
    if os.path.exists(path):
        return path

    rows = int(LINEITEM_ROWS_PER_SF * sf)
    rng = np.random.default_rng(42)
    base = np.datetime64("1992-01-02")
    n_dates = 2526  # 1992-01-02 .. 1998-12-01, receiptdate horizon
    date_strs = pa.array(
        [str(base + np.timedelta64(i, "D")) for i in range(n_dates)]
    )
    flags = pa.array(["A", "N", "R"])
    statuses = pa.array(["F", "O"])

    schema = pa.schema(
        [
            ("l_returnflag", pa.string()),
            ("l_linestatus", pa.string()),
            ("l_quantity", pa.float64()),
            ("l_extendedprice", pa.float64()),
            ("l_discount", pa.float64()),
            ("l_tax", pa.float64()),
            ("l_shipdate", pa.string()),
        ]
    )
    tmp = path + ".tmp"
    writer = pq.ParquetWriter(tmp, schema)
    try:
        for start in range(0, rows, _CHUNK):
            n = min(_CHUNK, rows - start)
            ship = rng.integers(0, n_dates, n).astype(np.int64)
            # returnflag correlates with shipdate in TPC-H (returns only
            # for old orders); keep the same flavor of skew
            old = ship < (n_dates // 2)
            flag = np.where(
                old, rng.integers(0, 2, n) * 2, np.int64(1)
            )  # old -> A/R, recent -> N
            status = (ship >= (n_dates * 5 // 8)).astype(np.int64)  # F then O
            cols = [
                pa.DictionaryArray.from_arrays(pa.array(flag, pa.int32()), flags).cast(pa.string()),
                pa.DictionaryArray.from_arrays(pa.array(status, pa.int32()), statuses).cast(pa.string()),
                pa.array(np.floor(rng.uniform(1, 51, n))),
                pa.array(np.round(rng.uniform(900.0, 104950.0, n), 2)),
                pa.array(rng.integers(0, 11, n) / 100.0),
                pa.array(rng.integers(0, 9, n) / 100.0),
                pa.DictionaryArray.from_arrays(pa.array(ship, pa.int32()), date_strs).cast(pa.string()),
            ]
            writer.write_table(pa.Table.from_arrays(cols, schema=schema))
    finally:
        writer.close()
    os.replace(tmp, path)
    return path


def cities_csv(rows: int) -> str:
    """A scaled-up uk_cities.csv (the `examples/csv_sql.rs` workload
    shape): city name, lat, lng; header row."""
    import pyarrow as pa
    import pyarrow.csv as pacsv

    os.makedirs(BENCH_DIR, exist_ok=True)
    path = os.path.join(BENCH_DIR, f"cities_{rows}.csv")
    if os.path.exists(path):
        return path
    rng = np.random.default_rng(7)
    pool = np.array([f"city_{i:04d}" for i in range(2000)])
    tbl = pa.table(
        {
            "city": pa.array(pool[rng.integers(0, len(pool), rows)]),
            "lat": pa.array(np.round(rng.uniform(49.9, 59.0, rows), 6)),
            "lng": pa.array(np.round(rng.uniform(-7.6, 1.8, rows), 6)),
        }
    )
    tmp = path + ".tmp"
    pacsv.write_csv(tbl, tmp)
    os.replace(tmp, path)
    return path


def groupby_batches(rows: int, groups: int, batch_rows: int, seed: int = 3):
    """In-memory table for config 2: int64 key of `groups` cardinality +
    three value columns.  Returns (schema, MemoryDataSource)."""
    from datafusion_tpu.datatypes import DataType, Field, Schema
    from datafusion_tpu.exec.batch import make_host_batch
    from datafusion_tpu.exec.datasource import MemoryDataSource

    schema = Schema(
        [
            Field("k", DataType.INT64, False),
            Field("v1", DataType.FLOAT64, False),
            Field("v2", DataType.FLOAT64, False),
            Field("v3", DataType.INT64, False),
        ]
    )
    rng = np.random.default_rng(seed)
    batches = []
    for start in range(0, rows, batch_rows):
        n = min(batch_rows, rows - start)
        cols = [
            rng.integers(0, groups, n).astype(np.int64),
            rng.uniform(0.0, 1000.0, n),
            rng.uniform(-1.0, 1.0, n),
            rng.integers(-(10**9), 10**9, n).astype(np.int64),
        ]
        batches.append(make_host_batch(schema, cols, [None] * 4, [None] * 4))
    return schema, MemoryDataSource(schema, batches)


def sort_batches(rows: int, batch_rows: int):
    """In-memory table for config 4: two sort keys + payload."""
    from datafusion_tpu.datatypes import DataType, Field, Schema
    from datafusion_tpu.exec.batch import make_host_batch
    from datafusion_tpu.exec.datasource import MemoryDataSource

    schema = Schema(
        [
            Field("a", DataType.FLOAT64, False),
            Field("b", DataType.INT64, False),
            Field("x", DataType.FLOAT64, False),
            Field("s", DataType.FLOAT32, False),  # single-key fast path
        ]
    )
    rng = np.random.default_rng(11)
    batches = []
    for start in range(0, rows, batch_rows):
        n = min(batch_rows, rows - start)
        cols = [
            rng.uniform(0.0, 1e6, n),
            rng.integers(0, 1 << 40, n).astype(np.int64),
            rng.uniform(0.0, 1.0, n),
            rng.uniform(0.0, 1e6, n).astype(np.float32),
        ]
        batches.append(make_host_batch(schema, cols, [None] * 4, [None] * 4))
    return schema, MemoryDataSource(schema, batches)


def tpch_join_csvs(sf: float = 0.01):
    """TPC-H-lite star-schema CSVs for the join configs (Q3/Q5/Q10/Q12
    shapes): nation/customer/orders/lineitem at roughly `sf` times the
    spec's cardinalities, seeded, cached on disk.  Returns
    {table: (path, schema)} plus enough skew (dangling orders, repeated
    customers) that LEFT OUTER and dedup paths do real work."""
    from datafusion_tpu.datatypes import DataType, Field, Schema

    os.makedirs(BENCH_DIR, exist_ok=True)
    n_cust = max(200, int(150_000 * sf))
    n_orders = max(2_000, int(1_500_000 * sf))
    n_line = max(8_000, int(6_000_000 * sf))
    n_nation = 25
    tag = f"sf{sf:g}"
    rng = np.random.default_rng(19)

    def write(name, header, rows):
        path = os.path.join(BENCH_DIR, f"join_{name}_{tag}.csv")
        if os.path.exists(path):
            return path
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(header + "\n")
            for r in rows:
                f.write(",".join(str(v) for v in r) + "\n")
        os.replace(tmp, path)
        return path

    nation = [(i, f"NATION_{i:02d}") for i in range(n_nation)]
    cust = [
        (i, int(rng.integers(0, n_nation)), int(rng.integers(0, 5)),
         round(float(rng.uniform(-999, 9999)), 2))
        for i in range(n_cust)
    ]
    # ~2% of orders reference customers past the table (dangling keys)
    orders = [
        (i, int(rng.integers(0, int(n_cust * 1.02))),
         f"1995-{rng.integers(1, 13):02d}-{rng.integers(1, 29):02d}",
         int(rng.integers(0, 3)))
        for i in range(n_orders)
    ]
    line = [
        (int(rng.integers(0, n_orders)), int(rng.integers(1, 51)),
         round(float(rng.uniform(900, 105000)), 2),
         round(float(rng.uniform(0, 0.1)), 2), int(rng.integers(0, 7)))
        for _ in range(n_line)
    ]
    I64, F64, U8 = DataType.INT64, DataType.FLOAT64, DataType.UTF8
    return {
        "nation": (
            write("nation", "n_nationkey,n_name", nation),
            Schema([Field("n_nationkey", I64, False),
                    Field("n_name", U8, False)]),
        ),
        "customer": (
            write("customer", "c_custkey,c_nationkey,c_mktsegment,c_acctbal",
                  cust),
            Schema([Field("c_custkey", I64, False),
                    Field("c_nationkey", I64, False),
                    Field("c_mktsegment", I64, False),
                    Field("c_acctbal", F64, False)]),
        ),
        "orders": (
            write("orders", "o_orderkey,o_custkey,o_orderdate,o_shippriority",
                  orders),
            Schema([Field("o_orderkey", I64, False),
                    Field("o_custkey", I64, False),
                    Field("o_orderdate", U8, False),
                    Field("o_shippriority", I64, False)]),
        ),
        "lineitem": (
            write("lineitem", "l_orderkey,l_quantity,l_extendedprice,"
                  "l_discount,l_shipmode", line),
            Schema([Field("l_orderkey", I64, False),
                    Field("l_quantity", I64, False),
                    Field("l_extendedprice", F64, False),
                    Field("l_discount", F64, False),
                    Field("l_shipmode", I64, False)]),
        ),
    }
