"""The five BASELINE.md benchmark configs.

Protocol (BASELINE.md "Measurement protocol"): the engine's own
single-thread CPU path is the baseline (the reference functionally
cannot run configs 2-5 — aggregates/sort are `unimplemented!()`,
`context.rs:161`); warm runs report p50 after warm-up (device-resident
steady state, excludes XLA compile); cold runs rebuild the operator
tree and re-scan the file each time, so they include parse, dictionary
encode, H2D, kernel, and D2H — with a per-phase breakdown from the
engine's METRICS counters.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

from benchmarks import data as bdata


def log(*a):
    print(*a, file=sys.stderr, flush=True)


WARMUP = int(os.environ.get("BENCH_WARMUP", 3))
WARM_RUNS = int(os.environ.get("BENCH_RUNS", 10))
COLD_RUNS = int(os.environ.get("BENCH_COLD_RUNS", 3))

Q1 = (
    "SELECT l_returnflag, l_linestatus, "
    "SUM(l_quantity), SUM(l_extendedprice), "
    "SUM(l_extendedprice * (1 - l_discount)), "
    "SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)), "
    "AVG(l_quantity), AVG(l_extendedprice), AVG(l_discount), COUNT(1) "
    "FROM lineitem "
    "WHERE l_shipdate <= '1998-09-02' "
    "GROUP BY l_returnflag, l_linestatus"
)


def _p50(times: list[float]) -> float:
    return float(np.median(times))


def _timed(fn, runs: int, warmup: int = WARMUP) -> tuple[float, object]:
    out = None
    for _ in range(warmup):
        out = fn()
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = fn()
        times.append(time.perf_counter() - t0)
    return _p50(times), out


def _assert_tables_match(got, want, label: str, rtol=1e-9):
    got_rows = sorted(got.to_rows())
    want_rows = sorted(want.to_rows())
    assert len(got_rows) == len(want_rows), (
        f"{label}: row count differs: {len(got_rows)} vs {len(want_rows)}"
    )
    for g, w in zip(got_rows, want_rows):
        for gv, wv in zip(g, w):
            if isinstance(gv, float) or isinstance(wv, float):
                np.testing.assert_allclose(gv, wv, rtol=rtol, err_msg=label)
            else:
                assert gv == wv, f"{label}: {gv!r} != {wv!r} in {g} vs {w}"


def _has_tpu() -> bool:
    import jax

    return any(d.platform != "cpu" for d in jax.devices())


def _hbm_peak_gbps() -> float:
    """Chip peak HBM bandwidth (BENCH_HBM_PEAK_GBPS overrides)."""
    import jax

    peaks = {"tpu": 819.0, "v5e": 819.0, "v4": 1228.0, "v6e": 1640.0}
    dev0 = jax.devices()[0]
    kind = getattr(dev0, "device_kind", "").lower()
    peak = next(
        (v for k, v in peaks.items() if k != "tpu" and k in kind),
        peaks["tpu"],
    )
    return float(os.environ.get("BENCH_HBM_PEAK_GBPS", peak))


def _pass_metrics(fn, bytes_per_pass: float, runs: int = 3) -> dict:
    """Measured launches_per_pass (the `device.launches` counter the
    engine increments per executable dispatch — not a formula) and an
    achieved-HBM estimate for one warm query, so BENCH rounds can check
    both monotonically.  `hbm_peak_bytes` is MEASURED residency from
    the device ledger (obs/device.py): the high-water mark of
    actually-live device buffers across the timed passes, replacing the
    guessed-peak formula as the item-4 `hbm_util` gate's numerator
    source of truth."""
    from datafusion_tpu.utils.metrics import METRICS

    from datafusion_tpu.obs import recorder
    from datafusion_tpu.obs.device import LEDGER

    fn()  # ensure warm before counting
    before = METRICS.snapshot()["counts"].get("device.launches", 0)
    flight_before = recorder.emitted()
    LEDGER.begin_peak_window()
    t0 = time.perf_counter()
    for _ in range(runs):
        fn()
    wall = (time.perf_counter() - t0) / runs
    after = METRICS.snapshot()["counts"].get("device.launches", 0)
    launches = max(0, after - before) / runs
    hbm = bytes_per_pass / max(wall, 1e-9) / 1e9
    return {
        "launches_per_pass": round(launches, 1),
        "hbm_gbps_achieved": round(hbm, 2),
        "hbm_util_pct": round(100 * hbm / _hbm_peak_gbps(), 2),
        "hbm_peak_bytes": LEDGER.window_peak_bytes(),
        # flight-recorder cost accounting: events emitted per warm pass
        # (each emit is ~1µs lock-free work — the ≤2% overhead budget
        # holds as long as this stays in the tens per millisecond-scale
        # query; see tests/test_telemetry.py::test_emit_overhead)
        "flight_events_per_pass": round(
            (recorder.emitted() - flight_before) / runs, 1
        ),
    }


def _phase_before() -> dict:
    """Stage-timer snapshot for the cold-path phase breakdown
    (obs/device.py): capture before the timed cold runs, feed to
    `_cold_phase_ms` after."""
    from datafusion_tpu.obs.device import phase_snapshot

    return phase_snapshot()


def _cold_profile(prof_cap) -> dict:
    """Per-phase top host frames from a cold leg's sampling-profiler
    capture (obs/profiler.py): `{phase: {"samples": n, "top_frames":
    [[label, count], ...]}}` — the BENCH-round record of WHERE the
    cold wall's host CPU went, beside `cold_phase_ms`'s how-much."""
    if prof_cap is None:
        return {}
    return prof_cap.report().by_phase(3)


def _cold_phase_ms(before: dict, total_wall_s: float, nruns: int) -> dict:
    """Per-run cold-phase milliseconds (decode/h2d/compile/execute/d2h/
    other) from the stage-timer deltas across `nruns` runs — the
    measured decomposition ROADMAP item 3's "cold >= 2x CPU" target is
    tuned against, recorded per BENCH config as `cold_phase_ms`.
    `total_wall_s` must be the MEASURED wall of the same runs the
    deltas cover (incl. any warmup run — its compile-heavy wall is far
    above p50, so approximating it as one p50 would overflow the
    accounted phases and zero "other")."""
    from datafusion_tpu.obs.device import phase_breakdown

    phases = phase_breakdown(before, total_wall_s)
    return {k: round(v * 1e3 / nruns, 2) for k, v in phases.items()}


def _warm_query(device, src, table, sql, rows, runs=WARM_RUNS, warmup=None):
    """Steady-state p50 of re-running one operator tree (device-resident
    inputs after warm-up).  The CPU baseline gets fewer runs (it is the
    yardstick, not the metric — and the single-core path is slow)."""
    from datafusion_tpu.exec.context import ExecutionContext
    from datafusion_tpu.exec.materialize import collect

    if device == "cpu":
        runs = min(3, runs)
        warmup = 1 if warmup is None else warmup
    ctx = ExecutionContext(device=device)
    ctx.register_datasource(table, src)
    rel = ctx.sql(sql)
    p50, out = _timed(lambda: collect(rel), runs, warmup if warmup is not None else WARMUP)
    log(f"    {device or 'default'} warm: p50 {p50*1e3:.1f} ms, {rows/p50/1e6:.2f} M rows/s")
    return p50, out


# -- config 1: CSV scan + projection + filter (examples/csv_sql.rs) --
def config1_csv_filter(device_kind: str):
    from datafusion_tpu.datatypes import DataType, Field, Schema
    from datafusion_tpu.exec.context import ExecutionContext
    from datafusion_tpu.exec.materialize import collect
    from datafusion_tpu.utils.metrics import METRICS

    rows = int(os.environ.get("BENCH_CSV_ROWS", 2_000_000))
    path = bdata.cities_csv(rows)
    schema = Schema(
        [
            Field("city", DataType.UTF8, False),
            Field("lat", DataType.FLOAT64, False),
            Field("lng", DataType.FLOAT64, False),
        ]
    )
    sql = "SELECT city, lat, lng, lat + lng FROM cities WHERE lat > 51.0 AND lat < 53.0"

    def cold(device):
        # 512k-row batches: per-batch link latency dominates on the
        # tunneled device, so fewer, larger batches
        ctx = ExecutionContext(device=device, batch_size=1 << 19)
        ctx.register_csv("cities", path, schema, has_header=True)
        return collect(ctx.sql(sql))

    log("  config 1: CSV scan+filter (cold, scan-inclusive)")
    cpu_p50, cpu_out = _timed(lambda: cold("cpu"), COLD_RUNS, warmup=1)
    log(f"    cpu cold: p50 {cpu_p50*1e3:.1f} ms, {rows/cpu_p50/1e6:.2f} M rows/s")
    if device_kind == "cpu":
        dev_p50, dev_out = cpu_p50, cpu_out
        cold_phase_ms, hbm_peak, cold_profile = {}, 0, {}
    else:
        from datafusion_tpu.obs import profiler as _profiler
        from datafusion_tpu.obs.device import LEDGER, profile_sync

        METRICS.reset()
        pb = _phase_before()
        LEDGER.begin_peak_window()
        t0 = time.perf_counter()
        # profile_sync: launches block so the "execute" phase measures
        # device wall, not async dispatch (obs/device.py); the host
        # profiler samples the same runs for per-phase top frames
        with profile_sync(), _profiler.profile(name="bench.cold1") as pc:
            dev_p50, dev_out = _timed(lambda: cold(device_kind), COLD_RUNS, warmup=1)
        # warmup=1: the warm-up run's stage timers are in the deltas,
        # so the wall fed to the breakdown is the measured total
        cold_phase_ms = _cold_phase_ms(
            pb, time.perf_counter() - t0, COLD_RUNS + 1
        )
        cold_profile = _cold_profile(pc)
        hbm_peak = LEDGER.window_peak_bytes()
        snap = METRICS.snapshot()
        parse = snap["timings_s"].get("scan.parse", 0.0) / (COLD_RUNS + 1)
        log(
            f"    {device_kind} cold: p50 {dev_p50*1e3:.1f} ms, "
            f"{rows/dev_p50/1e6:.2f} M rows/s (parse {parse*1e3:.0f} ms/run)"
            f"  phases={cold_phase_ms}"
        )
        _assert_tables_match(dev_out, cpu_out, "config1")
    return {
        "name": "csv_scan_filter",
        "rows": rows,
        "value": round(rows / dev_p50, 1),
        "unit": "rows/s",
        "p50_ms": round(dev_p50 * 1e3, 2),
        "vs_baseline": round(cpu_p50 / dev_p50, 3),
        "cold_phase_ms": cold_phase_ms,
        "cold_profile": cold_profile,
        "hbm_peak_bytes": hbm_peak,
        "out_rows": dev_out.num_rows,
    }


# -- config 2: GROUP BY hash-aggregate, low and high cardinality --
def config2_groupby(device_kind: str):
    rows = int(os.environ.get("BENCH_GROUPBY_ROWS", 4_000_000))
    out = {"name": "groupby_aggregate", "rows": rows, "unit": "rows/s"}
    sql = (
        "SELECT k, SUM(v1), AVG(v2), MIN(v3), MAX(v3), COUNT(1) "
        "FROM t GROUP BY k"
    )
    for label, groups in (("small_16", 16), ("high_100k", 100_000)):
        log(f"  config 2: GROUP BY {groups} groups (warm)")
        _, src = bdata.groupby_batches(rows, groups, 1 << 19)
        cpu_p50, cpu_out = _warm_query("cpu", src, "t", sql, rows)
        if device_kind == "cpu":
            dev_p50 = cpu_p50
        else:
            dev_p50, dev_out = _warm_query(device_kind, src, "t", sql, rows)
            _assert_tables_match(dev_out, cpu_out, f"config2/{label}", rtol=1e-6)
        out[label] = {
            "groups": groups,
            "value": round(rows / dev_p50, 1),
            "p50_ms": round(dev_p50 * 1e3, 2),
            "vs_baseline": round(cpu_p50 / dev_p50, 3),
        }
        if device_kind != "cpu":
            # fused-pass acceptance metrics: measured launch count and
            # achieved HBM for the warm aggregate pass (3 f64 value
            # columns + int64 key read once, plus ids + mask)
            from datafusion_tpu.exec.context import ExecutionContext
            from datafusion_tpu.exec.materialize import collect as _collect

            mctx = ExecutionContext(device=device_kind)
            mctx.register_datasource("t", src)
            mrel = mctx.sql(sql)
            out[label].update(_pass_metrics(
                lambda: _collect(mrel), rows * (3 * 8 + 8 + 4 + 1)
            ))
    out["value"] = out["high_100k"]["value"]
    out["vs_baseline"] = out["high_100k"]["vs_baseline"]
    return out


# -- config 3: TPC-H Q1 over Parquet lineitem (the headline) --
def config3_tpch_q1(device_kind: str, sf=None):
    from datafusion_tpu.exec.context import ExecutionContext
    from datafusion_tpu.exec.datasource import MemoryDataSource
    from datafusion_tpu.exec.materialize import collect
    from datafusion_tpu.utils.metrics import METRICS

    if sf is None:
        sf = float(os.environ.get("BENCH_SF", 1))
    sf = int(sf) if sf == int(sf) else sf
    log(f"  config 3: TPC-H Q1, Parquet lineitem SF-{sf}")
    path = bdata.lineitem_parquet(sf)
    rows = int(bdata.LINEITEM_ROWS_PER_SF * sf)

    def cold(device):
        # 512k-row batches: fewer, larger dispatches amortize per-batch
        # link overhead (same setting for the CPU baseline)
        ctx = ExecutionContext(device=device, batch_size=1 << 19)
        ctx.register_parquet("lineitem", path)
        return collect(ctx.sql(Q1))

    # cold: full scan -> encode -> H2D -> kernel each run
    cold("cpu")  # compile CPU kernels outside the timed region
    cpu_cold_p50, cpu_out = _timed(lambda: cold("cpu"), COLD_RUNS, warmup=0)
    log(f"    cpu cold: p50 {cpu_cold_p50*1e3:.0f} ms, {rows/cpu_cold_p50/1e6:.2f} M rows/s")
    if device_kind != "cpu":
        from datafusion_tpu.obs.device import LEDGER, profile_sync
        from datafusion_tpu.obs.device import enabled as device_ledger_enabled

        from datafusion_tpu.obs import profiler as _profiler

        cold(device_kind)  # compile device kernels
        METRICS.reset()
        pb = _phase_before()
        LEDGER.begin_peak_window()
        t0 = time.perf_counter()
        with profile_sync(), _profiler.profile(name="bench.cold3") as pc:
            dev_cold_p50, dev_out = _timed(lambda: cold(device_kind), COLD_RUNS, warmup=0)
        cold_phase_ms = _cold_phase_ms(
            pb, time.perf_counter() - t0, COLD_RUNS
        )
        cold_profile = _cold_profile(pc)
        hbm_peak = LEDGER.window_peak_bytes()
        snap = METRICS.snapshot()
        nruns = COLD_RUNS
        parse_encode = (
            snap["timings_s"].get("scan.parse", 0.0)
            + snap["timings_s"].get("h2d.encode", 0.0)
        )
        breakdown = {
            "parse_encode_s": round(parse_encode / nruns, 3),
            "h2d_mb": round(snap["counts"].get("h2d.bytes", 0) / nruns / 1e6, 1),
        }
        if device_ledger_enabled():
            # the h2d.dispatch timer accrues at the ledger seam; with
            # the ledger off it reads 0 and device_and_d2h_s would
            # silently absorb transfer time — omit both rather than
            # misattribute
            h2d = snap["timings_s"].get("h2d.dispatch", 0.0)
            breakdown["h2d_dispatch_s"] = round(h2d / nruns, 3)
            breakdown["device_and_d2h_s"] = round(
                max(dev_cold_p50 - (parse_encode + h2d) / nruns, 0.0), 3
            )
        log(f"    {device_kind} cold: p50 {dev_cold_p50*1e3:.0f} ms, "
            f"{rows/dev_cold_p50/1e6:.2f} M rows/s  breakdown={breakdown}  "
            f"phases={cold_phase_ms}")
        _assert_tables_match(dev_out, cpu_out, "config3 cold")
    else:
        dev_cold_p50 = cpu_cold_p50
        breakdown = {}
        cold_phase_ms, hbm_peak, cold_profile = {}, 0, {}

    # warm: the same rows resident in memory (and after warm-up, on
    # device) — steady-state re-query throughput
    q1_batch = int(os.environ.get("BENCH_Q1_BATCH", str(1 << 19)))
    ctx = ExecutionContext(device="cpu", batch_size=q1_batch)
    ctx.register_parquet("lineitem", path)
    scan_src = ctx.datasources["lineitem"]
    batches = list(scan_src.batches())
    mem_src = MemoryDataSource(scan_src.schema, batches)
    cpu_warm_p50, cpu_warm_out = _warm_query("cpu", mem_src, "lineitem", Q1, rows)
    utilization = {}
    if device_kind != "cpu":
        dev_warm_p50, dev_warm_out = _warm_query(device_kind, mem_src, "lineitem", Q1, rows)
        _assert_tables_match(dev_warm_out, cpu_warm_out, "config3 warm")
        utilization = _q1_device_utilization(
            device_kind, mem_src, rows, batch_size=q1_batch
        )
        log(f"    utilization: {utilization}")
    else:
        dev_warm_p50 = cpu_warm_p50

    return {
        "name": "tpch_q1_parquet" if sf == 1 else f"tpch_q1_parquet_sf{sf}",
        "sf": sf,
        "rows": rows,
        "unit": "rows/s",
        "value": round(rows / dev_warm_p50, 1),
        "warm_p50_ms": round(dev_warm_p50 * 1e3, 2),
        "vs_baseline": round(cpu_warm_p50 / dev_warm_p50, 3),
        "cold_value": round(rows / dev_cold_p50, 1),
        "cold_p50_ms": round(dev_cold_p50 * 1e3, 2),
        "cold_vs_baseline": round(cpu_cold_p50 / dev_cold_p50, 3),
        "cold_breakdown": breakdown,
        "cold_phase_ms": cold_phase_ms,
        "cold_profile": cold_profile,
        "hbm_peak_bytes": hbm_peak,
        "utilization": utilization,
    }


def _q1_device_utilization(device_kind: str, mem_src, rows: int,
                           batch_size: "int | None" = None) -> dict:
    """Device-side throughput and bandwidth utilization for the warm Q1
    kernel, separated from the session's synchronization floor.

    On the tunneled device every host<->device synchronization costs a
    fixed ~100 ms once any D2H has occurred in the process (launches
    pipeline; syncs do not), so the measured warm p50 is
    sync-floor-bound.  This measures (a) the floor itself (a trivial
    launch+block), and (b) N accumulate passes dispatched back-to-back
    with ONE final block — the device-only rate with the floor
    amortized — then converts bytes-touched into achieved HBM
    bandwidth against the chip peak (v5e ~819 GB/s).
    """
    import time as _t

    import jax
    import jax.numpy as jnp

    from datafusion_tpu.exec.context import ExecutionContext

    if batch_size is None:
        # derive from the source's ACTUAL batch geometry rather than a
        # literal: the launch correction multiplies launches/pass, and
        # launches/pass follows the batch count — a utilization context
        # batched differently from the measured config would correct
        # with the wrong launch count (this feeds BASELINE.md claims)
        sizes = [b.num_rows for b in mem_src.batches()]
        batch_size = max(sizes) if sizes else 1 << 19
    ctx = ExecutionContext(device=device_kind, batch_size=batch_size)
    ctx.register_datasource("lineitem", mem_src)
    rel = ctx.sql(Q1)
    for _ in range(2):
        jax.block_until_ready(rel.accumulate())

    tiny = jnp.ones((8,))
    trivial = jax.jit(lambda x: x + 1)
    jax.block_until_ready(trivial(tiny))
    floors = []
    for _ in range(5):
        t0 = _t.perf_counter()
        jax.block_until_ready(trivial(tiny))
        floors.append(_t.perf_counter() - t0)
    sync_floor = float(np.median(floors))

    # per-launch overhead: N trivial launches chained + one block, with
    # the single-launch sync floor subtracted — through a tunneled
    # transport this floor (~10-15 ms/launch), not HBM, usually bounds
    # the observable device-only rate
    n_triv = 20
    t0 = _t.perf_counter()
    y = tiny
    for _ in range(n_triv):
        y = trivial(y)
    jax.block_until_ready(y)
    launch_floor = max(
        (_t.perf_counter() - t0 - sync_floor) / n_triv, 0.0
    )

    from datafusion_tpu.utils.metrics import METRICS

    n_passes = 5
    launches_before = METRICS.snapshot()["counts"].get("device.launches", 0)
    t0 = _t.perf_counter()
    states = [rel.accumulate() for _ in range(n_passes)]
    jax.block_until_ready(states)
    total = _t.perf_counter() - t0
    launches_after = METRICS.snapshot()["counts"].get("device.launches", 0)
    device_time = max(total - sync_floor, 1e-9)
    dev_rows_s = n_passes * rows / device_time

    # traffic lower bound: every input column read once per pass —
    # 4 f64 value columns (quantity, extendedprice, discount, tax; the
    # derived slots compute on-device from these), 2 narrow key-code
    # columns, dense int32 ids, 1-byte mask
    bytes_per_pass = rows * (4 * 8 + 2 * 4 + 4 + 1)
    hbm_gbps = n_passes * bytes_per_pass / device_time / 1e9
    peaks = {"tpu": 819.0, "v5e": 819.0, "v4": 1228.0, "v6e": 1640.0}
    dev0 = jax.devices()[0]
    kind = getattr(dev0, "device_kind", "").lower()
    peak_gbps = next(
        (v for k, v in peaks.items() if k != "tpu" and k in kind),
        peaks["tpu"],
    )
    peak_gbps = float(os.environ.get("BENCH_HBM_PEAK_GBPS", peak_gbps))
    # launch-corrected compute: the per-pass time minus the transport's
    # per-launch overhead x launches/pass.  On a direct-attached chip
    # launch_floor ~ 0 and the two HBM numbers coincide; through a
    # tunnel the corrected number is the chip-side bound the transport
    # lets us observe.
    # measured launches, not a formula: the engine counts every
    # executable dispatch (`device.launches` in utils/retry.device_call)
    # — under fused passes a warm Q1 pass is 1-2 launches regardless of
    # batch count, and BASELINE.md claims must reflect what ran
    launches_per_pass = max(
        1, round((launches_after - launches_before) / n_passes)
    )
    compute_per_pass = max(
        device_time / n_passes - launches_per_pass * launch_floor, 1e-9
    )
    hbm_corrected = bytes_per_pass / compute_per_pass / 1e9
    return {
        "sync_floor_ms": round(sync_floor * 1e3, 1),
        "launch_floor_ms": round(launch_floor * 1e3, 2),
        "launches_per_pass": launches_per_pass,
        "device_rows_per_s": round(dev_rows_s, 1),
        "device_time_per_pass_ms": round(device_time / n_passes * 1e3, 2),
        "hbm_gbps_achieved": round(hbm_gbps, 1),
        "hbm_gbps_launch_corrected": round(hbm_corrected, 1),
        "hbm_peak_gbps": peak_gbps,
        "hbm_util_pct": round(100 * hbm_gbps / peak_gbps, 2),
        "hbm_util_pct_launch_corrected": round(
            100 * hbm_corrected / peak_gbps, 2
        ),
    }


# -- config 4: ORDER BY + LIMIT TopK on device --
def config4_sort_topk(device_kind: str):
    rows = int(os.environ.get("BENCH_SORT_ROWS", 4_000_000))
    log("  config 4: single-key TopK via lax.top_k (warm)")
    _, src = bdata.sort_batches(rows, 1 << 19)
    sql = "SELECT s, b, x FROM t ORDER BY s DESC LIMIT 100"
    cpu_p50, cpu_out = _warm_query("cpu", src, "t", sql, rows)
    if device_kind == "cpu":
        dev_p50 = cpu_p50
    else:
        dev_p50, dev_out = _warm_query(device_kind, src, "t", sql, rows)
        _assert_tables_match(dev_out, cpu_out, "config4 topk", rtol=1e-12)

    # float64 / int64 keys — the default SQL numeric types — ride the
    # wide full-width-score top_k path
    singles = {}
    for label, ssql in (
        ("single_f64", "SELECT a, b, x FROM t ORDER BY a DESC LIMIT 100"),
        ("single_i64", "SELECT b, a, x FROM t ORDER BY b LIMIT 100"),
    ):
        log(f"  config 4 {label}: wide-path TopK (warm)")
        scpu_p50, scpu_out = _warm_query("cpu", src, "t", ssql, rows)
        if device_kind == "cpu":
            sdev_p50 = scpu_p50
        else:
            sdev_p50, sdev_out = _warm_query(device_kind, src, "t", ssql, rows)
            _assert_tables_match(sdev_out, scpu_out, f"config4 {label}", rtol=1e-12)
        singles[label] = {
            "value": round(rows / sdev_p50, 1),
            "p50_ms": round(sdev_p50 * 1e3, 2),
            "vs_baseline": round(scpu_p50 / sdev_p50, 3),
        }

    log("  config 4m: multi-key TopK (sort kernel, warm)")
    msql = "SELECT a, b, x FROM t ORDER BY a DESC, b LIMIT 100"
    mcpu_p50, mcpu_out = _warm_query("cpu", src, "t", msql, rows)
    if device_kind == "cpu":
        mdev_p50 = mcpu_p50
    else:
        mdev_p50, mdev_out = _warm_query(device_kind, src, "t", msql, rows)
        _assert_tables_match(mdev_out, mcpu_out, "config4 multikey", rtol=1e-12)

    full_rows = int(os.environ.get("BENCH_FULLSORT_ROWS", 1_000_000))
    log("  config 4b: full ORDER BY (warm)")
    _, fsrc = bdata.sort_batches(full_rows, 1 << 19)
    fsql = "SELECT a, b, x FROM t ORDER BY a, b"
    fcpu_p50, fcpu_out = _warm_query("cpu", fsrc, "t", fsql, full_rows, runs=5)
    full_metrics = {}
    if device_kind == "cpu":
        fdev_p50 = fcpu_p50
    else:
        fdev_p50, fdev_out = _warm_query(device_kind, fsrc, "t", fsql, full_rows, runs=5)
        _assert_tables_match(fdev_out, fcpu_out, "config4 fullsort")
        # fused-pass acceptance metrics for the warm full sort (2 key
        # operands read + the permutation's byte planes written)
        from datafusion_tpu.exec.context import ExecutionContext
        from datafusion_tpu.exec.materialize import collect as _collect

        fctx = ExecutionContext(device=device_kind)
        fctx.register_datasource("t", fsrc)
        frel = fctx.sql(fsql)
        full_metrics = _pass_metrics(
            lambda: _collect(frel), full_rows * (2 * 8 + 3)
        )
    return {
        "name": "sort_topk",
        "rows": rows,
        "unit": "rows/s",
        "value": round(rows / dev_p50, 1),
        "p50_ms": round(dev_p50 * 1e3, 2),
        "vs_baseline": round(cpu_p50 / dev_p50, 3),
        **singles,
        "multi_key": {
            "value": round(rows / mdev_p50, 1),
            "p50_ms": round(mdev_p50 * 1e3, 2),
            "vs_baseline": round(mcpu_p50 / mdev_p50, 3),
        },
        "full_sort": {
            "rows": full_rows,
            "value": round(full_rows / fdev_p50, 1),
            "p50_ms": round(fdev_p50 * 1e3, 2),
            "vs_baseline": round(fcpu_p50 / fdev_p50, 3),
            **full_metrics,
        },
    }


# -- cache config: warm-repeat phase (result cache hit rate + speedup) --
def config_cache(device_kind: str):
    """Cold-vs-warm repeat of one query through the full SQL front end:
    the cold leg executes (and fills the result cache), the warm legs
    re-submit the identical SQL and must be served from the coordinator
    result cache (parse+plan+fingerprint+replay, no device work).
    Reports the hit rate and the warm/cold speedup."""
    from datafusion_tpu import cache as qcache
    from datafusion_tpu.cache.result import CachedResultRelation
    from datafusion_tpu.exec.context import ExecutionContext
    from datafusion_tpu.exec.materialize import collect

    rows = int(os.environ.get("BENCH_CACHE_ROWS", 2_000_000))
    groups = 10_000
    sql = (
        "SELECT k, SUM(v1), AVG(v2), MIN(v3), MAX(v3), COUNT(1) "
        "FROM t GROUP BY k"
    )
    log("  config cache: warm-repeat result cache")
    _, src = bdata.groupby_batches(rows, groups, 1 << 19)
    device = None if device_kind == "cpu" else device_kind
    with qcache.configured(enabled=True):
        ctx = ExecutionContext(device="cpu" if device is None else device)
        ctx.register_datasource("t", src)

        def run():
            return collect(ctx.sql(sql))

        run()  # compile + warm device state outside the cold timing
        ctx.result_cache.clear()
        t0 = time.perf_counter()
        cold_out = run()
        cold_s = time.perf_counter() - t0
        rel = ctx.sql(sql)
        assert isinstance(rel, CachedResultRelation), (
            "warm repeat was not served from the result cache"
        )
        warm_runs = max(WARM_RUNS, 5)
        times = []
        for _ in range(warm_runs):
            t0 = time.perf_counter()
            warm_out = collect(ctx.sql(sql))
            times.append(time.perf_counter() - t0)
        warm_s = _p50(times)
        _assert_tables_match(warm_out, cold_out, "config cache", rtol=1e-9)
        stats = ctx.result_cache.stats()
        # the per-context run history must have recorded every warm
        # repeat as a cache hit under the query's fingerprint (closes
        # the open BASELINE.md note from the observability/cache PRs)
        runs = ctx.stats_history(ctx.last_fingerprint)
        warm_hits = [r for r in runs if r.get("cache_hit")]
        assert len(warm_hits) >= warm_runs, (
            f"stats_history recorded {len(warm_hits)} warm hits for "
            f"{warm_runs} warm runs: {runs!r}"
        )
    hit_rate = stats["hits"] / max(stats["hits"] + stats["misses"], 1)
    log(
        f"    cold {cold_s * 1e3:.1f} ms -> warm p50 {warm_s * 1e3:.2f} ms "
        f"({cold_s / warm_s:.0f}x), hit rate {hit_rate:.2f}, "
        f"{stats['bytes']} cached bytes, "
        f"{len(warm_hits)}/{len(runs)} history runs cache-hit"
    )
    return {
        "name": "result_cache_warm_repeat",
        "rows": rows,
        "unit": "rows/s",
        "value": round(rows / warm_s, 1),
        "warm_p50_ms": round(warm_s * 1e3, 3),
        "cold_ms": round(cold_s * 1e3, 2),
        "warm_speedup": round(cold_s / warm_s, 1),
        "hit_rate": round(hit_rate, 4),
        "cached_bytes": stats["bytes"],
        "history_warm_hits": len(warm_hits),
        "vs_baseline": round(cold_s / warm_s, 3),
    }


def config_ingest(device_kind: str):
    """Streaming ingestion vs full rescan: the TPC-H Q1 materialized
    view maintained incrementally (datafusion_tpu/ingest) against
    recomputing it from scratch after every delta.

    Closed loop: `deltas` appends of `delta_rows` lineitem rows each.
    Per delta the timed legs are (a) the append — WAL-free, so the
    number is pure maintenance: delta encode + ONE fused monoid fold
    into the view's device accumulators — and (b) a full rescan of
    the defining query over the grown table.  At EVERY cut the view
    must be bit-identical to the rescan (untimed), each delta must
    cost exactly one counted maintenance launch, and the headline
    gate is maintenance >= 5x cheaper than the rescan.  `value` is
    the sustained ingest rate (rows/s through append+maintain);
    freshness is the p50 append latency — the view is synchronously
    fresh when append returns."""
    from datafusion_tpu.exec.context import ExecutionContext

    sf = float(os.environ.get("BENCH_INGEST_SF", 0.1))
    sf = int(sf) if sf == int(sf) else sf
    deltas = int(os.environ.get("BENCH_INGEST_DELTAS", 15))
    delta_rows = int(os.environ.get("BENCH_INGEST_DELTA_ROWS", 2000))
    log(f"  config ingest: Q1 view maintenance over lineitem SF-{sf}, "
        f"{deltas} deltas x {delta_rows} rows")
    path = bdata.lineitem_parquet(sf)
    base_rows = int(bdata.LINEITEM_ROWS_PER_SF * sf)
    device = None if device_kind == "cpu" else device_kind
    ctx = ExecutionContext(device="cpu" if device is None else device,
                           batch_size=1 << 19, result_cache=False)
    ctx.register_parquet("lineitem", path)
    ing = ctx.ingest()
    view = ing.create_view("q1", Q1)
    assert view.incremental, (
        f"Q1 view fell back to full recompute: {view.fallback_reason}")

    rng = np.random.default_rng(17)
    flags, statuses = ["A", "N", "R"], ["F", "O"]

    def make_delta():
        return {
            "l_returnflag": [flags[i] for i in
                             rng.integers(0, 3, delta_rows)],
            "l_linestatus": [statuses[i] for i in
                             rng.integers(0, 2, delta_rows)],
            "l_quantity": rng.uniform(1, 50, delta_rows).round(2),
            "l_extendedprice": rng.uniform(900, 105000,
                                           delta_rows).round(2),
            "l_discount": rng.uniform(0, 0.1, delta_rows).round(2),
            "l_tax": rng.uniform(0, 0.08, delta_rows).round(2),
            "l_shipdate": ["1995-06-15"] * delta_rows,
        }

    # warm both legs' compiles outside the timed loop (the warmup
    # delta stays in the stream — it is real data, just untimed)
    ing.append("lineitem", make_delta())
    ctx.sql_collect(Q1)
    launches0 = view.maintain_launches
    append_times, rescan_times = [], []
    for i in range(deltas):
        cols = make_delta()
        t0 = time.perf_counter()
        ing.append("lineitem", cols)
        append_times.append(time.perf_counter() - t0)
        got = sorted(ing.read_view("q1").to_rows())
        t0 = time.perf_counter()
        want = ctx.sql_collect(Q1)
        rescan_times.append(time.perf_counter() - t0)
        assert got == sorted(want.to_rows()), (
            f"view diverged from batch rescan at delta {i}")
    assert view.maintain_launches - launches0 == deltas, (
        f"{view.maintain_launches - launches0} maintenance launches "
        f"for {deltas} deltas — must be exactly one fused launch each")
    assert view.full_recomputes == 0
    append_p50, rescan_p50 = _p50(append_times), _p50(rescan_times)
    speedup = rescan_p50 / append_p50
    assert speedup >= 5.0, (
        f"incremental maintenance only {speedup:.1f}x cheaper than a "
        f"full rescan (append p50 {append_p50 * 1e3:.2f} ms vs rescan "
        f"p50 {rescan_p50 * 1e3:.1f} ms)")
    total_rows = base_rows + (deltas + 1) * delta_rows
    log(f"    append+maintain p50 {append_p50 * 1e3:.2f} ms "
        f"({delta_rows / append_p50:,.0f} rows/s) vs full rescan p50 "
        f"{rescan_p50 * 1e3:.1f} ms over {total_rows:,} rows — "
        f"{speedup:.0f}x cheaper per delta, "
        f"{deltas} deltas = {deltas} fused launches")
    return {
        "name": "ingest_q1_view",
        "rows": total_rows,
        "unit": "rows/s",
        "value": round(delta_rows / append_p50, 1),
        "delta_rows": delta_rows,
        "deltas": deltas,
        "append_p50_ms": round(append_p50 * 1e3, 3),
        "freshness_p50_ms": round(append_p50 * 1e3, 3),
        "rescan_p50_ms": round(rescan_p50 * 1e3, 2),
        "speedup_vs_rescan": round(speedup, 1),
        "maintain_launches": deltas,
        "vs_baseline": round(speedup, 3),
    }


def config_concurrency(device_kind: str):
    """Throughput under concurrency: the serving front door vs
    serialized back-to-back execution of the SAME workload — the first
    config where queries/s, not single-query latency, is the number.

    Closed-loop: `clients` threads each submit `per_client` distinct-
    literal variants of one aggregate shape (one compiled core,
    result-cache-proof literals).  The serving leg pins the table in
    device memory, shares group-id encoders across queries, and fuses
    compatible concurrent plans into megabatched launches; reported
    p50/p99 come from the `serve.latency` fleet histogram (timed
    round only).

    On the CPU backend a per-launch latency floor is injected
    (`BENCH_SERVE_LAUNCH_FLOOR_MS`, default 10; =0 disables) — see
    `benchmarks/serve_load.launch_floor_plan`, the harness shared with
    `scripts/serve_smoke.py` so the two cannot drift.  BOTH legs run
    under the same floor; real accelerators run uninjected."""
    from benchmarks import serve_load
    from datafusion_tpu.exec.context import ExecutionContext
    from datafusion_tpu.exec.materialize import collect
    from datafusion_tpu.obs.aggregate import HISTOGRAMS
    from datafusion_tpu.testing import faults
    from datafusion_tpu.utils.metrics import METRICS

    rows = int(os.environ.get("BENCH_SERVE_ROWS", 32768))
    groups = int(os.environ.get("BENCH_SERVE_GROUPS", 64))
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 8))
    per_client = int(os.environ.get("BENCH_SERVE_QUERIES", 8))
    floor_ms = float(os.environ.get(
        "BENCH_SERVE_LAUNCH_FLOOR_MS",
        "10" if device_kind == "cpu" else "0",
    ))
    log(f"  config concurrency: {clients} clients x {per_client} "
        f"queries over {rows} rows, launch floor {floor_ms} ms")
    _, src = bdata.groupby_batches(rows, groups, 1 << 15)
    device = None if device_kind == "cpu" else device_kind

    def q(lit: float) -> str:
        return (f"SELECT k, SUM(v1), AVG(v2), COUNT(1) FROM t "
                f"WHERE v2 < {lit:.6f} GROUP BY k")

    lits = [0.1 + 0.8 * i / (clients * per_client)
            for i in range(clients * per_client)]

    # serialized baseline: the same workload back-to-back on one thread
    ctx = ExecutionContext(
        device="cpu" if device is None else device, result_cache=False
    )
    ctx.register_datasource("t", src)
    collect(ctx.sql(q(0.95)))  # compile outside the timing
    if floor_ms > 0:
        faults.install(serve_load.launch_floor_plan(floor_ms))
    try:
        t0 = time.perf_counter()
        serial_out = [collect(ctx.sql(q(lit))) for lit in lits]
        serial_s = time.perf_counter() - t0
    finally:
        faults.clear()
    qps_serial = len(lits) / serial_s

    # served: closed-loop clients against the front door on a FRESH
    # context (no shared device caches with the baseline leg).
    # Megabatch cap = client count: a full closed-loop round flushes
    # the window the moment every client's query is queued (the window
    # is the MAX wait, size triggers early dispatch).
    sctx = ExecutionContext(
        device="cpu" if device is None else device, result_cache=False
    )
    sctx.register_datasource("t", bdata.groupby_batches(
        rows, groups, 1 << 15)[1])
    srv = sctx.serve(workers=2, window_s=0.01, megabatch_max=clients)
    results: dict = {}
    errors: list = []
    try:
        srv.submit(q(0.95)).result(timeout=300)  # pin + compile
        # untimed warm-up: every megabatch rung + one closed-loop
        # round, so the timed round is deterministically compile-free
        # (warm steady state is the measurement, as in every config)
        serve_load.warm_rungs(srv, q, clients)
        serve_load.closed_loop(srv, q, clients, per_client,
                               lambda i: 0.95 + 0.0005 * i, {}, errors)
        assert not errors, f"warm-up failures: {errors[:3]}"
        # timed-phase baselines (AFTER warm-up, like the smoke's, so
        # the reported fusion count and launches/query cover the same
        # phase)
        warm_launches0 = METRICS.counts.get("device.launches", 0)
        mega0 = METRICS.counts.get("serve.megabatch_launches", 0)
        h_before = (HISTOGRAMS["serve.latency"].snapshot()
                    if "serve.latency" in HISTOGRAMS else None)
        # tail attribution: the timed round's per-segment critical-path
        # decomposition (obs/attribution.py) is part of the bench
        # record — a concurrency regression should name the segment
        # that grew (queue wait vs window vs launch share vs demux),
        # not just the headline q/s
        from datafusion_tpu.obs import attribution

        attribution.EXPLAINER.clear()
        meter0 = {cid: dict(c) for cid, c in
                  attribution.METER.snapshot().items()}
        dispatch0 = METRICS.timings.get("device.dispatch", 0.0)
        if floor_ms > 0:
            faults.install(serve_load.launch_floor_plan(floor_ms))
        try:
            served_s = serve_load.closed_loop(
                srv, q, clients, per_client, lambda i: lits[i],
                results, errors,
            )
        finally:
            faults.clear()
    finally:
        srv.stop()
    assert not errors, f"{len(errors)} served queries failed: {errors[:3]}"
    qps_served = len(lits) / served_s
    # correctness: every served answer matches its serialized twin
    for i, lit in enumerate(lits):
        _assert_tables_match(
            results[divmod(i, per_client)], serial_out[i],
            f"concurrency lit={lit}",
        )
    mega = METRICS.counts.get("serve.megabatch_launches", 0) - mega0
    launches_per_query = (
        METRICS.counts.get("device.launches", 0) - warm_launches0
    ) / len(lits)
    p50, p99 = serve_load.phase_quantiles(
        HISTOGRAMS.get("serve.latency"), h_before
    )
    # the timed round's tail decomposition + metering conservation:
    # per-segment p50/p99 contributions and the apportioned
    # device-seconds against the measured launch wall
    tail = attribution.EXPLAINER.explain()
    meter1 = attribution.METER.snapshot()
    dev_sum = sum(
        c.get("device_seconds", 0.0)
        - meter0.get(cid, {}).get("device_seconds", 0.0)
        for cid, c in meter1.items()
    )
    launch_wall = METRICS.timings.get("device.dispatch", 0.0) - dispatch0
    log(
        f"    serialized {qps_serial:.1f} q/s -> served "
        f"{qps_served:.1f} q/s ({qps_served / qps_serial:.2f}x), "
        f"{mega} megabatch launches, "
        f"{launches_per_query:.2f} launches/query, "
        f"p50 {p50} p99 {p99}, tail top {tail['top']}"
    )
    return {
        "name": "concurrency",
        "unit": "queries/s",
        "value": round(qps_served, 2),
        "qps_serialized": round(qps_serial, 2),
        "vs_baseline": round(qps_served / qps_serial, 3),
        "clients": clients,
        "queries": len(lits),
        "megabatch_launches": mega,
        "launches_per_query": round(launches_per_query, 3),
        "launch_floor_ms": floor_ms,
        "p50_s": p50,
        "p99_s": p99,
        "critical_path": {
            "top": tail["top"],
            "segments": {
                r["segment"]: {"p50_s": r["p50_s"], "p99_s": r["p99_s"],
                               "share_of_wall": r["share_of_wall"]}
                for r in tail["segments"]
            },
        },
        "metering": {
            "clients": sum(1 for cid in meter1 if cid.startswith("c")),
            "device_seconds_sum": round(dev_sum, 6),
            "launch_wall_s": round(launch_wall, 6),
        },
    }


# -- worker-on-the-chip smoke (part of the bench protocol) --
def config_worker_smoke(device_kind: str):
    """Coordinator -> TPU-worker parity smoke on the attached chip
    (scripts/tpu_worker_smoke.py; VERDICT r4 asked for this leg in the
    recorded bench run).  On CPU-only hosts it reports skipped."""
    import json
    import subprocess

    out = {"name": "tpu_worker_smoke", "value": 0, "unit": "s",
           "vs_baseline": 0.0}
    if device_kind == "cpu":
        out["skipped"] = "no accelerator attached"
        return out
    log("  worker smoke: coordinator -> worker-on-TPU fragment parity")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # a hung/crashed smoke must degrade to an error entry, never abort
    # the whole bench run (the other configs' results would be lost)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "scripts", "tpu_worker_smoke.py")],
            cwd=repo, capture_output=True, text=True, timeout=1200,
        )
        sys.stderr.write(proc.stderr[-2000:])
        if proc.returncode != 0:
            out["error"] = (proc.stdout + proc.stderr)[-500:]
            return out
        result = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 — TimeoutExpired, bad JSON, ...
        out["error"] = f"{type(e).__name__}: {e}"[:500]
        return out
    out.update(result)
    out["value"] = result.get("query_s", 0)
    out["vs_baseline"] = 1.0  # parity leg: pass/fail, not a speed ratio
    log(f"    pass: {result.get('rows')} rows, query {result.get('query_s')}s")
    return out


# -- config 5: partitioned aggregate over an 8-device mesh --
def config5_mesh(_device_kind: str):
    """Runs in a subprocess on a CPU-simulated 8-device mesh (one
    physical TPU chip is attached here; the mesh path is validated and
    timed on virtual devices, the same trick the tests use)."""
    import json
    import subprocess

    log("  config 5: partitioned mesh aggregate (8 virtual CPU devices)")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.mesh_bench"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=1200,
    )
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        raise RuntimeError(f"mesh bench failed:\n{proc.stdout}\n{proc.stderr}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


# -- config joins: TPC-H Q3/Q5/Q10/Q12 shapes over the join subsystem --
def config_joins(device_kind: str):
    """Multi-table TPC-H shapes through the hash-join operator, gated
    on bit-level parity against a pandas-merge oracle, plus a warm
    pinned-probe leg: once the (dense-int, unique-key) orders build is
    resident, repeat passes must launch ZERO build kernels and stay
    under a launches-per-pass ceiling derived from the probe batch
    count — the 'warm probes move no build work' serving contract."""
    import pandas as pd

    from datafusion_tpu import cache as qcache
    from datafusion_tpu.exec.context import ExecutionContext
    from datafusion_tpu.exec.datasource import CsvDataSource
    from datafusion_tpu.exec.materialize import collect
    from datafusion_tpu.utils.metrics import METRICS

    sf = float(os.environ.get("BENCH_JOIN_SF", 0.01))
    batch_rows = 1 << 14
    tables = bdata.tpch_join_csvs(sf)
    device = None if device_kind == "cpu" else device_kind
    frames = {}
    with qcache.configured(enabled=False):
        ctx = ExecutionContext(
            device="cpu" if device is None else device, batch_size=batch_rows
        )
        for name, (path, schema) in tables.items():
            ctx.register_datasource(
                name, CsvDataSource(path, schema, True, batch_rows))
            frames[name] = pd.read_csv(path)

        def rows_of(sql):
            def key(row):
                return tuple(
                    (v is None, 0 if v is None else v) for v in row)
            return sorted(collect(ctx.sql(sql)).to_rows(), key=key)

        def check(label, got, want_df):
            want = sorted(
                tuple(None if pd.isna(v) else v for v in t)
                for t in want_df.itertuples(index=False)
            )
            assert len(got) == len(want), (
                f"{label}: {len(got)} rows vs oracle {len(want)}")
            for g, w in zip(got, want):
                for gv, wv in zip(g, w):
                    if isinstance(gv, float) or isinstance(wv, float):
                        np.testing.assert_allclose(
                            gv, wv, rtol=1e-9, err_msg=label)
                    else:
                        assert gv == wv, f"{label}: {g} vs {w}"

        li, od, cu, na = (frames["lineitem"], frames["orders"],
                          frames["customer"], frames["nation"])
        li = li.assign(rev=li.l_extendedprice * (1 - li.l_discount))
        results = {}

        q3 = ("SELECT o_orderkey, o_shippriority, "
              "SUM(l_extendedprice * (1 - l_discount)) FROM lineitem "
              "JOIN orders ON lineitem.l_orderkey = orders.o_orderkey "
              "JOIN customer ON orders.o_custkey = customer.c_custkey "
              "WHERE c_mktsegment = 1 "
              "GROUP BY o_orderkey, o_shippriority")
        t, got = _timed(lambda: rows_of(q3), runs=3, warmup=1)
        o3 = (li.merge(od, left_on="l_orderkey", right_on="o_orderkey")
              .merge(cu, left_on="o_custkey", right_on="c_custkey"))
        o3 = (o3[o3.c_mktsegment == 1]
              .groupby(["o_orderkey", "o_shippriority"], as_index=False)
              .agg(rev=("rev", "sum")))
        check("q3", got, o3[["o_orderkey", "o_shippriority", "rev"]])
        results["q3_s"] = round(t, 4)
        log(f"    Q3 shape: {len(got)} groups, p50 {t * 1e3:.1f} ms")

        q5 = ("SELECT n_name, SUM(l_extendedprice * (1 - l_discount)) "
              "FROM lineitem "
              "JOIN orders ON lineitem.l_orderkey = orders.o_orderkey "
              "JOIN customer ON orders.o_custkey = customer.c_custkey "
              "JOIN nation ON customer.c_nationkey = nation.n_nationkey "
              "GROUP BY n_name")
        t, got = _timed(lambda: rows_of(q5), runs=3, warmup=1)
        o5 = (li.merge(od, left_on="l_orderkey", right_on="o_orderkey")
              .merge(cu, left_on="o_custkey", right_on="c_custkey")
              .merge(na, left_on="c_nationkey", right_on="n_nationkey")
              .groupby("n_name", as_index=False).agg(rev=("rev", "sum")))
        check("q5", got, o5[["n_name", "rev"]])
        results["q5_s"] = round(t, 4)
        log(f"    Q5 shape: {len(got)} nations, p50 {t * 1e3:.1f} ms")

        q10 = ("SELECT c_custkey, n_name, "
               "SUM(l_extendedprice * (1 - l_discount)) FROM lineitem "
               "JOIN orders ON lineitem.l_orderkey = orders.o_orderkey "
               "JOIN customer ON orders.o_custkey = customer.c_custkey "
               "JOIN nation ON customer.c_nationkey = nation.n_nationkey "
               "WHERE o_orderdate <= '1995-06-30' "
               "GROUP BY c_custkey, n_name")
        t, got = _timed(lambda: rows_of(q10), runs=3, warmup=1)
        o10 = (li.merge(od, left_on="l_orderkey", right_on="o_orderkey")
               .merge(cu, left_on="o_custkey", right_on="c_custkey")
               .merge(na, left_on="c_nationkey", right_on="n_nationkey"))
        o10 = (o10[o10.o_orderdate <= "1995-06-30"]
               .groupby(["c_custkey", "n_name"], as_index=False)
               .agg(rev=("rev", "sum")))
        check("q10", got, o10[["c_custkey", "n_name", "rev"]])
        results["q10_s"] = round(t, 4)
        log(f"    Q10 shape: {len(got)} groups, p50 {t * 1e3:.1f} ms")

        q12 = ("SELECT l_shipmode, COUNT(1) FROM lineitem "
               "JOIN orders ON lineitem.l_orderkey = orders.o_orderkey "
               "WHERE l_quantity > 25 GROUP BY l_shipmode")
        t, got = _timed(lambda: rows_of(q12), runs=3, warmup=1)
        o12 = (li.merge(od, left_on="l_orderkey", right_on="o_orderkey"))
        o12 = (o12[o12.l_quantity > 25]
               .groupby("l_shipmode", as_index=False)
               .agg(n=("l_orderkey", "count")))
        check("q12", [(a, int(b)) for a, b in got],
              o12[["l_shipmode", "n"]])
        results["q12_s"] = round(t, 4)
        log(f"    Q12 shape: {len(got)} shipmodes, p50 {t * 1e3:.1f} ms")

        # warm pinned-probe gate on Q12 (orders build: unique dense int
        # key -> device path, pinned after the timed passes above)
        n_line = len(li)
        n_batches = -(-n_line // batch_rows)
        before = METRICS.snapshot()["counts"]
        pm = _pass_metrics(lambda: rows_of(q12), bytes_per_pass=0.0)
        after = METRICS.snapshot()["counts"]
        build_launches = (after.get("device.launches.join.build", 0)
                          - before.get("device.launches.join.build", 0))
        assert build_launches == 0, (
            f"warm Q12 passes launched {build_launches} build kernels")
        reuse = (after.get("join.build.reuse", 0)
                 - before.get("join.build.reuse", 0))
        assert reuse >= 3, f"pinned build reused {reuse} times in 4 passes"
        # ceiling: scan decode + filter + fused probe + aggregate per
        # probe batch, plus a fixed epilogue allowance
        ceiling = 8 * n_batches + 32
        assert pm["launches_per_pass"] <= ceiling, (
            f"warm Q12 launches_per_pass {pm['launches_per_pass']} "
            f"exceeds ceiling {ceiling} ({n_batches} probe batches)")
        log(f"    warm Q12: {pm['launches_per_pass']} launches/pass "
            f"(ceiling {ceiling}), 0 build launches, reuse={reuse}")

    total_rows = sum(len(f) for f in frames.values())
    wall = results["q3_s"] + results["q5_s"] + results["q10_s"] + results["q12_s"]
    return {
        "name": "tpch_joins",
        "rows": total_rows,
        "unit": "rows/s",
        "value": round(total_rows * 4 / max(wall, 1e-9), 1),
        "launches_per_pass_warm_q12": pm["launches_per_pass"],
        "probe_batches": n_batches,
        "vs_baseline": 1.0,  # parity leg: pass/fail, not a speed ratio
        **results,
    }


def config_adaptive(device_kind: str):
    """Feedback-driven planning (datafusion_tpu/cost): the same
    workload cold (empty cost store) vs trained (statistics persisted
    by the cold leg, loaded by a fresh process).

    Each leg is a SUBPROCESS so it pays its own compiles — the whole
    point is that the trained leg's pre-sized aggregate compiles ONE
    sort-merge kernel where the cold leg climbs the capacity regrow
    ladder, and its join builds the smaller side.  Gates: at least two
    decision classes flip, results bit-exact across legs, and the
    mis-defaulted aggregate shape speeds up >= 1.2x."""
    import importlib.util
    import json as _json
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    smoke_path = os.path.join(repo, "scripts", "adaptive_smoke.py")
    spec = importlib.util.spec_from_file_location("_adaptive", smoke_path)
    smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(smoke)

    tmpdir = tempfile.mkdtemp(prefix="df-tpu-bench-adaptive-")
    smoke._write_tables(tmpdir)

    def leg(label, cost="1"):
        env = dict(os.environ)
        env["DATAFUSION_TPU_COST_DIR"] = tmpdir
        env["DATAFUSION_TPU_COST"] = cost
        env.setdefault("DATAFUSION_TPU_FUSE_GROUP", "8")
        out = subprocess.run(
            [sys.executable, smoke_path, "--leg", tmpdir],
            cwd=repo, env=env, capture_output=True, text=True,
            timeout=600,
        )
        assert out.returncode == 0, (
            f"adaptive {label} leg failed:\n{out.stderr[-4000:]}")
        r = _json.loads(out.stdout.strip().splitlines()[-1])
        log(f"    {label}: agg {r['agg_wall_s'] * 1e3:.0f} ms, "
            f"decisions {r['decisions'] or '[]'}")
        return r

    log("  config adaptive: cold vs trained planning")
    cold = leg("cold")
    trained = leg("trained")
    changed = sorted(set(trained["decisions"]) - set(cold["decisions"]))
    assert len(changed) >= 2, (
        f"expected >=2 decision classes to flip, got {changed}")
    assert trained["agg_rows"] == cold["agg_rows"], (
        "trained aggregate rows diverged from cold")
    assert trained["join_rows"] == cold["join_rows"], (
        "trained join rows diverged from cold")
    speedup = cold["agg_wall_s"] / max(trained["agg_wall_s"], 1e-9)
    assert speedup >= 1.2, (
        f"trained aggregate speedup {speedup:.2f}x below the 1.2x gate "
        f"(cold {cold['agg_wall_s']:.3f}s, "
        f"trained {trained['agg_wall_s']:.3f}s)")
    log(f"    trained speedup on the mis-defaulted aggregate: "
        f"{speedup:.2f}x, decisions flipped: {changed}")
    return {
        "name": "adaptive_planning",
        "rows": smoke.ROWS,
        "unit": "speedup",
        "value": round(speedup, 3),
        "cold_agg_ms": round(cold["agg_wall_s"] * 1e3, 1),
        "trained_agg_ms": round(trained["agg_wall_s"] * 1e3, 1),
        "decisions_changed": changed,
        "vs_baseline": round(speedup, 3),
    }
