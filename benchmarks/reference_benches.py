#!/usr/bin/env python
"""The reference's own bench list, revived.

`/root/reference/Cargo.toml:50-68` comments out five criterion bench
targets (`read_csv`, `filter_primitive`, `sql_bench`, `dataframe_bench`,
`udf_udt`) and Travis runs `cargo bench` with nothing to execute
(`.travis.yml:30-33`).  These are their working equivalents over the
same fixture (`test/data/uk_cities.csv`, the reference's example
input), micro-scale so they run anywhere in seconds:

    python -m benchmarks.reference_benches

Prints one JSON object with p50 micro-timings per target.  The macro
perf suite is bench.py (the five BASELINE.md configs).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _p50(fn, runs=20, warmup=3):
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return round(float(np.median(times)) * 1e3, 3)


def main():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    from datafusion_tpu import DataType, ExecutionContext, Field, Schema, lit

    data = os.path.join(repo, "test", "data", "uk_cities.csv")
    schema = Schema(
        [
            Field("city", DataType.UTF8, False),
            Field("lat", DataType.FLOAT64, False),
            Field("lng", DataType.FLOAT64, False),
        ]
    )

    def fresh_ctx():
        ctx = ExecutionContext()
        ctx.register_csv("cities", data, schema, has_header=False)
        return ctx

    results = {}

    # read_csv: scan + parse the fixture end to end
    ctx = fresh_ctx()
    results["read_csv_ms"] = _p50(
        lambda: ctx.sql_collect("SELECT city, lat, lng FROM cities")
    )

    # filter_primitive: Float64 comparison filter (the reference's
    # filter.rs could only gather Float64/Utf8)
    results["filter_primitive_ms"] = _p50(
        lambda: ctx.sql_collect("SELECT lat FROM cities WHERE lat > 52.0")
    )

    # sql_bench: the full csv_sql.rs statement, parse-to-rows
    results["sql_ms"] = _p50(
        lambda: ctx.sql_collect(
            "SELECT city, lat, lng, lat + lng FROM cities "
            "WHERE lat > 51.0 AND lat < 53"
        )
    )

    # dataframe_bench: the same query through the DataFrame API
    cities = ctx.table("cities")
    lat, lng = cities["lat"], cities["lng"]
    df = (
        cities.filter(lat.gt(lit(51.0)).and_(lat.lt(lit(53.0))))
        .select("city", lat, lng, lat + lng)
    )
    results["dataframe_ms"] = _p50(lambda: df.collect())

    # udf_udt: scalar UDF + struct-producing UDT (the console geo fns)
    from datafusion_tpu.cli import make_context

    geo = make_context()
    geo.register_csv("cities", data, schema, has_header=False)
    results["udf_udt_ms"] = _p50(
        lambda: geo.sql_collect(
            "SELECT ST_AsText(ST_Point(lat, lng)) FROM cities WHERE lat < 53"
        )
    )

    print(json.dumps(results))


if __name__ == "__main__":
    main()
