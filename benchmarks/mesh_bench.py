"""Config 5: partitioned GROUP BY aggregate over an 8-device mesh.

Run with JAX_PLATFORMS=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=8 (suite.py sets
both); compares the shard_map partial-aggregate + psum-combine path
against the same query on one device, on identical in-memory
partitions.  Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main():
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax

    # honor JAX_PLATFORMS even where sitecustomize re-registers an
    # accelerator backend at boot (same re-pin as tests/conftest.py) —
    # without this the "CPU mesh" silently lands on the TPU AOT
    # compiler, which rejects pmin/pmax collectives
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        jax.config.update("jax_platforms", platforms)

    from benchmarks import data as bdata
    from datafusion_tpu.exec.context import ExecutionContext
    from datafusion_tpu.exec.materialize import collect
    from datafusion_tpu.parallel.partition import (
        PartitionedContext,
        PartitionedDataSource,
    )

    n_dev = len(jax.devices())
    rows = int(os.environ.get("BENCH_MESH_ROWS", 4_000_000))
    groups = int(os.environ.get("BENCH_MESH_GROUPS", 1000))
    per_part = rows // n_dev
    parts = []
    schema = None
    for i in range(n_dev):
        # distinct seed per partition: 8 copies of the same rows would
        # benchmark a degenerate input
        schema, src = bdata.groupby_batches(per_part, groups, 1 << 18, seed=100 + i)
        parts.append(src)
    pds = PartitionedDataSource(parts)
    sql = "SELECT k, SUM(v1), AVG(v2), MIN(v3), MAX(v3), COUNT(1) FROM t GROUP BY k"

    def timed(fn, runs=5, warmup=2):
        out = None
        for _ in range(warmup):
            out = fn()
        times = []
        for _ in range(runs):
            t0 = time.perf_counter()
            out = fn()
            times.append(time.perf_counter() - t0)
        return float(np.median(times)), out

    # single device (partitions scanned as a serial union)
    ctx1 = ExecutionContext(device="cpu")
    ctx1.register_datasource("t", pds)
    rel1 = ctx1.sql(sql)
    p50_1, out1 = timed(lambda: collect(rel1))

    # 8-device mesh: shard_map partial aggregates + psum combine
    ctxm = PartitionedContext(n_devices=n_dev)
    ctxm.register_datasource("t", pds)
    relm = ctxm.sql(sql)
    p50_m, outm = timed(lambda: collect(relm))

    got = sorted(outm.to_rows())
    want = sorted(out1.to_rows())
    assert len(got) == len(want), f"{len(got)} vs {len(want)} groups"
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g, float), np.asarray(w, float), rtol=1e-9
        )

    # non-aggregate mesh path: filter+project over partitions via the
    # stacked shard_map pipeline (round 2 ran these as a serial union)
    psql = "SELECT k, v1 * 2.0, v3 FROM t WHERE v1 > 500.0"
    relp1 = ctx1.sql(psql)
    pipe_p50_1, pout1 = timed(lambda: collect(relp1), runs=3, warmup=1)
    relpm = ctxm.sql(psql)
    pipe_p50_m, poutm = timed(lambda: collect(relpm), runs=3, warmup=1)
    assert poutm.num_rows == pout1.num_rows, (
        f"{poutm.num_rows} vs {pout1.num_rows} rows"
    )
    # value parity, not just cardinality (same protection the aggregate
    # check above has)
    got_rows = sorted(poutm.to_rows())
    want_rows = sorted(pout1.to_rows())
    for g, w in zip(got_rows, want_rows):
        np.testing.assert_allclose(
            np.asarray(g, float), np.asarray(w, float), rtol=1e-9
        )

    print(json.dumps({
        "name": "partitioned_mesh_aggregate",
        "rows": rows,
        "groups": groups,
        "devices": n_dev,
        "unit": "rows/s",
        "value": round(rows / p50_m, 1),
        "p50_ms": round(p50_m * 1e3, 2),
        "single_device_p50_ms": round(p50_1 * 1e3, 2),
        "vs_baseline": round(p50_1 / p50_m, 3),
        "pipeline": {
            "rows": rows,
            "out_rows": int(poutm.num_rows),
            "value": round(rows / pipe_p50_m, 1),
            "p50_ms": round(pipe_p50_m * 1e3, 2),
            "single_device_p50_ms": round(pipe_p50_1 * 1e3, 2),
            "vs_baseline": round(pipe_p50_1 / pipe_p50_m, 3),
        },
        "note": (
            f"{n_dev} VIRTUAL devices share one physical core: this "
            "validates the shard_map+psum path and bounds its overhead; "
            "it cannot show scaling (no multi-chip hardware here)"
        ),
    }))


if __name__ == "__main__":
    main()
