// datafusion-tpu native runtime: SQL front-end + plan IR.
//
// The reference's front-end is native Rust: the tokenizer/parser shim
// (`src/dfparser.rs:74`, delegating ANSI statements to the `sqlparser`
// crate and hand-parsing the CREATE EXTERNAL TABLE DDL at
// `dfparser.rs:101-208`) and the serde-serializable plan IR
// (`src/logicalplan.rs:133-345`).  This file is the C++ equivalent:
//
//  - a SQL tokenizer + recursive-descent parser producing the engine's
//    AST (as a JSON tree consumed by datafusion_tpu/native/sqlfront.py;
//    grammar and precedence mirror datafusion_tpu/sql/parser.py, which
//    the golden planner tests pin down);
//  - the logical plan / expression IR with the exact externally-tagged
//    JSON wire format of plan/{expr,logical}.py (the distributed-mode
//    plan-shipping contract, reference `logicalplan.rs:609-648`) and
//    the exact pretty-print format the planner golden tests assert.
//
// Numbers ride through serde as raw text (Python ints are unbounded;
// re-emitting the original bytes keeps round trips lossless).
//
// C ABI (ctypes; no pybind11 in this environment):
//   dtf_parse_sql(sql)      -> {"ok": <ast json>} | {"error": msg}
//   dtf_plan_roundtrip(json)-> the same plan re-serialized from the
//                              C++ IR (byte-identical on success)
//   dtf_plan_repr(json)     -> the plan pretty-print
//   dtf_free(ptr)

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// JSON (order-preserving objects, raw-text numbers)
// ---------------------------------------------------------------------------

struct Json;
using JsonMembers = std::vector<std::pair<std::string, Json>>;

struct Json {
  enum Kind { NUL, BOOL, NUMBER, STRING, ARRAY, OBJECT } kind = NUL;
  bool b = false;
  std::string text;  // NUMBER: raw text; STRING: decoded bytes
  std::vector<Json> items;
  JsonMembers members;

  static Json null() { return Json{}; }
  static Json boolean(bool v) {
    Json j; j.kind = BOOL; j.b = v; return j;
  }
  static Json number_raw(std::string raw) {
    Json j; j.kind = NUMBER; j.text = std::move(raw); return j;
  }
  static Json number(long long v) { return number_raw(std::to_string(v)); }
  static Json str(std::string s) {
    Json j; j.kind = STRING; j.text = std::move(s); return j;
  }
  static Json array() { Json j; j.kind = ARRAY; return j; }
  static Json object() { Json j; j.kind = OBJECT; return j; }

  Json& set(const std::string& k, Json v) {
    members.emplace_back(k, std::move(v));
    return *this;
  }
  const Json* get(const std::string& k) const {
    for (auto& kv : members)
      if (kv.first == k) return &kv.second;
    return nullptr;
  }
  bool is(Kind k) const { return kind == k; }
  long long as_int() const {
    if (kind != NUMBER) throw std::runtime_error("expected number");
    return strtoll(text.c_str(), nullptr, 10);
  }
};

struct JsonParser {
  const char* p;
  const char* end;

  explicit JsonParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  [[noreturn]] void fail(const std::string& m) {
    throw std::runtime_error("JSON: " + m);
  }
  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++;
  }
  bool eat(char c) {
    skip_ws();
    if (p < end && *p == c) { p++; return true; }
    return false;
  }
  void expect(char c) {
    if (!eat(c)) fail(std::string("expected '") + c + "'");
  }

  Json parse() {
    Json v = parse_value();
    skip_ws();
    if (p != end) fail("trailing data");
    return v;
  }

  Json parse_value() {
    skip_ws();
    if (p >= end) fail("unexpected end");
    char c = *p;
    if (c == '{') return parse_object();
    if (c == '[') return parse_array();
    if (c == '"') return Json::str(parse_string());
    if (c == 't') { literal("true"); return Json::boolean(true); }
    if (c == 'f') { literal("false"); return Json::boolean(false); }
    if (c == 'n') { literal("null"); return Json::null(); }
    return parse_number();
  }

  void literal(const char* s) {
    size_t n = strlen(s);
    if (size_t(end - p) < n || strncmp(p, s, n) != 0) fail("bad literal");
    p += n;
  }

  Json parse_number() {
    const char* start = p;
    if (p < end && *p == '-') p++;
    while (p < end && (isdigit((unsigned char)*p) || *p == '.' || *p == 'e' ||
                       *p == 'E' || *p == '+' || *p == '-'))
      p++;
    if (p == start) fail("bad number");
    return Json::number_raw(std::string(start, p));
  }

  std::string parse_string() {
    expect('"');
    std::string out;
    while (true) {
      if (p >= end) fail("unterminated string");
      unsigned char c = (unsigned char)*p++;
      if (c == '"') break;
      if (c == '\\') {
        if (p >= end) fail("bad escape");
        char e = *p++;
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            unsigned cp = parse_hex4();
            if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 && p[0] == '\\' &&
                p[1] == 'u') {
              p += 2;
              unsigned lo = parse_hex4();
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            append_utf8(out, cp);
            break;
          }
          default: fail("bad escape");
        }
      } else {
        out += (char)c;
      }
    }
    return out;
  }

  unsigned parse_hex4() {
    if (end - p < 4) fail("bad \\u escape");
    unsigned v = 0;
    for (int i = 0; i < 4; i++) {
      char c = *p++;
      v <<= 4;
      if (c >= '0' && c <= '9') v |= unsigned(c - '0');
      else if (c >= 'a' && c <= 'f') v |= unsigned(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') v |= unsigned(c - 'A' + 10);
      else fail("bad hex digit");
    }
    return v;
  }

  static void append_utf8(std::string& out, unsigned cp) {
    if (cp < 0x80) out += (char)cp;
    else if (cp < 0x800) {
      out += (char)(0xC0 | (cp >> 6));
      out += (char)(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += (char)(0xE0 | (cp >> 12));
      out += (char)(0x80 | ((cp >> 6) & 0x3F));
      out += (char)(0x80 | (cp & 0x3F));
    } else {
      out += (char)(0xF0 | (cp >> 18));
      out += (char)(0x80 | ((cp >> 12) & 0x3F));
      out += (char)(0x80 | ((cp >> 6) & 0x3F));
      out += (char)(0x80 | (cp & 0x3F));
    }
  }

  Json parse_object() {
    expect('{');
    Json obj = Json::object();
    skip_ws();
    if (eat('}')) return obj;
    while (true) {
      skip_ws();
      std::string key = parse_string();
      expect(':');
      obj.members.emplace_back(std::move(key), parse_value());
      if (eat(',')) continue;
      expect('}');
      break;
    }
    return obj;
  }

  Json parse_array() {
    expect('[');
    Json arr = Json::array();
    skip_ws();
    if (eat(']')) return arr;
    while (true) {
      arr.items.push_back(parse_value());
      if (eat(',')) continue;
      expect(']');
      break;
    }
    return arr;
  }
};

// compact serialization matching json.dumps(separators=(",", ":"),
// ensure_ascii=False): raw UTF-8, escapes for ", \ and control chars
void write_json_string(std::string& out, const std::string& s) {
  out += '"';
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += (char)c;
        }
    }
  }
  out += '"';
}

void write_json(std::string& out, const Json& j) {
  switch (j.kind) {
    case Json::NUL: out += "null"; break;
    case Json::BOOL: out += j.b ? "true" : "false"; break;
    case Json::NUMBER: out += j.text; break;
    case Json::STRING: write_json_string(out, j.text); break;
    case Json::ARRAY: {
      out += '[';
      for (size_t i = 0; i < j.items.size(); i++) {
        if (i) out += ',';
        write_json(out, j.items[i]);
      }
      out += ']';
      break;
    }
    case Json::OBJECT: {
      out += '{';
      for (size_t i = 0; i < j.members.size(); i++) {
        if (i) out += ',';
        write_json_string(out, j.members[i].first);
        out += ':';
        write_json(out, j.members[i].second);
      }
      out += '}';
      break;
    }
  }
}

std::string dumps(const Json& j) {
  std::string out;
  write_json(out, j);
  return out;
}

// ---------------------------------------------------------------------------
// SQL tokenizer (mirror of datafusion_tpu/sql/tokenizer.py)
// ---------------------------------------------------------------------------

enum TokKind { T_WORD, T_NUMBER, T_STRING, T_OP, T_EOF };

struct Tok {
  TokKind kind;
  std::string value;
  size_t pos;
};

struct SqlError : std::runtime_error {
  explicit SqlError(const std::string& m) : std::runtime_error(m) {}
};

// identifier characters: ASCII letters/digits/underscore plus any
// non-ASCII byte (Python's str.isalpha admits unicode letters)
bool word_start(unsigned char c) {
  return isalpha(c) || c == '_' || c >= 0x80;
}
bool word_cont(unsigned char c) {
  return isalnum(c) || c == '_' || c >= 0x80;
}

bool is_two_char_op(const char* p, const char* end) {
  if (end - p < 2) return false;
  return (p[0] == '!' && p[1] == '=') || (p[0] == '<' && p[1] == '>') ||
         (p[0] == '<' && p[1] == '=') || (p[0] == '>' && p[1] == '=');
}

bool is_one_char_op(char c) {
  return strchr("(),.;*=<>+-/%", c) != nullptr;
}

std::vector<Tok> tokenize(const std::string& sql) {
  std::vector<Tok> toks;
  const char* s = sql.data();
  size_t i = 0, n = sql.size();
  while (i < n) {
    unsigned char c = (unsigned char)s[i];
    if (isspace(c)) { i++; continue; }
    if (c == '-' && i + 1 < n && s[i + 1] == '-') {  // line comment
      while (i < n && s[i] != '\n') i++;
      continue;
    }
    if (c == '/' && i + 1 < n && s[i + 1] == '*') {  // block comment
      size_t e = sql.find("*/", i + 2);
      if (e == std::string::npos)
        throw SqlError("Unterminated block comment at " + std::to_string(i));
      i = e + 2;
      continue;
    }
    if (word_start(c)) {
      size_t j = i + 1;
      while (j < n && word_cont((unsigned char)s[j])) j++;
      toks.push_back({T_WORD, sql.substr(i, j - i), i});
      i = j;
      continue;
    }
    if (isdigit(c) || (c == '.' && i + 1 < n && isdigit((unsigned char)s[i + 1]))) {
      size_t j = i;
      bool seen_dot = false, seen_exp = false;
      while (j < n) {
        char ch = s[j];
        if (isdigit((unsigned char)ch)) { j++; }
        else if (ch == '.' && !seen_dot && !seen_exp) { seen_dot = true; j++; }
        else if ((ch == 'e' || ch == 'E') && !seen_exp && j > i) {
          size_t k = j + 1;
          if (k < n && (s[k] == '+' || s[k] == '-')) k++;
          if (k < n && isdigit((unsigned char)s[k])) { seen_exp = true; j = k; }
          else break;
        } else break;
      }
      toks.push_back({T_NUMBER, sql.substr(i, j - i), i});
      i = j;
      continue;
    }
    if (c == '\'') {
      size_t j = i + 1;
      std::string buf;
      while (true) {
        if (j >= n)
          throw SqlError("Unterminated string literal at " + std::to_string(i));
        if (s[j] == '\'') {
          if (j + 1 < n && s[j + 1] == '\'') { buf += '\''; j += 2; continue; }
          break;
        }
        buf += s[j];
        j++;
      }
      toks.push_back({T_STRING, buf, i});
      i = j + 1;
      continue;
    }
    if (is_two_char_op(s + i, s + n)) {
      toks.push_back({T_OP, sql.substr(i, 2), i});
      i += 2;
      continue;
    }
    if (is_one_char_op((char)c)) {
      toks.push_back({T_OP, std::string(1, (char)c), i});
      i += 1;
      continue;
    }
    throw SqlError("Unexpected character '" + std::string(1, (char)c) +
                   "' at position " + std::to_string(i));
  }
  toks.push_back({T_EOF, "", n});
  return toks;
}

// ---------------------------------------------------------------------------
// SQL parser (mirror of datafusion_tpu/sql/parser.py) -> AST as Json
// ---------------------------------------------------------------------------

std::string upper(const std::string& s) {
  std::string o = s;
  for (auto& c : o)
    if (c >= 'a' && c <= 'z') c = char(c - 'a' + 'A');
  return o;
}

const int PREC_OR = 5, PREC_AND = 10, PREC_NOT = 15, PREC_CMP = 20,
          PREC_ADD = 30, PREC_MUL = 40;

bool is_cmp_op(const std::string& v) {
  return v == "=" || v == "!=" || v == "<>" || v == "<" || v == "<=" ||
         v == ">" || v == ">=";
}

const char* RESERVED_STOP[] = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "BY",
    "ASC", "DESC", "AND", "OR", "NOT", "AS", "IS", "NULL",
};
bool is_reserved(const std::string& up) {
  for (const char* r : RESERVED_STOP)
    if (up == r) return true;
  return false;
}

// SQL type word -> canonical enum value (ast.SqlType in Python)
const char* type_word(const std::string& up) {
  if (up == "BOOLEAN" || up == "BOOL") return "BOOLEAN";
  if (up == "TINYINT") return "TINYINT";
  if (up == "SMALLINT") return "SMALLINT";
  if (up == "INT" || up == "INTEGER") return "INT";
  if (up == "BIGINT") return "BIGINT";
  if (up == "FLOAT") return "FLOAT";
  if (up == "REAL") return "REAL";
  if (up == "DOUBLE") return "DOUBLE";
  if (up == "CHAR") return "CHAR";
  if (up == "VARCHAR") return "VARCHAR";
  return nullptr;
}

Json tagged(const char* tag, Json body) {
  Json j = Json::object();
  j.set(tag, std::move(body));
  return j;
}

struct SqlParser {
  std::string sql;
  std::vector<Tok> toks;
  size_t i = 0;

  explicit SqlParser(std::string text) : sql(std::move(text)), toks(tokenize(sql)) {}

  const Tok& peek() const { return toks[i]; }
  const Tok& next() {
    const Tok& t = toks[i];
    if (t.kind != T_EOF) i++;
    return t;
  }
  std::string tok_repr(const Tok& t) const {
    const char* k = t.kind == T_WORD ? "WORD" : t.kind == T_NUMBER ? "NUMBER"
                    : t.kind == T_STRING ? "STRING" : t.kind == T_OP ? "OP" : "EOF";
    return std::string(k) + "('" + t.value + "')";
  }
  [[noreturn]] void fail(const std::string& m) const {
    throw SqlError(m + " in '" + sql + "'");
  }

  std::string peek_word() const {
    return peek().kind == T_WORD ? upper(peek().value) : std::string();
  }
  bool parse_keyword(const char* kw) {
    if (peek_word() == kw) { next(); return true; }
    return false;
  }
  bool parse_keywords2(const char* a, const char* b) {
    size_t mark = i;
    if (parse_keyword(a) && parse_keyword(b)) return true;
    i = mark;
    return false;
  }
  bool parse_keywords3(const char* a, const char* b, const char* c) {
    size_t mark = i;
    if (parse_keyword(a) && parse_keyword(b) && parse_keyword(c)) return true;
    i = mark;
    return false;
  }
  void expect_keyword(const char* kw) {
    if (!parse_keyword(kw)) fail(std::string("Expected ") + kw + ", found " + tok_repr(peek()));
  }
  bool consume_op(const char* op) {
    if (peek().kind == T_OP && peek().value == op) { next(); return true; }
    return false;
  }
  void expect_op(const char* op) {
    if (!consume_op(op))
      fail(std::string("Expected '") + op + "', found " + tok_repr(peek()));
  }
  std::string expect_identifier() {
    const Tok& t = peek();
    if (t.kind == T_WORD && !is_reserved(upper(t.value))) {
      next();
      return t.value;
    }
    fail("Expected identifier, found " + tok_repr(t));
  }

  // -- statements --
  Json parse_statement() {
    if (parse_keywords3("CREATE", "EXTERNAL", "TABLE"))
      return parse_create_external_table();
    if (parse_keyword("EXPLAIN")) return tagged("Explain", parse_statement());
    if (parse_keyword("SELECT")) return parse_select();
    fail("Expected a statement, found " + tok_repr(peek()));
  }

  Json parse_select() {
    Json projection = Json::array();
    while (true) {
      if (consume_op("*")) {
        projection.items.push_back(Json::str("Wildcard"));
      } else {
        Json e = parse_expr(0);
        if (parse_keyword("AS")) {
          Json body = Json::object();
          body.set("expr", std::move(e));
          body.set("alias", Json::str(expect_identifier()));
          e = tagged("Aliased", std::move(body));
        }
        projection.items.push_back(std::move(e));
      }
      if (!consume_op(",")) break;
    }
    Json sel = Json::object();
    sel.set("projection", std::move(projection));
    if (parse_keyword("FROM"))
      sel.set("relation", Json::str(expect_identifier()));
    else
      sel.set("relation", Json::null());
    sel.set("selection", parse_keyword("WHERE") ? parse_expr(0) : Json::null());
    Json group_by = Json::array();
    if (parse_keywords2("GROUP", "BY")) {
      while (true) {
        group_by.items.push_back(parse_expr(0));
        if (!consume_op(",")) break;
      }
    }
    sel.set("group_by", std::move(group_by));
    sel.set("having", parse_keyword("HAVING") ? parse_expr(0) : Json::null());
    Json order_by = Json::array();
    if (parse_keywords2("ORDER", "BY")) {
      while (true) {
        Json e = parse_expr(0);
        bool asc = true;
        if (parse_keyword("DESC")) asc = false;
        else parse_keyword("ASC");
        Json ob = Json::object();
        ob.set("expr", std::move(e));
        ob.set("asc", Json::boolean(asc));
        order_by.items.push_back(std::move(ob));
        if (!consume_op(",")) break;
      }
    }
    sel.set("order_by", std::move(order_by));
    sel.set("limit", parse_keyword("LIMIT") ? parse_expr(0) : Json::null());
    consume_op(";");
    if (peek().kind != T_EOF)
      fail("Unexpected trailing token " + tok_repr(peek()));
    return tagged("Select", std::move(sel));
  }

  Json parse_create_external_table() {
    std::string name = expect_identifier();
    Json columns = Json::array();
    if (consume_op("(")) {
      while (true) {
        std::string col_name = expect_identifier();
        const char* col_type = parse_data_type();
        bool allow_null = true;
        if (parse_keywords2("NOT", "NULL")) allow_null = false;
        else parse_keyword("NULL");
        Json col = Json::object();
        col.set("name", Json::str(col_name));
        col.set("type", Json::str(col_type));
        col.set("allow_null", Json::boolean(allow_null));
        columns.items.push_back(std::move(col));
        if (consume_op(",")) continue;
        expect_op(")");
        break;
      }
    }
    bool headers = true;
    const char* file_type;
    if (parse_keywords3("STORED", "AS", "CSV")) {
      if (parse_keywords3("WITH", "HEADER", "ROW")) headers = true;
      else if (parse_keywords3("WITHOUT", "HEADER", "ROW")) headers = false;
      file_type = "CSV";
    } else if (parse_keywords3("STORED", "AS", "NDJSON")) {
      file_type = "NDJSON";
    } else if (parse_keywords3("STORED", "AS", "PARQUET")) {
      file_type = "PARQUET";
    } else {
      fail("Expected 'STORED AS' clause, found " + tok_repr(peek()));
    }
    if (!parse_keyword("LOCATION")) throw SqlError("Missing 'LOCATION' clause");
    const Tok& t = next();
    if (t.kind != T_STRING)
      throw SqlError("Expected string literal after LOCATION, found " + tok_repr(t));
    consume_op(";");
    Json body = Json::object();
    body.set("name", Json::str(name));
    body.set("columns", std::move(columns));
    body.set("file_type", Json::str(file_type));
    body.set("header_row", Json::boolean(headers));
    body.set("location", Json::str(t.value));
    return tagged("CreateExternalTable", std::move(body));
  }

  const char* parse_data_type() {
    std::string w = peek_word();
    const char* ty = w.empty() ? nullptr : type_word(w);
    if (ty == nullptr)
      fail("Expected a data type, found " + tok_repr(peek()));
    next();
    if (consume_op("(")) {  // CHAR(n) / VARCHAR(n) / FLOAT(p)
      const Tok& t = next();
      if (t.kind != T_NUMBER)
        throw SqlError("Expected length in type, found " + tok_repr(t));
      expect_op(")");
    }
    return ty;
  }

  // -- expressions (precedence climbing) --
  int next_precedence() const {
    const Tok& t = peek();
    if (t.kind == T_OP) {
      if (is_cmp_op(t.value)) return PREC_CMP;
      if (t.value == "+" || t.value == "-") return PREC_ADD;
      if (t.value == "*" || t.value == "/" || t.value == "%") return PREC_MUL;
      return 0;
    }
    if (t.kind == T_WORD) {
      std::string w = upper(t.value);
      if (w == "OR") return PREC_OR;
      if (w == "AND") return PREC_AND;
      if (w == "IS") return PREC_CMP;
    }
    return 0;
  }

  Json parse_expr(int min_prec) {
    Json expr = parse_prefix();
    while (true) {
      int prec = next_precedence();
      if (prec <= min_prec) return expr;
      expr = parse_infix(std::move(expr), prec);
    }
  }

  Json binary(Json left, const std::string& op, Json right) {
    Json body = Json::object();
    body.set("left", std::move(left));
    body.set("op", Json::str(op));
    body.set("right", std::move(right));
    return tagged("Binary", std::move(body));
  }

  Json parse_infix(Json left, int prec) {
    const Tok& t = next();
    if (t.kind == T_OP) {
      std::string op = t.value == "<>" ? "!=" : t.value;
      return binary(std::move(left), op, parse_expr(prec));
    }
    std::string w = upper(t.value);
    if (w == "AND" || w == "OR")
      return binary(std::move(left), w, parse_expr(prec));
    if (w == "IS") {
      if (parse_keywords2("NOT", "NULL")) return tagged("IsNotNull", std::move(left));
      if (parse_keyword("NULL")) return tagged("IsNull", std::move(left));
      fail("Expected NULL or NOT NULL after IS");
    }
    fail("Unexpected infix token " + tok_repr(t));
  }

  Json unary(const char* op, Json e) {
    Json body = Json::object();
    body.set("op", Json::str(op));
    body.set("expr", std::move(e));
    return tagged("Unary", std::move(body));
  }

  Json parse_prefix() {
    const Tok& t = next();
    if (t.kind == T_NUMBER) {
      bool is_double = t.value.find('.') != std::string::npos ||
                       t.value.find('e') != std::string::npos ||
                       t.value.find('E') != std::string::npos;
      // raw text rides through; Python int()/float() does the convert
      return tagged(is_double ? "Double" : "Long", Json::str(t.value));
    }
    if (t.kind == T_STRING) return tagged("String", Json::str(t.value));
    if (t.kind == T_OP) {
      if (t.value == "(") {
        Json inner = parse_expr(0);
        expect_op(")");
        return tagged("Nested", std::move(inner));
      }
      if (t.value == "-") return unary("-", parse_expr(PREC_MUL));
      if (t.value == "+") return unary("+", parse_expr(PREC_MUL));
      if (t.value == "*") return Json::str("Wildcard");
      fail("Unexpected token " + tok_repr(t));
    }
    if (t.kind == T_WORD) {
      std::string w = upper(t.value);
      if (w == "TRUE") return tagged("Bool", Json::boolean(true));
      if (w == "FALSE") return tagged("Bool", Json::boolean(false));
      if (w == "NULL") return Json::str("Null");
      if (w == "NOT") return unary("NOT", parse_expr(PREC_NOT));
      if (w == "CAST") {
        expect_op("(");
        Json inner = parse_expr(0);
        expect_keyword("AS");
        const char* ty = parse_data_type();
        expect_op(")");
        Json body = Json::object();
        body.set("expr", std::move(inner));
        body.set("type", Json::str(ty));
        return tagged("Cast", std::move(body));
      }
      if (is_reserved(w)) fail("Unexpected keyword '" + t.value + "'");
      if (consume_op("(")) {  // function call
        Json args = Json::array();
        if (!consume_op(")")) {
          while (true) {
            if (consume_op("*")) args.items.push_back(Json::str("Wildcard"));
            else args.items.push_back(parse_expr(0));
            if (consume_op(",")) continue;
            expect_op(")");
            break;
          }
        }
        Json body = Json::object();
        body.set("name", Json::str(t.value));
        body.set("args", std::move(args));
        return tagged("Function", std::move(body));
      }
      return tagged("Identifier", Json::str(t.value));
    }
    fail("Unexpected token " + tok_repr(t));
  }
};

// ---------------------------------------------------------------------------
// Plan / expression IR (mirror of plan/{expr,logical}.py; reference
// `logicalplan.rs:133-345`)
// ---------------------------------------------------------------------------

struct DTypeT {
  std::string name;            // "Int64", ... or "Struct"
  Json struct_fields;          // raw field list for Struct types
  bool is_struct = false;
};

struct FieldT {
  std::string name;
  DTypeT type;
  bool nullable = true;
};

struct SchemaT {
  std::vector<FieldT> fields;
};

struct ExprT {
  enum Kind {
    COLUMN, LITERAL, BINARY, IS_NULL, IS_NOT_NULL, CAST, SORT, SCALAR_FN, AGG_FN
  } kind = COLUMN;
  long long column = 0;          // COLUMN
  std::string lit_tag;           // LITERAL: "Int64" ... or "" for Null
  Json lit_value;                // LITERAL payload (raw)
  std::string op;                // BINARY: operator variant name
  std::string name;              // SCALAR_FN / AGG_FN
  DTypeT dtype;                  // CAST target / fn return type
  bool asc = true;               // SORT
  bool count_star = false;       // AGG_FN
  std::vector<ExprT> children;   // binary: [l, r]; others: [e] / args
};

struct PlanT {
  enum Kind { EMPTY, TABLE_SCAN, PROJECTION, SELECTION, AGGREGATE, SORT, LIMIT }
      kind = EMPTY;
  std::string schema_name, table_name;
  SchemaT schema;                 // node/table schema
  bool has_projection = false;
  std::vector<long long> projection;
  ExprT predicate;                // SELECTION
  std::vector<ExprT> exprs;       // PROJECTION / SORT keys
  std::vector<ExprT> group_exprs, aggr_exprs;  // AGGREGATE
  long long limit = 0;            // LIMIT
  std::unique_ptr<PlanT> input;
};

[[noreturn]] void plan_fail(const std::string& m) {
  throw std::runtime_error(m);
}

const char* OPERATORS[] = {"Eq", "NotEq", "Lt", "LtEq", "Gt", "GtEq", "Plus",
                           "Minus", "Multiply", "Divide", "Modulus", "And", "Or"};
const char* SCALAR_TYPES[] = {"Boolean", "Int8", "Int16", "Int32", "Int64",
                              "UInt8", "UInt16", "UInt32", "UInt64", "Float32",
                              "Float64", "Utf8"};

DTypeT dtype_from_json(const Json& j) {
  DTypeT t;
  if (j.is(Json::STRING)) {
    for (const char* n : SCALAR_TYPES)
      if (j.text == n) { t.name = j.text; return t; }
    plan_fail("Unknown DataType '" + j.text + "'");
  }
  if (j.is(Json::OBJECT) && j.get("Struct") != nullptr) {
    t.name = "Struct";
    t.is_struct = true;
    t.struct_fields = *j.get("Struct");
    return t;
  }
  plan_fail("Cannot deserialize DataType");
}

Json dtype_to_json(const DTypeT& t) {
  if (!t.is_struct) return Json::str(t.name);
  Json j = Json::object();
  j.set("Struct", t.struct_fields);
  return j;
}

FieldT field_from_json(const Json& j) {
  const Json* name = j.get("name");
  const Json* dt = j.get("data_type");
  const Json* nl = j.get("nullable");
  if (name == nullptr || dt == nullptr || nl == nullptr)
    plan_fail("Malformed Field wire object");
  FieldT f;
  f.name = name->text;
  f.type = dtype_from_json(*dt);
  f.nullable = nl->b;
  return f;
}

Json field_to_json(const FieldT& f) {
  Json j = Json::object();
  j.set("name", Json::str(f.name));
  j.set("data_type", dtype_to_json(f.type));
  j.set("nullable", Json::boolean(f.nullable));
  return j;
}

SchemaT schema_from_json(const Json& j) {
  const Json* fields = j.get("fields");
  if (fields == nullptr || !fields->is(Json::ARRAY))
    plan_fail("Malformed Schema wire object");
  SchemaT s;
  for (const Json& f : fields->items) s.fields.push_back(field_from_json(f));
  return s;
}

Json schema_to_json(const SchemaT& s) {
  Json fields = Json::array();
  for (const FieldT& f : s.fields) fields.items.push_back(field_to_json(f));
  Json j = Json::object();
  j.set("fields", std::move(fields));
  return j;
}

ExprT expr_from_json(const Json& j);

std::vector<ExprT> exprs_from_json(const Json& arr) {
  if (!arr.is(Json::ARRAY)) plan_fail("expected expression array");
  std::vector<ExprT> out;
  for (const Json& e : arr.items) out.push_back(expr_from_json(e));
  return out;
}

ExprT expr_from_json(const Json& j) {
  if (!j.is(Json::OBJECT) || j.members.size() != 1)
    plan_fail("Malformed Expr wire object");
  const std::string& tag = j.members[0].first;
  const Json& body = j.members[0].second;
  ExprT e;
  if (tag == "Column") {
    e.kind = ExprT::COLUMN;
    e.column = body.as_int();
  } else if (tag == "Literal") {
    e.kind = ExprT::LITERAL;
    if (body.is(Json::STRING) && body.text == "Null") {
      e.lit_tag = "";
    } else if (body.is(Json::OBJECT) && body.members.size() == 1) {
      e.lit_tag = body.members[0].first;
      bool known = false;
      for (const char* n : SCALAR_TYPES)
        if (e.lit_tag == n) known = true;
      if (!known) plan_fail("Unknown ScalarValue type '" + e.lit_tag + "'");
      e.lit_value = body.members[0].second;
    } else {
      plan_fail("Malformed ScalarValue wire object");
    }
  } else if (tag == "BinaryExpr") {
    e.kind = ExprT::BINARY;
    const Json* l = body.get("left");
    const Json* op = body.get("op");
    const Json* r = body.get("right");
    if (l == nullptr || op == nullptr || r == nullptr)
      plan_fail("Malformed BinaryExpr");
    bool known = false;
    for (const char* n : OPERATORS)
      if (op->text == n) known = true;
    if (!known) plan_fail("Unknown Operator '" + op->text + "'");
    e.op = op->text;
    e.children.push_back(expr_from_json(*l));
    e.children.push_back(expr_from_json(*r));
  } else if (tag == "IsNull" || tag == "IsNotNull") {
    e.kind = tag == "IsNull" ? ExprT::IS_NULL : ExprT::IS_NOT_NULL;
    e.children.push_back(expr_from_json(body));
  } else if (tag == "Cast") {
    e.kind = ExprT::CAST;
    const Json* ex = body.get("expr");
    const Json* dt = body.get("data_type");
    if (ex == nullptr || dt == nullptr) plan_fail("Malformed Cast");
    e.children.push_back(expr_from_json(*ex));
    e.dtype = dtype_from_json(*dt);
  } else if (tag == "Sort") {
    e.kind = ExprT::SORT;
    const Json* ex = body.get("expr");
    const Json* asc = body.get("asc");
    if (ex == nullptr || asc == nullptr) plan_fail("Malformed Sort expr");
    e.children.push_back(expr_from_json(*ex));
    e.asc = asc->b;
  } else if (tag == "ScalarFunction" || tag == "AggregateFunction") {
    e.kind = tag == "ScalarFunction" ? ExprT::SCALAR_FN : ExprT::AGG_FN;
    const Json* nm = body.get("name");
    const Json* args = body.get("args");
    const Json* rt = body.get("return_type");
    if (nm == nullptr || args == nullptr || rt == nullptr)
      plan_fail("Malformed function expr");
    e.name = nm->text;
    e.children = exprs_from_json(*args);
    e.dtype = dtype_from_json(*rt);
    const Json* cs = body.get("count_star");
    e.count_star = cs != nullptr && cs->b;
  } else {
    plan_fail("Unknown Expr variant '" + tag + "'");
  }
  return e;
}

Json expr_to_json(const ExprT& e) {
  switch (e.kind) {
    case ExprT::COLUMN:
      return tagged("Column", Json::number(e.column));
    case ExprT::LITERAL: {
      if (e.lit_tag.empty()) return tagged("Literal", Json::str("Null"));
      Json sv = Json::object();
      sv.set(e.lit_tag, e.lit_value);
      return tagged("Literal", std::move(sv));
    }
    case ExprT::BINARY: {
      Json body = Json::object();
      body.set("left", expr_to_json(e.children[0]));
      body.set("op", Json::str(e.op));
      body.set("right", expr_to_json(e.children[1]));
      return tagged("BinaryExpr", std::move(body));
    }
    case ExprT::IS_NULL:
      return tagged("IsNull", expr_to_json(e.children[0]));
    case ExprT::IS_NOT_NULL:
      return tagged("IsNotNull", expr_to_json(e.children[0]));
    case ExprT::CAST: {
      Json body = Json::object();
      body.set("expr", expr_to_json(e.children[0]));
      body.set("data_type", dtype_to_json(e.dtype));
      return tagged("Cast", std::move(body));
    }
    case ExprT::SORT: {
      Json body = Json::object();
      body.set("expr", expr_to_json(e.children[0]));
      body.set("asc", Json::boolean(e.asc));
      return tagged("Sort", std::move(body));
    }
    case ExprT::SCALAR_FN:
    case ExprT::AGG_FN: {
      Json args = Json::array();
      for (const ExprT& a : e.children) args.items.push_back(expr_to_json(a));
      Json body = Json::object();
      body.set("name", Json::str(e.name));
      body.set("args", std::move(args));
      body.set("return_type", dtype_to_json(e.dtype));
      if (e.kind == ExprT::AGG_FN && e.count_star)
        body.set("count_star", Json::boolean(true));
      return tagged(e.kind == ExprT::SCALAR_FN ? "ScalarFunction"
                                               : "AggregateFunction",
                    std::move(body));
    }
  }
  plan_fail("unreachable");
}

// scalar literal repr: Boolean(true), Utf8("CO"), Int64(1), Float64(9.0)
std::string literal_repr(const ExprT& e) {
  if (e.lit_tag.empty()) return "Null";
  if (e.lit_tag == "Boolean")
    return std::string("Boolean(") + (e.lit_value.b ? "true" : "false") + ")";
  if (e.lit_tag == "Utf8") {
    std::string out = "Utf8(\"";
    for (char c : e.lit_value.text) {
      if (c == '\\') out += "\\\\";
      else if (c == '"') out += "\\\"";
      else out += c;
    }
    out += "\")";
    return out;
  }
  if (e.lit_tag == "Float32" || e.lit_tag == "Float64") {
    // numbers carry their wire text; json.dumps of a Python float is
    // repr(float) so the raw text already matches — just guarantee a
    // decimal point (Rust/Python Debug always shows one)
    std::string v = e.lit_value.text;
    if (v.find('.') == std::string::npos && v.find('e') == std::string::npos &&
        v.find('E') == std::string::npos && v.find("inf") == std::string::npos &&
        v.find("nan") == std::string::npos)
      v += ".0";
    return e.lit_tag + "(" + v + ")";
  }
  return e.lit_tag + "(" + e.lit_value.text + ")";
}

std::string expr_repr(const ExprT& e) {
  switch (e.kind) {
    case ExprT::COLUMN: return "#" + std::to_string(e.column);
    case ExprT::LITERAL: return literal_repr(e);
    case ExprT::BINARY:
      return expr_repr(e.children[0]) + " " + e.op + " " + expr_repr(e.children[1]);
    case ExprT::IS_NULL: return expr_repr(e.children[0]) + " IS NULL";
    case ExprT::IS_NOT_NULL: return expr_repr(e.children[0]) + " IS NOT NULL";
    case ExprT::CAST:
      return "CAST(" + expr_repr(e.children[0]) + " AS " + e.dtype.name + ")";
    case ExprT::SORT:
      return expr_repr(e.children[0]) + (e.asc ? " ASC" : " DESC");
    case ExprT::SCALAR_FN:
    case ExprT::AGG_FN: {
      std::string out = e.name + "(";
      for (size_t i = 0; i < e.children.size(); i++) {
        if (i) out += ", ";
        out += expr_repr(e.children[i]);
      }
      return out + ")";
    }
  }
  plan_fail("unreachable");
}

std::unique_ptr<PlanT> plan_from_json(const Json& j) {
  if (!j.is(Json::OBJECT) || j.members.size() != 1)
    plan_fail("Malformed LogicalPlan wire object");
  const std::string& tag = j.members[0].first;
  const Json& body = j.members[0].second;
  auto p = std::make_unique<PlanT>();
  auto need = [&](const char* k) -> const Json& {
    const Json* v = body.get(k);
    if (v == nullptr) plan_fail("Malformed " + tag + ": missing " + k);
    return *v;
  };
  if (tag == "EmptyRelation") {
    p->kind = PlanT::EMPTY;
    p->schema = schema_from_json(need("schema"));
  } else if (tag == "TableScan") {
    p->kind = PlanT::TABLE_SCAN;
    p->schema_name = need("schema_name").text;
    p->table_name = need("table_name").text;
    p->schema = schema_from_json(need("schema"));
    const Json& proj = need("projection");
    if (!proj.is(Json::NUL)) {
      p->has_projection = true;
      for (const Json& i : proj.items) p->projection.push_back(i.as_int());
    }
  } else if (tag == "Projection") {
    p->kind = PlanT::PROJECTION;
    p->exprs = exprs_from_json(need("expr"));
    p->input = plan_from_json(need("input"));
    p->schema = schema_from_json(need("schema"));
  } else if (tag == "Selection") {
    p->kind = PlanT::SELECTION;
    p->predicate = expr_from_json(need("expr"));
    p->input = plan_from_json(need("input"));
  } else if (tag == "Aggregate") {
    p->kind = PlanT::AGGREGATE;
    p->input = plan_from_json(need("input"));
    p->group_exprs = exprs_from_json(need("group_expr"));
    p->aggr_exprs = exprs_from_json(need("aggr_expr"));
    p->schema = schema_from_json(need("schema"));
  } else if (tag == "Sort") {
    p->kind = PlanT::SORT;
    p->exprs = exprs_from_json(need("expr"));
    p->input = plan_from_json(need("input"));
    p->schema = schema_from_json(need("schema"));
  } else if (tag == "Limit") {
    p->kind = PlanT::LIMIT;
    p->limit = need("limit").as_int();
    p->input = plan_from_json(need("input"));
    p->schema = schema_from_json(need("schema"));
  } else {
    plan_fail("Unknown LogicalPlan variant '" + tag + "'");
  }
  return p;
}

Json plan_to_json(const PlanT& p) {
  Json body = Json::object();
  switch (p.kind) {
    case PlanT::EMPTY:
      body.set("schema", schema_to_json(p.schema));
      return tagged("EmptyRelation", std::move(body));
    case PlanT::TABLE_SCAN: {
      body.set("schema_name", Json::str(p.schema_name));
      body.set("table_name", Json::str(p.table_name));
      body.set("schema", schema_to_json(p.schema));
      if (p.has_projection) {
        Json proj = Json::array();
        for (long long i : p.projection) proj.items.push_back(Json::number(i));
        body.set("projection", std::move(proj));
      } else {
        body.set("projection", Json::null());
      }
      return tagged("TableScan", std::move(body));
    }
    case PlanT::PROJECTION: {
      Json exprs = Json::array();
      for (const ExprT& e : p.exprs) exprs.items.push_back(expr_to_json(e));
      body.set("expr", std::move(exprs));
      body.set("input", plan_to_json(*p.input));
      body.set("schema", schema_to_json(p.schema));
      return tagged("Projection", std::move(body));
    }
    case PlanT::SELECTION:
      body.set("expr", expr_to_json(p.predicate));
      body.set("input", plan_to_json(*p.input));
      return tagged("Selection", std::move(body));
    case PlanT::AGGREGATE: {
      body.set("input", plan_to_json(*p.input));
      Json g = Json::array();
      for (const ExprT& e : p.group_exprs) g.items.push_back(expr_to_json(e));
      body.set("group_expr", std::move(g));
      Json a = Json::array();
      for (const ExprT& e : p.aggr_exprs) a.items.push_back(expr_to_json(e));
      body.set("aggr_expr", std::move(a));
      body.set("schema", schema_to_json(p.schema));
      return tagged("Aggregate", std::move(body));
    }
    case PlanT::SORT: {
      Json exprs = Json::array();
      for (const ExprT& e : p.exprs) exprs.items.push_back(expr_to_json(e));
      body.set("expr", std::move(exprs));
      body.set("input", plan_to_json(*p.input));
      body.set("schema", schema_to_json(p.schema));
      return tagged("Sort", std::move(body));
    }
    case PlanT::LIMIT:
      body.set("limit", Json::number(p.limit));
      body.set("input", plan_to_json(*p.input));
      body.set("schema", schema_to_json(p.schema));
      return tagged("Limit", std::move(body));
  }
  plan_fail("unreachable");
}

// pretty-printer (reference fmt_with_indent, `logicalplan.rs:363-440`;
// the format the planner golden tests assert)
void plan_fmt(const PlanT& p, std::string& out, int indent) {
  for (int i = 0; i < indent; i++) out += "  ";
  switch (p.kind) {
    case PlanT::EMPTY:
      out += "EmptyRelation";
      break;
    case PlanT::TABLE_SCAN: {
      out += "TableScan: " + p.table_name + " projection=";
      if (!p.has_projection) {
        out += "None";
      } else {
        out += "Some([";
        for (size_t i = 0; i < p.projection.size(); i++) {
          if (i) out += ", ";
          out += std::to_string(p.projection[i]);
        }
        out += "])";
      }
      break;
    }
    case PlanT::PROJECTION: {
      out += "Projection: ";
      for (size_t i = 0; i < p.exprs.size(); i++) {
        if (i) out += ", ";
        out += expr_repr(p.exprs[i]);
      }
      break;
    }
    case PlanT::SELECTION:
      out += "Selection: " + expr_repr(p.predicate);
      break;
    case PlanT::AGGREGATE: {
      out += "Aggregate: groupBy=[[";
      for (size_t i = 0; i < p.group_exprs.size(); i++) {
        if (i) out += ", ";
        out += expr_repr(p.group_exprs[i]);
      }
      out += "]], aggr=[[";
      for (size_t i = 0; i < p.aggr_exprs.size(); i++) {
        if (i) out += ", ";
        out += expr_repr(p.aggr_exprs[i]);
      }
      out += "]]";
      break;
    }
    case PlanT::SORT: {
      out += "Sort: ";
      for (size_t i = 0; i < p.exprs.size(); i++) {
        if (i) out += ", ";
        out += expr_repr(p.exprs[i]);
      }
      break;
    }
    case PlanT::LIMIT:
      out += "Limit: " + std::to_string(p.limit);
      break;
  }
  if (p.input) {
    out += "\n";
    plan_fmt(*p.input, out, indent + 1);
  }
}

char* dup_string(const std::string& s) {
  char* out = (char*)malloc(s.size() + 1);
  if (out != nullptr) memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

char* error_json(const std::string& msg) {
  Json j = Json::object();
  j.set("error", Json::str(msg));
  return dup_string(dumps(j));
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// Parse one SQL statement; returns {"ok": <ast>} or {"error": msg}.
char* dtf_parse_sql(const char* sql) {
  try {
    SqlParser parser(sql != nullptr ? sql : "");
    Json ast = parser.parse_statement();
    Json out = Json::object();
    out.set("ok", std::move(ast));
    return dup_string(dumps(out));
  } catch (const std::exception& e) {
    return error_json(e.what());
  }
}

// Wire-format proof: deserialize a plan into the C++ IR and re-serialize.
// Byte-identical output == the C++ IR speaks the shipping contract.
char* dtf_plan_roundtrip(const char* json) {
  try {
    const std::string text(json != nullptr ? json : "");
    JsonParser jp(text);
    auto plan = plan_from_json(jp.parse());
    return dup_string(dumps(plan_to_json(*plan)));
  } catch (const std::exception& e) {
    return error_json(e.what());
  }
}

// Pretty-print a serialized plan (the golden-test format).
char* dtf_plan_repr(const char* json) {
  try {
    const std::string text(json != nullptr ? json : "");
    JsonParser jp(text);
    auto plan = plan_from_json(jp.parse());
    std::string out;
    plan_fmt(*plan, out, 0);
    return dup_string(out);
  } catch (const std::exception& e) {
    return error_json(e.what());
  }
}

void dtf_free(char* p) { free(p); }

}  // extern "C"
