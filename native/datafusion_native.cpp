// datafusion-tpu native runtime: C++ host-side components.
//
// The reference engine is 100% native (Rust).  Its host hot loop is the
// schema-driven CSV parse feeding columnar batches
// (`src/execution/datasource.rs:31-50` via arrow csv::Reader); this is
// the C++ equivalent, built as a shared library with a C ABI consumed
// through ctypes (no pybind11 in this environment).
//
// Properties mirrored from the Python/pyarrow reader (io/readers.py):
//  - schema-driven typed parsing (bool/int8..64/uint8..64/f32/f64/utf8)
//  - RFC-4180 quoting: quoted fields may contain commas, newlines and
//    escaped quotes ("")
//  - empty fields are NULL (validity bitmap per column)
//  - utf8 columns dictionary-encode natively: append-only per-column
//    string table -> int32 codes, stable across batches (GROUP BY keys
//    stay consistent for a whole scan)
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

enum ColType : int32_t {
  T_BOOL = 0,
  T_INT8 = 1,
  T_INT16 = 2,
  T_INT32 = 3,
  T_INT64 = 4,
  T_UINT8 = 5,
  T_UINT16 = 6,
  T_UINT32 = 7,
  T_UINT64 = 8,
  T_FLOAT32 = 9,
  T_FLOAT64 = 10,
  T_UTF8 = 11,
};

size_t type_width(int32_t t) {
  switch (t) {
    case T_BOOL: case T_INT8: case T_UINT8: return 1;
    case T_INT16: case T_UINT16: return 2;
    case T_INT32: case T_UINT32: case T_FLOAT32: case T_UTF8: return 4;
    default: return 8;
  }
}

struct Dictionary {
  std::vector<std::string> values;
  std::unordered_map<std::string, int32_t> index;

  int32_t add(const std::string& s) {
    auto it = index.find(s);
    if (it != index.end()) return it->second;
    int32_t code = static_cast<int32_t>(values.size());
    values.push_back(s);
    index.emplace(s, code);
    return code;
  }
};

struct Column {
  int32_t type;
  bool active = true;             // projection: parse & store this column
  std::vector<uint8_t> data;      // batch_rows * width bytes
  std::vector<uint8_t> validity;  // 1 byte per row (1 = valid)
  bool any_null = false;
  Dictionary dict;                // utf8 only
};

struct CsvReader {
  FILE* file = nullptr;
  std::vector<Column> cols;
  int64_t batch_size = 0;
  int64_t rows_in_batch = 0;
  bool eof = false;
  std::string error;
  std::string pending;   // raw bytes carried across fread chunks
  size_t pending_pos = 0;
  std::vector<std::string> fields;  // scratch: one parsed record

  ~CsvReader() {
    if (file) fclose(file);
  }
};

// Pull one RFC-4180 record from the file into r.fields.
// Returns false at clean EOF, sets r.error on failure.
bool read_record(CsvReader& r) {
  r.fields.clear();
  std::string field;
  bool in_quotes = false;
  bool saw_any = false;
  bool field_was_quoted = false;

  auto next_char = [&](int* c) -> bool {
    if (r.pending_pos >= r.pending.size()) {
      char buf[1 << 16];
      size_t n = fread(buf, 1, sizeof buf, r.file);
      if (n == 0) return false;
      r.pending.assign(buf, n);
      r.pending_pos = 0;
    }
    *c = static_cast<unsigned char>(r.pending[r.pending_pos++]);
    return true;
  };

  int c;
  while (true) {
    if (!next_char(&c)) {
      if (in_quotes) {
        r.error = "unterminated quoted field at EOF";
        return false;
      }
      if (!saw_any) return false;  // clean EOF
      r.fields.push_back(field);
      return true;
    }
    saw_any = true;
    if (in_quotes) {
      if (c == '"') {
        int c2;
        if (!next_char(&c2)) {  // quote then EOF: close field & record
          in_quotes = false;
          r.fields.push_back(field);
          return true;
        }
        if (c2 == '"') {
          field.push_back('"');  // escaped quote
        } else {
          in_quotes = false;
          r.pending_pos--;  // reprocess c2 outside quotes
        }
      } else {
        field.push_back(static_cast<char>(c));
      }
    } else {
      if (c == '"' && field.empty() && !field_was_quoted) {
        in_quotes = true;
        field_was_quoted = true;
      } else if (c == ',') {
        r.fields.push_back(field);
        field.clear();
        field_was_quoted = false;
      } else if (c == '\n') {
        if (r.fields.empty() && field.empty() && !field_was_quoted) {
          // blank line: skip, keep reading
          saw_any = false;
          continue;
        }
        r.fields.push_back(field);
        return true;
      } else if (c == '\r') {
        // swallow (CRLF)
      } else {
        field.push_back(static_cast<char>(c));
      }
    }
  }
}

template <typename T>
void store(Column& col, int64_t row, T v) {
  std::memcpy(col.data.data() + row * sizeof(T), &v, sizeof(T));
}

bool parse_value(Column& col, int64_t row, const std::string& s,
                 std::string* err) {
  const char* p = s.c_str();
  char* end = nullptr;
  errno = 0;
  switch (col.type) {
    case T_BOOL: {
      // accept the same spellings as pyarrow's ConvertOptions defaults
      uint8_t v;
      if (s == "true" || s == "1" || s == "True" || s == "TRUE") v = 1;
      else if (s == "false" || s == "0" || s == "False" || s == "FALSE") v = 0;
      else { *err = "bad bool: " + s; return false; }
      store<uint8_t>(col, row, v);
      return true;
    }
    case T_INT8: case T_INT16: case T_INT32: case T_INT64: {
      long long v = strtoll(p, &end, 10);
      // Per-width range check: out-of-range values must error (the
      // pyarrow fallback raises), never silently wrap via the cast.
      long long lo, hi;
      switch (col.type) {
        case T_INT8: lo = INT8_MIN; hi = INT8_MAX; break;
        case T_INT16: lo = INT16_MIN; hi = INT16_MAX; break;
        case T_INT32: lo = INT32_MIN; hi = INT32_MAX; break;
        default: lo = INT64_MIN; hi = INT64_MAX; break;
      }
      if (end == p || *end != '\0' || errno == ERANGE || v < lo || v > hi) {
        *err = "bad int: " + s;
        return false;
      }
      switch (col.type) {
        case T_INT8: store<int8_t>(col, row, (int8_t)v); break;
        case T_INT16: store<int16_t>(col, row, (int16_t)v); break;
        case T_INT32: store<int32_t>(col, row, (int32_t)v); break;
        default: store<int64_t>(col, row, (int64_t)v); break;
      }
      return true;
    }
    case T_UINT8: case T_UINT16: case T_UINT32: case T_UINT64: {
      unsigned long long v = strtoull(p, &end, 10);
      unsigned long long hi;
      switch (col.type) {
        case T_UINT8: hi = UINT8_MAX; break;
        case T_UINT16: hi = UINT16_MAX; break;
        case T_UINT32: hi = UINT32_MAX; break;
        default: hi = UINT64_MAX; break;
      }
      if (end == p || *end != '\0' || errno == ERANGE || s[0] == '-' ||
          v > hi) {
        *err = "bad uint: " + s;
        return false;
      }
      switch (col.type) {
        case T_UINT8: store<uint8_t>(col, row, (uint8_t)v); break;
        case T_UINT16: store<uint16_t>(col, row, (uint16_t)v); break;
        case T_UINT32: store<uint32_t>(col, row, (uint32_t)v); break;
        default: store<uint64_t>(col, row, (uint64_t)v); break;
      }
      return true;
    }
    case T_FLOAT32: case T_FLOAT64: {
      double v = strtod(p, &end);
      if (end == p || *end != '\0') { *err = "bad float: " + s; return false; }
      if (col.type == T_FLOAT32) store<float>(col, row, (float)v);
      else store<double>(col, row, v);
      return true;
    }
    case T_UTF8:
      store<int32_t>(col, row, col.dict.add(s));
      return true;
  }
  *err = "unknown column type";
  return false;
}

}  // namespace

extern "C" {

// `active`: optional per-column projection mask (1 = parse & store);
// NULL means all columns.  Unprojected fields are skipped entirely —
// the projection push-down that gates host parse cost and H2D bytes.
void* dtf_csv_open(const char* path, int32_t ncols, const int32_t* col_types,
                   int32_t has_header, int64_t batch_size,
                   const uint8_t* active) {
  auto* r = new CsvReader();
  r->file = fopen(path, "rb");
  if (!r->file) {
    r->error = std::string("cannot open ") + path;
    return r;  // caller checks dtf_csv_error
  }
  r->batch_size = batch_size;
  r->cols.resize(ncols);
  for (int32_t i = 0; i < ncols; i++) {
    r->cols[i].type = col_types[i];
    r->cols[i].active = (active == nullptr) || active[i] != 0;
    if (r->cols[i].active) {
      r->cols[i].data.resize(batch_size * type_width(col_types[i]));
      r->cols[i].validity.assign(batch_size, 1);
    }
  }
  if (has_header) {
    if (!read_record(*r)) r->eof = true;  // header-only / empty file
  }
  return r;
}

const char* dtf_csv_error(void* handle) {
  auto* r = static_cast<CsvReader*>(handle);
  return r->error.empty() ? nullptr : r->error.c_str();
}

// Parse up to batch_size rows; returns row count (0 at EOF, -1 error).
int64_t dtf_csv_next(void* handle) {
  auto* r = static_cast<CsvReader*>(handle);
  if (!r->error.empty()) return -1;
  if (r->eof) return 0;
  for (auto& c : r->cols) {
    if (!c.active) continue;
    std::fill(c.validity.begin(), c.validity.end(), 1);
    c.any_null = false;
  }
  int64_t row = 0;
  while (row < r->batch_size) {
    if (!read_record(*r)) {
      if (!r->error.empty()) return -1;
      r->eof = true;
      break;
    }
    if ((int64_t)r->fields.size() != (int64_t)r->cols.size()) {
      char buf[128];
      snprintf(buf, sizeof buf, "row has %zu fields, schema has %zu",
               r->fields.size(), r->cols.size());
      r->error = buf;
      return -1;
    }
    for (size_t i = 0; i < r->cols.size(); i++) {
      Column& col = r->cols[i];
      if (!col.active) continue;
      const std::string& s = r->fields[i];
      if (s.empty() && col.type != T_UTF8) {
        col.validity[row] = 0;
        col.any_null = true;
        std::memset(col.data.data() + row * type_width(col.type), 0,
                    type_width(col.type));
        continue;
      }
      // empty utf8 field: pyarrow's strings_can_be_null treats it as
      // NULL too (matches the Python reader)
      if (s.empty() && col.type == T_UTF8) {
        col.validity[row] = 0;
        col.any_null = true;
        store<int32_t>(col, row, 0);
        continue;
      }
      if (!parse_value(col, row, s, &r->error)) return -1;
    }
    row++;
  }
  r->rows_in_batch = row;
  return row;
}

void* dtf_csv_col_data(void* handle, int32_t i) {
  return static_cast<CsvReader*>(handle)->cols[i].data.data();
}

// Returns NULL when every row in the batch is valid (no null bitmap).
uint8_t* dtf_csv_col_validity(void* handle, int32_t i) {
  auto& col = static_cast<CsvReader*>(handle)->cols[i];
  return col.any_null ? col.validity.data() : nullptr;
}

int32_t dtf_csv_dict_size(void* handle, int32_t i) {
  return (int32_t)static_cast<CsvReader*>(handle)->cols[i].dict.values.size();
}

const char* dtf_csv_dict_value(void* handle, int32_t i, int32_t j,
                               int32_t* len) {
  const std::string& s =
      static_cast<CsvReader*>(handle)->cols[i].dict.values[j];
  *len = (int32_t)s.size();
  return s.data();
}

void dtf_csv_close(void* handle) { delete static_cast<CsvReader*>(handle); }

}  // extern "C"
