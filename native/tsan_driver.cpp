// ThreadSanitizer driver for the native runtime (scripts/tsan_check.sh).
//
// The host runtime is threaded — worker handler threads, the prefetch
// producer, the pyarrow-confinement pool — and worker fragment scans
// run the native CSV reader from whatever handler thread took the
// connection (parallel/worker.py).  SURVEY §5.2 names TSan+ASan CI as
// the rebuild's answer to Rust's compile-time data-race freedom; this
// drives the exact concurrent shapes the engine uses:
//   - N threads each scanning their own reader handle over one shared
//     input file (the worker serving parallel fragment requests);
//   - N threads through the SQL front-end + plan IR round trip (the
//     parser is called from server threads too).
// Reader handles are documented single-thread-per-handle, so no handle
// is shared; what TSan checks is that the implementation has no hidden
// shared mutable state (globals, caches, errno-style buffers).

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* dtf_csv_open(const char* path, int32_t n_cols, const int32_t* types,
                   int32_t has_header, int64_t batch_size,
                   const uint8_t* projected);
const char* dtf_csv_error(void* r);
int64_t dtf_csv_next(void* r);
void* dtf_csv_col_data(void* r, int32_t col);
const uint8_t* dtf_csv_col_validity(void* r, int32_t col);
int32_t dtf_csv_dict_size(void* r, int32_t col);
void* dtf_csv_dict_value(void* r, int32_t col, int32_t code, int32_t* len);
void dtf_csv_close(void* r);
char* dtf_parse_sql(const char* sql);
char* dtf_plan_roundtrip(const char* json);
char* dtf_plan_repr(const char* json);
void dtf_free(char* p);
}

static const char* kPath = "/tmp/tsan_driver_input.csv";

static void write_input() {
  FILE* f = fopen(kPath, "w");
  assert(f);
  fprintf(f, "city,lat,flag,n\n");
  for (int i = 0; i < 20000; i++) {
    fprintf(f, "name%d,%d.%02d,%s,%d\n", i % 257, i % 90, i % 100,
            (i % 3 ? "true" : "false"), i);
  }
  fclose(f);
}

static void scan_worker(int64_t* total_rows) {
  // types: 11=Utf8, 10=Float64, 0=Boolean, 4=Int64 (native/csv.py map)
  int32_t types[4] = {11, 10, 0, 4};
  void* r = dtf_csv_open(kPath, 4, types, 1, 4096, nullptr);
  assert(r && !dtf_csv_error(r));
  int64_t rows = 0;
  for (;;) {
    int64_t n = dtf_csv_next(r);
    assert(n >= 0);
    if (n == 0) break;
    rows += n;
    // touch every column surface a real scan touches
    assert(dtf_csv_col_data(r, 0));
    assert(dtf_csv_col_data(r, 3));
    dtf_csv_col_validity(r, 1);
    int32_t len = 0;
    int32_t ds = dtf_csv_dict_size(r, 0);
    assert(ds > 0);
    assert(dtf_csv_dict_value(r, 0, ds - 1, &len));
  }
  dtf_csv_close(r);
  *total_rows = rows;
}

static void sql_worker(int reps) {
  const char* stmts[] = {
      "SELECT a, b + 1 FROM t WHERE a > 2.5 AND c = 'x'",
      "SELECT COUNT(*), MIN(x) FROM t GROUP BY z ORDER BY z LIMIT 5",
      "SELEC nonsense",  // error path from a thread
  };
  for (int i = 0; i < reps; i++) {
    for (const char* s : stmts) {
      char* out = dtf_parse_sql(s);
      assert(out);
      if (out[0] == '{' && strstr(out, "\"error\"") == nullptr) {
        char* rt = dtf_plan_roundtrip(out);
        assert(rt);
        dtf_free(rt);
        char* pr = dtf_plan_repr(out);
        assert(pr);
        dtf_free(pr);
      }
      dtf_free(out);
    }
  }
}

int main() {
  write_input();
  const int kThreads = 8;
  std::vector<std::thread> ts;
  std::vector<int64_t> rows(kThreads, 0);
  for (int i = 0; i < kThreads; i++) {
    if (i % 2 == 0)
      ts.emplace_back(scan_worker, &rows[i]);
    else
      ts.emplace_back(sql_worker, 50);
  }
  for (auto& t : ts) t.join();
  for (int i = 0; i < kThreads; i += 2) assert(rows[i] == 20000);
  std::remove(kPath);
  printf("tsan driver done\n");
  return 0;
}
