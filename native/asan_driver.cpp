// AddressSanitizer driver for the native runtime (scripts/asan_check.sh).
//
// The reference gets memory safety from Rust; the C++ rebuild gets it
// from an ASan-instrumented build of every native component, driven
// end-to-end here: the CSV reader over a generated file (all dtypes,
// quoting, nulls, dictionary growth) and the SQL front-end + plan IR
// over a statement/plan corpus including error paths.

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

extern "C" {
// CSV reader (datafusion_native.cpp)
void* dtf_csv_open(const char* path, int32_t n_cols, const int32_t* types,
                   int32_t has_header, int64_t batch_size,
                   const uint8_t* projected);
const char* dtf_csv_error(void* r);
int64_t dtf_csv_next(void* r);
void* dtf_csv_col_data(void* r, int32_t col);
const uint8_t* dtf_csv_col_validity(void* r, int32_t col);
int32_t dtf_csv_dict_size(void* r, int32_t col);
void* dtf_csv_dict_value(void* r, int32_t col, int32_t code, int32_t* len);
void dtf_csv_close(void* r);
// SQL front-end + plan IR (sql_frontend.cpp)
char* dtf_parse_sql(const char* sql);
char* dtf_plan_roundtrip(const char* json);
char* dtf_plan_repr(const char* json);
void dtf_free(char* p);
}

static void check_sql(const char* sql) {
  char* out = dtf_parse_sql(sql);
  assert(out != nullptr);
  dtf_free(out);
}

static void check_plan(const char* json) {
  char* rt = dtf_plan_roundtrip(json);
  assert(rt != nullptr);
  dtf_free(rt);
  char* pr = dtf_plan_repr(json);
  assert(pr != nullptr);
  dtf_free(pr);
}

int main() {
  // -- SQL parser: valid + invalid statements --
  const char* stmts[] = {
      "SELECT a, b + 1 AS s FROM t WHERE a > 2.5 AND b != 'x''y'",
      "SELECT COUNT(*), MIN(x) FROM t GROUP BY z HAVING COUNT(*) > 1 "
      "ORDER BY z DESC LIMIT 5",
      "CREATE EXTERNAL TABLE uk (city VARCHAR NOT NULL, lat DOUBLE) "
      "STORED AS CSV WITHOUT HEADER ROW LOCATION '/x/y.csv'",
      "EXPLAIN SELECT * FROM t",
      "SELECT CAST(a AS BIGINT), -b, a IS NOT NULL, (a+b)*2 % 3 FROM t",
      // error paths must not leak or over-read either
      "", "SELEC", "SELECT 'unterminated", "SELECT a FROM t WHERE",
      "SELECT /* unterminated", "CREATE EXTERNAL TABLE t (a NOTATYPE)",
  };
  for (const char* s : stmts) check_sql(s);

  // -- plan IR: valid + malformed wire objects --
  const char* plans[] = {
      "{\"Limit\":{\"limit\":3,\"input\":{\"Sort\":{\"expr\":[{\"Sort\":"
      "{\"expr\":{\"Column\":0},\"asc\":true}}],\"input\":{\"Selection\":"
      "{\"expr\":{\"BinaryExpr\":{\"left\":{\"Column\":1},\"op\":\"Gt\","
      "\"right\":{\"Literal\":{\"Float64\":1.5}}}},\"input\":{\"TableScan\":"
      "{\"schema_name\":\"d\",\"table_name\":\"t\",\"schema\":{\"fields\":"
      "[{\"name\":\"a\",\"data_type\":\"Int64\",\"nullable\":false},"
      "{\"name\":\"b\",\"data_type\":\"Float64\",\"nullable\":true}]},"
      "\"projection\":[0,1]}}}},\"schema\":{\"fields\":[]}}},"
      "\"schema\":{\"fields\":[]}}}",
      "{\"EmptyRelation\":{\"schema\":{\"fields\":[{\"name\":\"s\","
      "\"data_type\":{\"Struct\":[{\"name\":\"z\",\"data_type\":\"UInt16\","
      "\"nullable\":false}]},\"nullable\":false}]}}}",
      "{\"Aggregate\":{\"input\":{\"EmptyRelation\":{\"schema\":{\"fields\":[]}}},"
      "\"group_expr\":[{\"Column\":0}],\"aggr_expr\":[{\"AggregateFunction\":"
      "{\"name\":\"COUNT\",\"args\":[{\"Column\":0}],\"return_type\":\"UInt64\","
      "\"count_star\":true}}],\"schema\":{\"fields\":[]}}}",
      // malformed
      "", "{", "{\"Nope\":{}}", "{\"Selection\":{\"expr\":{\"Column\":0}}}",
      "{\"Literal\":\"Null\"}", "[1,2,", "{\"TableScan\":{}}",
  };
  for (const char* p : plans) check_plan(p);

  // -- CSV reader over a temp file --
  const char* path = "/tmp/dtf_asan_test.csv";
  FILE* f = fopen(path, "w");
  assert(f);
  fputs("b,i8,i64,u64,f64,s\n", f);
  fputs("true,1,-9223372036854775808,18446744073709551615,1.5,hello\n", f);
  fputs("false,-128,42,0,-2.25,\"qu\"\"oted, comma\"\n", f);
  fputs(",,,,,\n", f);  // all nulls
  fputs("true,127,1,2,3.5,hello\n", f);  // dict reuse
  fclose(f);
  int32_t types[] = {0, 1, 4, 8, 10, 11};  // bool,i8,i64,u64,f64,utf8
  void* r = dtf_csv_open(path, 6, types, 1, 2, nullptr);
  assert(r && dtf_csv_error(r) == nullptr);
  int64_t total = 0;
  int64_t n;
  while ((n = dtf_csv_next(r)) > 0) {
    total += n;
    for (int c = 0; c < 6; c++) {
      assert(dtf_csv_col_data(r, c) != nullptr);
      dtf_csv_col_validity(r, c);
    }
    int32_t dsz = dtf_csv_dict_size(r, 5);
    for (int32_t code = 0; code < dsz; code++) {
      int32_t len = 0;
      assert(dtf_csv_dict_value(r, 5, code, &len) != nullptr);
    }
  }
  assert(dtf_csv_error(r) == nullptr);
  assert(total == 4);
  dtf_csv_close(r);
  remove(path);

  puts("asan driver: all checks passed");
  return 0;
}
