"""Device data-plane observability (datafusion_tpu/obs/device.py):
HBM residency-ledger semantics under churn (release on buffer death,
no double-count on re-adopt, owner re-tagging), the leak detector's
two-sweep confirmation, the cold-path phase breakdown, per-table scan
histograms at the datasource boundary, lint rule DF006, and the
EXPLAIN ANALYZE phase-bar/HBM rendering — plus the
``DATAFUSION_TPU_DEVICE_LEDGER=0`` escape hatch."""

from __future__ import annotations

import gc
import os

import numpy as np
import pytest

from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.obs import aggregate, device, recorder
from datafusion_tpu.obs.device import (
    PHASE_ORDER,
    DeviceLedger,
    phase_bar,
    phase_breakdown,
    phase_ms,
    phase_snapshot,
)
from datafusion_tpu.utils.metrics import METRICS

SCHEMA = Schema(
    [
        Field("k", DataType.INT64, False),
        Field("v", DataType.FLOAT64, False),
    ]
)


def _write_csv(path, rows=256, seed=11):
    rng = np.random.default_rng(seed)
    with open(path, "w", encoding="utf-8") as f:
        f.write("k,v\n")
        for _ in range(rows):
            f.write(f"{int(rng.integers(0, 8))},{rng.uniform(-5, 5):.6f}\n")
    return str(path)


@pytest.fixture()
def ledger():
    """A fresh, isolated DeviceLedger (the process-global LEDGER keeps
    serving the engine untouched)."""
    led = DeviceLedger()
    yield led
    led.clear()


class TestLedgerChurn:
    def test_put_tracks_then_release_on_death(self, ledger):
        arr = np.arange(4096, dtype=np.float64)
        out = ledger.put(arr, None, owner="scan.t")
        assert ledger.live_bytes() == out.nbytes
        assert ledger.peak_bytes() == out.nbytes
        assert ledger.owners() == {
            "scan.t": {"bytes": out.nbytes, "buffers": 1}
        }
        # cache eviction / batch teardown = the handle dies: the
        # weakref finalizer must release the entry (and the peak
        # watermark must survive as the high-water record)
        nbytes = out.nbytes
        del out
        gc.collect()
        assert ledger.live_bytes() == 0
        assert ledger.entries == 0
        assert ledger.peak_bytes() == nbytes

    def test_readopt_does_not_double_count(self, ledger):
        # failover fragment replay / warm re-collect adopts buffers the
        # engine already tracks: attribution refreshes, bytes do not
        # double-count
        import jax.numpy as jnp

        x = jnp.arange(1024)
        ledger.adopt(x, owner="fragment.q1")
        ledger.adopt(x, owner="fragment.q1.replay")
        assert ledger.entries == 1
        assert ledger.live_bytes() == x.nbytes
        assert list(ledger.owners()) == ["fragment.q1.replay"]

    def test_retag_round_cache_owner(self, ledger):
        # a mesh round admitted into the round cache stops being
        # transient: retag moves it to the cache owner and out of the
        # leak sweep's candidate set
        import jax.numpy as jnp

        cols = (jnp.arange(512), jnp.arange(512, dtype=jnp.float32))
        ledger.adopt(cols, owner="mesh.round", cached=False)
        assert ledger.owners()["mesh.round"]["buffers"] == 2
        ledger.retag(cols, "mesh.round_cache", cached=True)
        owners = ledger.owners()
        assert "mesh.round" not in owners
        assert owners["mesh.round_cache"]["buffers"] == 2
        # cached entries never become leak candidates
        assert ledger.sweep(None, grace_s=0.0) == 0
        assert ledger.sweep(None, grace_s=0.0) == 0

    def test_peak_window_preserves_process_peak(self, ledger):
        # EXPLAIN ANALYZE / bench cold legs measure per-run peaks via a
        # WINDOW: the process-wide watermark (what scrapes and
        # fleet.hbm.peak_bytes report) must survive untouched
        big = ledger.put(np.zeros(1 << 16, np.uint8), None, owner="x")
        high = ledger.peak_bytes()
        assert high >= big.nbytes
        del big
        gc.collect()
        ledger.begin_peak_window()
        small = ledger.put(np.zeros(1 << 10, np.uint8), None, owner="y")
        assert small is not None
        assert ledger.window_peak_bytes() < high
        assert ledger.window_peak_bytes() >= 1 << 10
        assert ledger.peak_bytes() == high  # process peak intact

    def test_readopt_clears_leak_candidate(self, ledger):
        # a buffer marked as a leak candidate by one sweep that is then
        # re-adopted (fragment replay) was just proven in use: the
        # refresh must clear candidacy, not let a later sweep report it
        import jax.numpy as jnp

        x = jnp.arange(512)
        ledger.adopt(x, owner="fragment.q1", cached=False)
        assert ledger.sweep(None, grace_s=0.0) == 0  # marks candidate
        ledger.adopt(x, owner="fragment.q1.replay", cached=False)
        assert ledger.sweep(None, grace_s=0.0) == 0  # re-marks, no report
        assert ledger.leaks_reported == 0

    def test_transfer_profiles_without_residency(self, ledger):
        arr = np.arange(2048, dtype=np.int32)
        before = METRICS.counts.get("h2d.dispatch", 0)  # timing key
        out = ledger.transfer(arr, None)
        assert out is not None
        assert ledger.entries == 0  # transient: profiled, not resident

    def test_transfer_profile_false_is_silent(self, ledger):
        # the mesh stacker's fan-out arm: dispatch without blocking or
        # recording — no flight event, no timer accrual; the caller
        # times the batch and records ONE note_h2d
        recorder.clear()
        before_t = METRICS.timings.get("h2d.dispatch", 0.0)
        out = ledger.transfer(np.arange(1024), None, profile=False)
        assert out is not None
        assert METRICS.timings.get("h2d.dispatch", 0.0) == before_t
        assert not [
            e for e in recorder.events() if e["kind"] == "device.h2d"
        ]
        ledger.note_h2d(out.nbytes, 0.001)
        assert METRICS.timings.get("h2d.dispatch", 0.0) > before_t
        events = [
            e for e in recorder.events() if e["kind"] == "device.h2d"
        ]
        assert len(events) == 1 and events[0]["attrs"]["bytes"] == out.nbytes

    def test_leak_detector_two_sweep_confirmation(self, ledger):
        import jax.numpy as jnp

        leaked = ledger.adopt(jnp.arange(256), owner="anon", cached=False)
        recorder.clear()
        before = ledger.leaks_reported
        # sweep 1 marks the candidate, never reports
        assert ledger.sweep(None, grace_s=0.0) == 0
        # sweep 2 past the grace reports it, exactly once
        assert ledger.sweep(None, grace_s=0.0) == 1
        assert ledger.sweep(None, grace_s=0.0) == 0
        assert ledger.leaks_reported == before + 1
        leaks = [e for e in recorder.events() if e["kind"] == "device.leak"]
        assert len(leaks) == 1
        assert leaks[0]["attrs"]["bytes"] == leaked.nbytes

    def test_sweep_scopes_to_completing_trace(self, ledger):
        import jax.numpy as jnp

        e = ledger.adopt(jnp.arange(64), owner="anon", cached=False)
        assert e is not None
        tok = next(iter(ledger._entries))
        ledger._entries[tok].trace_id = "trace-a"
        # a different query completing must not candidate trace-a's
        # buffers
        assert ledger.sweep("trace-b", grace_s=0.0) == 0
        assert ledger.sweep("trace-b", grace_s=0.0) == 0
        # its own completion does
        assert ledger.sweep("trace-a", grace_s=0.0) == 0
        assert ledger.sweep("trace-a", grace_s=0.0) == 1

    def test_untraced_sweep_skips_traced_queries_buffers(self, ledger):
        # an UNTRACED query completing (trace_id None) must not
        # candidate a concurrent traced query's in-flight buffers —
        # only trace-less ones are in scope
        import jax.numpy as jnp

        traced = ledger.adopt(jnp.arange(64), owner="anon", cached=False)
        assert traced is not None
        tok = next(iter(ledger._entries))
        ledger._entries[tok].trace_id = "trace-running"
        assert ledger.sweep(None, grace_s=0.0) == 0
        assert ledger.sweep(None, grace_s=0.0) == 0  # still no report
        assert ledger.leaks_reported == 0

    def test_put_events_claim_gbps_only_under_profile_sync(self, ledger):
        # async production put: dispatch-only wall, no GB/s claim;
        # profiled put (EXPLAIN ANALYZE / bench cold legs): blocked on
        # completion, true achieved GB/s vs the link baseline
        recorder.clear()
        out1 = ledger.put(np.arange(512), None, owner="x")
        assert out1 is not None
        with device.profile_sync():
            out2 = ledger.put(np.arange(512, dtype=np.int64), None,
                              owner="x")
            assert out2 is not None
        ev = [e for e in recorder.events() if e["kind"] == "device.h2d"]
        assert len(ev) == 2
        assert ev[0]["attrs"].get("dispatch_only") is True
        assert "gbps" not in ev[0]["attrs"]
        assert "gbps" in ev[1]["attrs"]
        assert "dispatch_only" not in ev[1]["attrs"]

    def test_put_of_device_array_is_residency_not_h2d(self, ledger):
        # device-resident input = reshard/placement (mesh state
        # distribution), not a host->device transfer: tracked, but no
        # device.h2d event and no h2d.dispatch accrual
        import jax.numpy as jnp

        dev = jnp.arange(1024)
        recorder.clear()
        before = METRICS.timings.get("h2d.dispatch", 0.0)
        out = ledger.put(dev, None, owner="mesh.state")
        assert out is not None
        assert ledger.entries == 1
        assert METRICS.timings.get("h2d.dispatch", 0.0) == before
        assert not [
            e for e in recorder.events() if e["kind"] == "device.h2d"
        ]

    def test_disabled_ledger_is_a_bare_device_put(self, ledger):
        saved = device._ENABLED
        device.configure(enabled=False)
        try:
            out = ledger.put(np.arange(128), None, owner="x")
            assert hasattr(out, "copy_to_host_async")
            assert ledger.entries == 0
            assert ledger.adopt(out, owner="x") is out
            assert ledger.sweep(None) == 0
        finally:
            device.configure(enabled=saved)

    def test_report_text_renders(self, ledger):
        held = ledger.put(
            np.arange(1000, dtype=np.float64), None, owner="scan.t"
        )
        assert held is not None  # the live handle keeps the entry live
        text = ledger.report_text()
        assert "live" in text and "peak" in text
        assert "scan.t" in text


class TestQueryIntegration:
    def test_query_tracks_and_gc_frees(self, tmp_path):
        from datafusion_tpu.obs.device import LEDGER

        path = _write_csv(tmp_path / "t.csv")
        LEDGER.clear()
        ctx = ExecutionContext()
        ctx.register_csv("t", path, SCHEMA, has_header=True)
        out = collect(ctx.sql("SELECT k, SUM(v) FROM t GROUP BY k"))
        assert out.num_rows == 8
        assert LEDGER.peak_bytes() > 0
        # engine teardown releases every tracked buffer
        del ctx, out
        gc.collect()
        assert LEDGER.live_bytes() == 0

    def test_launch_tags_decompose_launches(self, tmp_path):
        path = _write_csv(tmp_path / "t.csv")
        ctx = ExecutionContext()
        ctx.register_csv("t", path, SCHEMA, has_header=True)
        before = {
            k: v for k, v in METRICS.counts.items()
            if k.startswith("device.launches.")
        }
        collect(ctx.sql("SELECT k, SUM(v) FROM t GROUP BY k"))
        tagged = {
            k: v - before.get(k, 0)
            for k, v in METRICS.counts.items()
            if k.startswith("device.launches.") and v > before.get(k, 0)
        }
        assert any(k.startswith("device.launches.agg") for k in tagged), (
            tagged
        )

    def test_explain_analyze_renders_phases_and_hbm(self, tmp_path):
        path = _write_csv(tmp_path / "t.csv")
        ctx = ExecutionContext()
        ctx.register_csv("t", path, SCHEMA, has_header=True)
        res = ctx.sql_collect(
            "EXPLAIN ANALYZE SELECT k, SUM(v) FROM t GROUP BY k"
        )
        assert set(res.phases) == set(PHASE_ORDER)
        assert res.hbm["peak_bytes"] > 0
        report = res.report()
        assert "Phases: " in report
        assert "HBM: peak " in report

    def test_explain_analyze_disabled_ledger_skips_device_lines(
            self, tmp_path):
        path = _write_csv(tmp_path / "t.csv")
        ctx = ExecutionContext()
        ctx.register_csv("t", path, SCHEMA, has_header=True)
        saved = device._ENABLED
        device.configure(enabled=False)
        try:
            res = ctx.sql_collect(
                "EXPLAIN ANALYZE SELECT k, SUM(v) FROM t GROUP BY k"
            )
        finally:
            device.configure(enabled=saved)
        assert res.phases == {} and res.hbm == {}
        report = res.report()
        assert "Phases: " not in report
        assert "HBM: peak " not in report

    def test_metrics_text_exposes_hbm_and_scan_histograms(self, tmp_path):
        from datafusion_tpu.obs.device import LEDGER

        path = _write_csv(tmp_path / "t.csv")
        aggregate.reset_histograms()
        ctx = ExecutionContext()
        ctx.register_csv("t", path, SCHEMA, has_header=True)
        collect(ctx.sql("SELECT k, SUM(v) FROM t GROUP BY k"))
        LEDGER.live_bytes()  # refresh the gauges
        text = ctx.metrics_text()
        assert 'name="device.hbm.live_bytes"' in text
        assert 'name="device.hbm.peak_bytes"' in text
        assert 'name="scan.t.latency.count"' in text
        assert 'name="scan.t.bytes.p50"' in text

    def test_flight_event_carries_phases(self, tmp_path):
        # a completed root query's flight event records the phase
        # breakdown (the slow-query artifact copies the same dict)
        path = _write_csv(tmp_path / "t.csv")
        recorder.clear()
        ctx = ExecutionContext()
        ctx.register_csv("t", path, SCHEMA, has_header=True)
        collect(ctx.sql("SELECT k, SUM(v) FROM t GROUP BY k"))
        done = [e for e in recorder.events() if e["kind"] == "query.done"]
        assert done, [e["kind"] for e in recorder.events()]
        phases = done[-1]["attrs"].get("phases")
        assert phases is not None and set(phases) == set(PHASE_ORDER)


class TestScanHistograms:
    def test_observe_scan_geometry(self):
        aggregate.reset_histograms()
        aggregate.observe_scan("lineitem", 0.25, 1 << 20)
        lat = aggregate.HISTOGRAMS["scan.lineitem.latency"]
        by = aggregate.HISTOGRAMS["scan.lineitem.bytes"]
        assert lat.count == 1 and by.count == 1
        assert by.base == 1.0 and by.nbuckets == 48
        # a byte-geometry quantile answers in bytes, not seconds
        q = by.quantile(0.5)
        assert q is not None and q >= 1 << 20

    def test_bytes_histograms_merge_fleet_wide(self):
        aggregate.reset_histograms()
        aggregate.observe_scan("t", 0.01, 4096)
        snap = aggregate.node_snapshot()
        agg = aggregate.FleetAggregator(include_local=False)
        agg.ingest("w1", snap)
        agg.ingest("w2", dict(snap, ts=snap["ts"]))
        fleet = agg.fleet()
        merged = fleet["histograms"]["scan.t.bytes"]
        # geometry survives the snapshot round trip: same base/buckets
        assert merged.base == 1.0
        assert merged.count == 2
        gauges = agg.gauges()
        assert gauges["fleet.scan.t.bytes.count"] == 2
        assert "fleet.scan.t.latency.p50_s" in gauges

    def test_fleet_hbm_sums_across_nodes(self):
        snap = {
            "ts": __import__("time").time(),
            "histograms": {},
            "counts": {},
            "gauges": {"device.hbm.live_bytes": 100,
                       "device.hbm.peak_bytes": 250},
        }
        agg = aggregate.FleetAggregator(include_local=False)
        agg.ingest("w1", snap)
        agg.ingest("w2", dict(snap))
        gauges = agg.gauges()
        assert gauges["fleet.hbm.live_bytes"] == 200
        assert gauges["fleet.hbm.peak_bytes"] == 500


class TestPhaseBreakdown:
    def test_profile_sync_scopes_and_launch_works_inside(self):
        # profile-sync is the opt-in "block launches for phase-accurate
        # execute timing" mode used by EXPLAIN ANALYZE and bench cold
        # legs; it must nest, scope, and leave device_call functional
        import jax.numpy as jnp

        from datafusion_tpu.utils.retry import device_call

        assert not device.profile_sync_active()
        with device.profile_sync():
            assert device.profile_sync_active()
            with device.profile_sync():  # nests
                assert device.profile_sync_active()
                out = device_call(lambda: jnp.arange(8) * 2, _tag="test")
                assert int(out[3]) == 6
            assert device.profile_sync_active()
        assert not device.profile_sync_active()
        # disabled ledger keeps the mode off even inside the context
        saved = device._ENABLED
        device.configure(enabled=False)
        try:
            with device.profile_sync():
                assert not device.profile_sync_active()
        finally:
            device.configure(enabled=saved)

    def test_breakdown_math(self):
        before = phase_snapshot()
        METRICS.observe("scan.parse", 0.10)
        METRICS.observe("h2d.dispatch", 0.05)
        METRICS.observe("compile.xla", 0.02)
        METRICS.observe("device.dispatch", 0.08)
        METRICS.observe("d2h.wait", 0.03)
        phases = phase_breakdown(before, wall_s=0.40)
        assert phases["decode"] == pytest.approx(0.10)
        assert phases["h2d"] == pytest.approx(0.05)
        assert phases["compile"] == pytest.approx(0.02)
        # compile splits OUT of the dispatch wall
        assert phases["execute"] == pytest.approx(0.06)
        assert phases["d2h"] == pytest.approx(0.03)
        # other = wall - accounted (host merge, planning, assembly)
        assert phases["other"] == pytest.approx(0.40 - 0.26)
        ms = phase_ms(phases)
        assert ms["decode"] == pytest.approx(100.0)

    def test_bar_renders_proportional(self):
        phases = {"decode": 0.5, "h2d": 0.25, "execute": 0.25,
                  "compile": 0.0, "d2h": 0.0, "other": 0.0}
        bar = phase_bar(phases, wall_s=1.0)
        assert "decode" in bar and "50%" in bar
        assert "h2d" in bar and "25%" in bar
        # zero phases stay out of the line
        assert "compile" not in bar

    def test_bar_empty(self):
        assert phase_bar({}, 1.0) == "(no phases recorded)"

    def test_disabled_ledger_yields_no_phases(self):
        # with the ledger off, h2d.dispatch never accrues — a rendered
        # bar would silently fold H2D into "other", so the phase
        # functions return empty and consumers skip the line
        saved = device._ENABLED
        device.configure(enabled=False)
        try:
            assert phase_snapshot() == {}
            assert phase_breakdown(None, 1.0) == {}
        finally:
            device.configure(enabled=saved)


class TestHbmPressureSlo:
    def test_hbm_frac_burn_and_breach(self, monkeypatch):
        from datafusion_tpu.obs import slo
        from datafusion_tpu.obs.device import LEDGER

        monkeypatch.setenv("DATAFUSION_TPU_HBM_BYTES", str(1 << 20))
        wd = slo.SloWatchdog(capture_on_breach=False)
        wd.add(slo.Objective("pressure", "hbm_frac", 0.5))
        LEDGER.clear()
        held = LEDGER.put(np.zeros(1 << 17, np.uint8), None, owner="x")
        row = wd.evaluate()[0]
        assert row["kind"] == "hbm_frac"
        # 128KiB live of a 1MiB device, 50% allowed -> burn 0.25
        assert row["burn_rate"] == pytest.approx(0.25, rel=0.05)
        assert not row["breached"]
        held2 = LEDGER.put(np.zeros(1 << 19, np.uint8), None, owner="x")
        row = wd.evaluate()[0]
        assert row["breached"] and row["burn_rate"] >= 1.0
        assert held is not None and held2 is not None
        LEDGER.clear()

    def test_disabled_ledger_keeps_hbm_objective_dormant(self, monkeypatch):
        # with DATAFUSION_TPU_DEVICE_LEDGER=0 nothing registers, so
        # live_bytes()=0 must not read as a confidently healthy device
        from datafusion_tpu.obs import slo

        monkeypatch.setenv("DATAFUSION_TPU_HBM_BYTES", str(1 << 20))
        saved = device._ENABLED
        device.configure(enabled=False)
        try:
            wd = slo.SloWatchdog(capture_on_breach=False)
            wd.add(slo.Objective("pressure", "hbm_frac", 0.5))
            row = wd.evaluate()[0]
            assert row["samples"] == 0 and not row["breached"]
            # ...and a ledger-off node publishes NO hbm gauges for the
            # fleet to sum as measured zeros
            snap = aggregate.node_snapshot()
            assert not any(
                k.startswith("device.hbm.") for k in snap["gauges"]
            )
        finally:
            device.configure(enabled=saved)

    def test_capacity_sums_local_devices(self, monkeypatch):
        # ledger live bytes span ALL local devices (the mesh shards
        # across them), so capacity must too — dividing by one chip
        # would over-report pressure N-fold on an N-device host
        import jax

        from datafusion_tpu.obs import device as obs_device

        monkeypatch.delenv("DATAFUSION_TPU_HBM_BYTES", raising=False)

        class _Dev:
            def __init__(self, limit):
                self._limit = limit

            def memory_stats(self):
                return {"bytes_limit": self._limit}

        monkeypatch.setattr(jax, "devices", lambda: [_Dev(1 << 30)] * 4)
        assert obs_device.hbm_capacity_bytes() == 4 * (1 << 30)

        class _Opaque:
            def memory_stats(self):
                return None

        # one device hiding its stats -> unknown total, stay dormant
        monkeypatch.setattr(
            jax, "devices", lambda: [_Dev(1 << 30), _Opaque()]
        )
        assert obs_device.hbm_capacity_bytes() is None

    def test_unknown_capacity_stays_dormant(self, monkeypatch):
        from datafusion_tpu.obs import device as obs_device
        from datafusion_tpu.obs import slo

        monkeypatch.delenv("DATAFUSION_TPU_HBM_BYTES", raising=False)
        monkeypatch.setattr(obs_device, "hbm_capacity_bytes", lambda: None)
        wd = slo.SloWatchdog(capture_on_breach=False)
        wd.add(slo.Objective("pressure", "hbm_frac", 0.5))
        row = wd.evaluate()[0]
        assert row["burn_rate"] == 0.0 and not row["breached"]
        assert row["samples"] == 0

    def test_env_declaration(self):
        from datafusion_tpu.obs import slo

        objs = slo.objectives_from_env(
            {"DATAFUSION_TPU_SLO_PRESSURE_HBM_FRAC": "0.8"}
        )
        assert [(o.name, o.kind, o.threshold) for o in objs] == [
            ("pressure", "hbm_frac", 0.8)
        ]


class TestLintDF006:
    def test_raw_device_put_is_a_finding(self):
        from datafusion_tpu.analysis.lint import lint_source

        src = "import jax\n\ndef f(a):\n    return jax.device_put(a)\n"
        findings = lint_source(src, "datafusion_tpu/exec/foo.py")
        assert any(f.rule == "DF006" for f in findings), findings

    def test_alias_reference_is_a_finding(self):
        from datafusion_tpu.analysis.lint import lint_source

        src = "import jax\nput = jax.device_put\n"
        findings = lint_source(src, "datafusion_tpu/exec/foo.py")
        assert any(f.rule == "DF006" for f in findings), findings

    def test_device_module_and_suppression_exempt(self):
        from datafusion_tpu.analysis.lint import lint_source

        src = "import jax\n\ndef f(a):\n    return jax.device_put(a)\n"
        assert not [
            f for f in lint_source(src, "datafusion_tpu/obs/device.py")
            if f.rule == "DF006"
        ]
        suppressed = (
            "import jax\n\ndef f(a):\n"
            "    return jax.device_put(a)  # df-lint: ok(DF006) — probe\n"
        )
        assert not [
            f for f in lint_source(suppressed, "datafusion_tpu/exec/foo.py")
            if f.rule == "DF006"
        ]

    def test_repo_is_df006_clean(self):
        from datafusion_tpu.analysis.lint import RawDevicePut, lint_paths

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        findings = lint_paths(
            [os.path.join(repo, "datafusion_tpu")], rules=[RawDevicePut()]
        )
        assert findings == [], findings
