"""Multi-tenant QoS (datafusion_tpu/qos): weighted fair-share
admission, per-tenant isolation budgets, pin-aware placement, and the
elastic-capacity hint.

The overload contract under test:
- weighted fair drain: a share-3 tenant advances 3 queries per
  share-1 query while both have backlog; deadline urgency reorders
  only WITHIN a tenant, never across the fair queue;
- shed-over-quota: at queue-full the tenant furthest over its share
  pays — its newest / least-urgent queued ticket sheds with the
  dedicated ``quota`` reason, and conservation
  (admitted + shed == submitted) still holds;
- isolation budgets: a tenant that exhausted its own retry/hedge
  child bucket is denied WITHOUT the global bucket being consulted
  or drained;
- pin-aware placement: queries route to advertised pin-holders, and
  a saturated holder set replicates onto spare capacity;
- default-off: with ``DATAFUSION_TPU_QOS`` unset and no shares, the
  admission path drains byte-identical FIFO (A/B asserted).
"""

from __future__ import annotations

import os
import types

import pytest

from datafusion_tpu import qos
from datafusion_tpu.obs import attribution
from datafusion_tpu.obs.attribution import METER
from datafusion_tpu.utils.deadline import Deadline
from datafusion_tpu.utils.hedge import HedgeTracker
from datafusion_tpu.utils.metrics import METRICS
from datafusion_tpu.utils.retry import RetryBudget


@pytest.fixture(autouse=True)
def _clean_tenant_state():
    """Tests own the process-global meters and the QoS env knobs."""
    prior = {
        k: os.environ.pop(k, None)
        for k in ("DATAFUSION_TPU_QOS", "DATAFUSION_TPU_QOS_SHARES",
                  "DATAFUSION_TPU_HBM_BYTES")
    }
    attribution.reset_for_tests()
    yield
    attribution.reset_for_tests()
    for k, v in prior.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


class _T:
    """A ticket stub: exactly the attributes the policy reads."""

    def __init__(self, cid: str, seq: float, deadline=None):
        self.client_id = cid
        self.deadline = deadline
        self.entry_mono = float(seq)


def _clients(tickets) -> list:
    return [t.client_id for t in tickets]


class TestFairShareOrdering:
    def test_weighted_drain_is_proportional(self):
        """Shares a=3, b=1 with an alternating backlog must drain 3
        of a's queries per b query — a,a,b,a,b,b, not strict priority
        and not FIFO."""
        pol = qos.FairSharePolicy({"a": 3.0, "b": 1.0})
        backlog = [_T("a", 0), _T("b", 1), _T("a", 2),
                   _T("b", 3), _T("a", 4), _T("b", 5)]
        got = pol.order(backlog, unit_cost_s=1.0, attained={})
        assert _clients(got) == ["a", "a", "b", "a", "b", "b"]

    def test_attained_service_pushes_tenant_back(self):
        """Equal shares, but tenant b already consumed 10s of service:
        a's whole backlog drains before b advances at all."""
        pol = qos.FairSharePolicy({"a": 1.0, "b": 1.0})
        backlog = [_T("b", 0), _T("a", 1), _T("b", 2), _T("a", 3)]
        got = pol.order(backlog, unit_cost_s=0.001,
                        attained={"a": 0.0, "b": 10.0})
        assert _clients(got) == ["a", "a", "b", "b"]

    def test_deadline_urgency_reorders_within_tenant_only(self):
        """A tight deadline moves a query ahead of its OWN tenant's
        backlog, but cannot jump an over-quota tenant past the fair
        queue."""
        pol = qos.FairSharePolicy({"a": 1.0, "b": 1.0})
        tight = _T("a", 2, deadline=Deadline.after(0.05))
        loose = _T("a", 0, deadline=Deadline.after(10.0))
        got = pol.order([loose, _T("a", 1), tight],
                        unit_cost_s=1.0, attained={})
        assert got[0] is tight
        # cross-tenant: b is 10s over quota; its tight deadlines do
        # NOT beat a's deadline-free backlog
        got = pol.order(
            [_T("b", 0, deadline=Deadline.after(0.01)),
             _T("a", 1), _T("b", 2, deadline=Deadline.after(0.01))],
            unit_cost_s=0.001, attained={"a": 0.0, "b": 10.0})
        assert _clients(got) == ["a", "b", "b"]

    def test_singleton_and_fifo_stability(self):
        pol = qos.FairSharePolicy()
        only = [_T("a", 0)]
        assert pol.order(only, attained={}) == only
        # equal shares, equal attained, no deadlines: arrival order
        backlog = [_T(f"c{i}", i) for i in range(5)]
        assert _clients(pol.order(backlog, attained={})) == \
            [f"c{i}" for i in range(5)]


class TestShedVictim:
    def test_over_quota_tenants_newest_ticket_pays(self):
        pol = qos.FairSharePolicy({"a": 1.0, "b": 1.0})
        METER.charge("b", "device_seconds", 100.0)
        b_old, b_new = _T("b", 1.0), _T("b", 2.0)
        victim, incoming_is_victim = pol.shed_victim(
            [b_old, _T("a", 0.5), b_new], incoming_client="a")
        assert not incoming_is_victim
        assert victim is b_new  # newest of the over-quota tenant

    def test_incoming_over_quota_tenant_sheds_itself(self):
        pol = qos.FairSharePolicy({"a": 1.0, "b": 1.0})
        METER.charge("b", "device_seconds", 100.0)
        victim, incoming_is_victim = pol.shed_victim(
            [_T("a", 0.5)], incoming_client="b")
        assert incoming_is_victim and victim is None

    def test_least_urgent_sheds_first_within_tenant(self):
        pol = qos.FairSharePolicy()
        METER.charge("b", "device_seconds", 100.0)
        urgent = _T("b", 2.0, deadline=Deadline.after(0.05))
        lazy = _T("b", 1.0, deadline=Deadline.after(60.0))
        victim, _ = pol.shed_victim([urgent, lazy], incoming_client="a")
        assert victim is lazy


class TestTenantBuckets:
    def test_child_denial_never_drains_global(self):
        """Shares a=1, b=7 over parent burst 8: a's child holds
        exactly one token.  Its second spend is denied by the CHILD
        while the global reserve is untouched — and b still spends."""
        tb = qos.TenantBuckets(1.0, 8.0, {"a": 1.0, "b": 7.0})
        budget = RetryBudget(1.0, 8.0, tenant_buckets=tb)
        for _ in range(5):
            budget.earn(client="a")  # global 1+5 -> 6; child a capped at 1
        assert budget.spend(client="a") is True    # global 6 -> 5
        assert budget.tenant_tokens("a") == 0.0
        assert budget.spend(client="a") is False   # child empty: denied
        assert budget.tokens == 5.0                # ... global untouched
        assert budget.spend(client="b") is True    # b's own budget intact
        assert METER.snapshot()["a"]["retry_denied"] == 1.0

    def test_hedge_tenant_denial(self):
        before = METRICS.counts.get("hedge.tenant_denied", 0)
        tb = qos.TenantBuckets(0.25, 4.0, {"a": 1.0, "b": 1.0})
        tracker = HedgeTracker(ratio=0.25, burst=4.0, tenant_buckets=tb)
        assert tracker.try_hedge(client="a") is True   # the initial token
        assert tracker.try_hedge(client="a") is False  # child exhausted
        assert METRICS.counts.get("hedge.tenant_denied", 0) == before + 1
        assert METER.snapshot()["a"]["hedge_denied"] == 1.0
        # b's child is intact; after real traffic re-earns the GLOBAL
        # reserve (a's denial never drained it), b still hedges
        for _ in range(4):
            tracker.observe_dispatch(client="b")
        assert tracker.try_hedge(client="b") is True   # isolation held

    def test_global_denial_refunds_child(self):
        tb = qos.TenantBuckets(0.0, 4.0, {"a": 1.0})
        # global bucket starts with its single initial token
        budget = RetryBudget(0.0, 4.0, tenant_buckets=tb)
        assert budget.spend(client="a") is True   # global 1 -> 0
        budget.earn(client="a")                   # ratio 0: child refills? no
        tb._bucket("a")._tokens = 1.0             # re-arm the child only
        assert budget.spend(client="a") is False  # global empty
        assert tb.tokens("a") == 1.0              # child token refunded

    def test_overflow_fold_caps_cardinality(self):
        tb = qos.TenantBuckets(1.0, 8.0)
        before = METRICS.counts.get("qos.tenant_bucket_overflow", 0)
        for i in range(qos._MAX_TENANT_BUCKETS + 3):
            tb.earn(f"t{i}")
        assert len(tb._buckets) <= qos._MAX_TENANT_BUCKETS + 1
        assert METRICS.counts.get("qos.tenant_bucket_overflow", 0) > before
        assert qos._OVERFLOW in tb._buckets

    def test_off_by_default(self):
        assert qos.tenant_buckets_from_env(0.25, 4.0) is None
        assert qos.policy_from_config(None) is None
        assert RetryBudget(0.25)._tenants is None


class TestConfig:
    def test_parse_shares(self):
        assert qos.parse_shares("a=3, b=1") == {"a": 3.0, "b": 1.0}
        assert qos.parse_shares("") == {}
        assert qos.parse_shares(None) == {}
        assert qos.parse_shares("solo") == {"solo": 1.0}  # bare = share 1
        assert qos.parse_shares("x=notanum") == {}

    def test_env_arming(self, monkeypatch):
        monkeypatch.setenv("DATAFUSION_TPU_QOS", "1")
        monkeypatch.setenv("DATAFUSION_TPU_QOS_SHARES", "a=3,b=1")
        pol = qos.policy_from_config()
        assert pol is not None and pol.share("a") == 3.0
        assert qos.tenant_buckets_from_env(0.25, 4.0) is not None

    def test_explicit_shares_arm_without_env(self):
        pol = qos.policy_from_config({"a": 2.0})
        assert pol is not None and pol.share("a") == 2.0


class TestScaleHint:
    def test_truth_table(self):
        assert qos.scale_hint(None, 0.9) == 0     # no evidence: hold
        assert qos.scale_hint(1.2, 0.8) == 1      # burning + queue-bound
        assert qos.scale_hint(1.2, 0.1) == 0      # burning, compute-bound
        assert qos.scale_hint(0.05, 0.1) == -1    # idle everywhere
        assert qos.scale_hint(0.5, 0.2) == 0      # steady

    def test_max_burn_rate(self):
        from datafusion_tpu.obs import slo

        assert slo.max_burn_rate(rows=[]) is None
        rows = [{"burn_rate": 0.2}, {"burn_rate": 1.7}, {}]
        assert slo.max_burn_rate(rows=rows) == 1.7
        if not slo.WATCHDOG.armed():
            assert slo.max_burn_rate() is None  # unarmed: no evidence

    def test_queue_wait_share(self):
        assert attribution.queue_wait_share() == 0.0
        attribution.EXPLAINER.observe(
            1.0, {"queue_wait": 0.8, "launch_wall": 0.2})
        share = attribution.queue_wait_share()
        assert 0.7 < share <= 0.8

    def test_debug_snapshot_shape(self):
        doc = qos.debug_snapshot(qos.FairSharePolicy({"a": 2.0}))
        assert doc["shares"] == {"a": 2.0}
        assert set(doc["scale"]) == \
            {"hint", "max_burn_rate", "queue_wait_share"}


class _FakeWorker:
    def __init__(self, host, port):
        self.host, self.port = host, port


class _Frag:
    def __init__(self, names):
        self._names = names

    def table_names(self):
        return self._names


def _placement(workers_info, frag, live):
    """Drive `_pin_placement` with stub membership/fragments — the
    decision logic needs only the view's workers dict."""
    from datafusion_tpu.parallel.coordinator import DistributedContext

    view = types.SimpleNamespace(workers=workers_info)
    coord = types.SimpleNamespace(membership=view)
    return DistributedContext._pin_placement(coord, frag, live)


class TestPinPlacement:
    def test_routes_to_pin_holder(self):
        before = METRICS.counts.get("coord.pin_routed", 0)
        w1, w2 = _FakeWorker("h1", 1), _FakeWorker("h2", 2)
        info = {"h1:1": {"pins": ["table:other"]},
                "h2:2": {"pins": ["table:t"],
                         "hbm_headroom_bytes": 1 << 20}}
        got = _placement(info, _Frag(["t"]), [w1, w2])
        assert got is w2
        assert METRICS.counts.get("coord.pin_routed", 0) == before + 1

    def test_saturated_holders_replicate_to_spare(self):
        before = METRICS.counts.get("coord.pin_replicated", 0)
        holder = _FakeWorker("h1", 1)
        spare = _FakeWorker("h2", 2)
        info = {"h1:1": {"pins": ["table:t"], "hbm_headroom_bytes": 0},
                "h2:2": {"pins": [], "hbm_headroom_bytes": 1 << 20}}
        got = _placement(info, _Frag(["t"]), [holder, spare])
        assert got is spare
        assert METRICS.counts.get("coord.pin_replicated", 0) == before + 1

    def test_everyone_saturated_falls_back_to_holder(self):
        holder = _FakeWorker("h1", 1)
        spare = _FakeWorker("h2", 2)
        info = {"h1:1": {"pins": ["table:t"], "hbm_headroom_bytes": 0},
                "h2:2": {"pins": [], "hbm_headroom_bytes": 0}}
        assert _placement(info, _Frag(["t"]), [holder, spare]) is holder

    def test_no_holders_is_advisory_none(self):
        w = _FakeWorker("h1", 1)
        assert _placement({"h1:1": {"pins": []}}, _Frag(["t"]), [w]) is None
        assert _placement({}, _Frag(["t"]), [w]) is None
        assert _placement({"h1:1": {"pins": ["table:t"]}},
                          _Frag([]), [w]) is None

    def test_unknown_headroom_counts_as_headroom(self):
        w = _FakeWorker("h1", 1)
        assert _placement({"h1:1": {"pins": ["table:t"]}},
                          _Frag(["t"]), [w]) is w


class TestPinAdvertisement:
    def _harness(self):
        from datafusion_tpu.cluster import ClusterState, LocalClusterClient
        from datafusion_tpu.cluster.agent import WorkerClusterAgent

        class _WS:
            batch_size = 4
            fragment_cache = None
            pins = ["table:hot"]

            def pinned_fingerprints(self):
                return list(self.pins)

        client = LocalClusterClient(ClusterState())
        ws = _WS()
        agent = WorkerClusterAgent(client, "w:1", ws, ttl_s=30.0)
        return client, ws, agent

    def test_lease_value_untouched_when_off(self):
        client, ws, agent = self._harness()
        agent.poll_once()
        info = client.membership()["workers"]["w:1"]
        assert "pins" not in info

    def test_pins_ride_lease_and_reput_on_change(self, monkeypatch):
        monkeypatch.setenv("DATAFUSION_TPU_QOS", "1")
        client, ws, agent = self._harness()
        agent.poll_once()
        assert client.membership()["workers"]["w:1"]["pins"] == \
            ["table:hot"]
        before = METRICS.counts.get("worker.pins_readvertised", 0)
        agent.poll_once()  # unchanged pin set: no re-put
        assert METRICS.counts.get("worker.pins_readvertised", 0) == before
        ws.pins = ["table:hot", "table:warm"]
        agent.poll_once()  # changed: re-put within one heartbeat
        assert METRICS.counts.get("worker.pins_readvertised", 0) == \
            before + 1
        assert client.membership()["workers"]["w:1"]["pins"] == \
            ["table:hot", "table:warm"]

    def test_cluster_gauge_counts_advertised_pins(self, monkeypatch):
        monkeypatch.setenv("DATAFUSION_TPU_QOS", "1")
        client, ws, agent = self._harness()
        agent.poll_once()
        assert client.state.gauges()["cluster.pins_advertised"] >= 1


class TestServingIntegration:
    """End-to-end over a real `Server` (CPU execution path)."""

    def _ctx(self):
        from tests.test_serve import _ctx, _table

        return _ctx({"t": _table(7)})

    def _record_order(self, ctx, order: list):
        """Shadow `ctx.execute` on the instance: `_run_group` executes
        tickets in drained-window order under each ticket's client
        scope, so the recorded scopes ARE the admission drain order."""
        orig = ctx.execute
        depth = [0]  # execute() recurses into sub-plans: record top-level only

        def recording(plan):
            if depth[0] == 0:
                order.append(attribution.current_client())
            depth[0] += 1
            try:
                return orig(plan)
            finally:
                depth[0] -= 1

        ctx.execute = recording

    def test_fifo_byte_identical_when_off(self):
        from tests.test_serve import _q

        ctx = self._ctx()
        # a skewed meter that WOULD reorder under QoS must not matter
        METER.charge("c0", "device_seconds", 100.0)
        order: list = []
        self._record_order(ctx, order)
        srv = ctx.serve(workers=1, window_s=0.25, megabatch_max=32)
        try:
            assert srv._qos is None
            tickets = [srv.submit(_q("t", 0.3 + 0.01 * i),
                                  client_id=f"c{i}") for i in range(6)]
            for t in tickets:
                t.result(timeout=60)
        finally:
            srv.stop()
        assert order == [f"c{i}" for i in range(6)]  # pure arrival FIFO
        assert srv.admitted + srv.shed == srv.submitted

    def test_fair_drain_pushes_heavy_tenant_back(self):
        from tests.test_serve import _q

        ctx = self._ctx()
        METER.charge("hog", "device_seconds", 100.0)
        order: list = []
        self._record_order(ctx, order)
        srv = ctx.serve(workers=1, window_s=0.5, megabatch_max=32,
                        shares={"hog": 1.0, "small": 1.0})
        try:
            assert srv._qos is not None
            tickets = [srv.submit(_q("t", 0.3 + 0.01 * i),
                                  client_id="hog" if i < 3 else "small")
                       for i in range(6)]
            for t in tickets:
                t.result(timeout=60)
        finally:
            srv.stop()
        # the attained-service-heavy tenant drains after the light one
        assert order == ["small"] * 3 + ["hog"] * 3
        assert "qos" in srv.stats()

    def test_quota_shed_names_the_over_quota_tenant(self):
        from datafusion_tpu.errors import QueryShedError
        from tests.test_serve import _q

        ctx = self._ctx()
        METER.charge("b", "device_seconds", 100.0)
        srv = ctx.serve(workers=1, queue_depth=2, window_s=0.75,
                        megabatch_max=32,
                        shares={"a": 1.0, "b": 1.0})
        try:
            t1 = srv.submit(_q("t", 0.3), client_id="b")
            t2 = srv.submit(_q("t", 0.31), client_id="b")
            # the queue is full; a's arrival evicts b's NEWEST ticket
            # with the dedicated "quota" reason
            t3 = srv.submit(_q("t", 0.32), client_id="a")
            with pytest.raises(QueryShedError) as exc:
                t2.result(timeout=60)
            assert exc.value.reason == "quota"
            t1.result(timeout=60)
            t3.result(timeout=60)
        finally:
            srv.stop()
        assert srv.admitted + srv.shed == srv.submitted
        assert METER.snapshot()["b"]["shed_quota"] == 1.0
        assert "shed_quota" not in METER.snapshot().get("a", {})
