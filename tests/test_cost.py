"""Feedback-driven planning (datafusion_tpu/cost): the cost/statistics
store, the advisor's decision functions, and the adaptive loop end to
end.

The contracts under test:
- store mechanics: EWMA/last/max views per field, lock-free observe,
  decision/replan logs, bounded persistence;
- persistence survives a process restart (reset + reload from the same
  ``DATAFUSION_TPU_COST_DIR``), and a corrupt store file degrades to an
  empty store that never blocks planning;
- table keys retire on the RIGHT version bumps: a rewritten backing
  file and an ingest append each read/write fresh entries, while a
  byte-identical re-registration keeps learned statistics;
- trained-store planning flips real decisions (aggregate capacity
  pre-size, join build side) with bit-exact results;
- an induced cardinality misestimate triggers a replan that still
  returns the exact answer (and shows up in counters, flight events,
  and EXPLAIN ANALYZE);
- ``DATAFUSION_TPU_COST=0`` restores static planning: same results,
  zero decisions.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from datafusion_tpu import cost
from datafusion_tpu.cost import advisor
from datafusion_tpu.cost.store import CostStore
from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.datasource import MemoryDataSource
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.utils.metrics import METRICS


@pytest.fixture(autouse=True)
def _fresh_store():
    """Each test owns the process store and its env knobs."""
    saved = {
        k: os.environ.pop(k, None)
        for k in ("DATAFUSION_TPU_COST", "DATAFUSION_TPU_COST_DIR",
                  "DATAFUSION_TPU_COST_REPLAN_RATIO")
    }
    cost.reset_store()
    yield
    cost.reset_store()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


SCHEMA = Schema(
    [Field("k", DataType.UTF8, False), Field("v", DataType.FLOAT64, False)]
)


def _mem_source(groups: int = 4, rows: int = 200):
    d = StringDictionary()
    keys = [f"g{i % groups}" for i in range(rows)]
    codes = np.array([d.add(s) for s in keys], dtype=np.int32)
    vals = np.arange(rows, dtype=np.float64)
    batch = make_host_batch(SCHEMA, [codes, vals], [None, None], [d, None])
    return MemoryDataSource(SCHEMA, [batch])


def _ctx(tables=None) -> ExecutionContext:
    # result cache off: these tests assert on per-execution planning
    # behavior, which a cached result would short-circuit
    ctx = ExecutionContext(device="cpu", result_cache=False)
    for name, ds in (tables or {"t": _mem_source()}).items():
        ctx.register_datasource(name, ds)
    return ctx


SQL = "SELECT k, SUM(v) FROM t GROUP BY k"


def _rows(ctx, sql=SQL):
    return sorted(collect(ctx.sql(sql)).to_rows())


# -- store mechanics ------------------------------------------------------


class TestCostStore:
    def test_observe_keeps_ewma_last_and_max(self):
        st = CostStore()
        st.observe("t", "scan", rows=100)
        st.observe("t", "scan", rows=10)
        rec = st.lookup("t", "scan")
        assert rec["n"] == 2
        assert rec["rows_last"] == 10
        assert rec["rows_max"] == 100
        # EWMA sits between the samples, pulled toward the newer one
        assert 10 < rec["rows"] < 100

    def test_value_defaults_on_miss(self):
        st = CostStore()
        assert st.value("t", "scan", "rows") is None
        assert st.value("t", "scan", "rows", 7) == 7
        st.observe("t", "scan", rows=3)
        assert st.value("t", "scan", "rows_last", 7) == 3
        assert st.value("t", "scan", "nope", 7) == 7

    def test_decisions_carry_monotone_serials(self):
        st = CostStore()
        a = st.note_decision("x", 1, 2, "because")
        b = st.note_decision("y", 3, 4, "because", table="t")
        assert b["seq"] == a["seq"] + 1
        assert b["table"] == "t"
        assert [d["decision"] for d in st.decisions] == ["x", "y"]

    def test_snapshot_groups_by_table(self):
        st = CostStore()
        st.observe("t1", "scan", rows=5)
        st.observe("t1", "agg:g=k", groups=2)
        st.observe("t2", "scan", rows=9)
        snap = st.snapshot()
        assert set(snap["tables"]) == {"t1", "t2"}
        assert set(snap["tables"]["t1"]) == {"scan", "agg:g=k"}
        assert snap["entries"] == 3


# -- persistence ----------------------------------------------------------


class TestPersistence:
    def test_store_survives_restart(self, tmp_path):
        os.environ["DATAFUSION_TPU_COST_DIR"] = str(tmp_path)
        cost.reset_store()
        st = cost.store()
        st.observe("t@s1", "scan", rows=123)
        st.flush(force=True)
        # "restart": drop the process store, reload from disk
        cost.reset_store()
        st2 = cost.store()
        assert st2 is not st
        assert st2.value("t@s1", "scan", "rows_last") == 123

    def test_flush_is_throttled_until_forced(self, tmp_path):
        path = str(tmp_path / "cost_store.json")
        st = CostStore(path)
        st.observe("t", "scan", rows=1)
        assert st.flush(force=True)
        st.observe("t", "scan", rows=2)
        assert not st.flush()  # inside the save interval
        assert st.flush(force=True)

    def test_corrupt_store_degrades_to_empty(self, tmp_path):
        path = tmp_path / "cost_store.json"
        path.write_text('{"version": 1, "entries": {"t\\tscan"')
        before = METRICS.counts.get("cost.store.corrupt", 0)
        st = CostStore(str(path))
        assert len(st) == 0
        assert METRICS.counts.get("cost.store.corrupt", 0) == before + 1
        # ...and planning on top of the empty store still answers
        os.environ["DATAFUSION_TPU_COST_DIR"] = str(tmp_path)
        cost.reset_store()
        ctx = _ctx()
        assert _rows(ctx)

    def test_wrong_schema_version_dropped(self, tmp_path):
        path = tmp_path / "cost_store.json"
        path.write_text(json.dumps(
            {"version": 999, "entries": {"t\tscan": {"n": 1}}}))
        st = CostStore(str(path))
        assert len(st) == 0

    def test_flush_prunes_to_entry_budget(self, tmp_path):
        from datafusion_tpu.cost.store import _MAX_ENTRIES

        path = str(tmp_path / "cost_store.json")
        st = CostStore(path)
        for i in range(_MAX_ENTRIES + 10):
            st.observe(f"t{i}", "scan", rows=i)
        assert st.flush(force=True)
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
        assert len(payload["entries"]) == _MAX_ENTRIES


# -- table keys: version bumps retire the right entries -------------------


class TestTableKeys:
    def test_rewritten_file_reads_fresh_entries(self, tmp_path):
        p = tmp_path / "t.csv"
        p.write_text("k,v\na,1\nb,2\n")
        ctx = ExecutionContext(device="cpu", result_cache=False)
        ctx.register_csv("t", str(p), SCHEMA)
        key1 = ctx.cost_table_key("t")
        assert "@s" in key1  # file-identity key, stable across restarts
        # same file re-registered (a restart): identical key — the
        # learned statistics survive
        ctx2 = ExecutionContext(device="cpu", result_cache=False)
        ctx2.register_csv("t", str(p), SCHEMA)
        assert ctx2.cost_table_key("t") == key1
        # rewritten file: new key, stale cardinalities unreachable
        p.write_text("k,v\na,1\nb,2\nc,3\nd,4\n")
        ctx3 = ExecutionContext(device="cpu", result_cache=False)
        ctx3.register_csv("t", str(p), SCHEMA)
        assert ctx3.cost_table_key("t") != key1

    def test_ingest_append_bumps_key(self):
        from datafusion_tpu.ingest import AppendableSource

        src = AppendableSource.wrap(_mem_source(), "t")
        ctx = ExecutionContext(device="cpu", result_cache=False)
        ctx.register_datasource("t", src)
        key1 = ctx.cost_table_key("t")
        assert "@d0" in key1
        src.append_batch(src.build_batch({"k": ["z"], "v": [9.0]}))
        key2 = ctx.cost_table_key("t")
        assert key2 != key1 and "@d1" in key2

    def test_reregistration_bumps_in_memory_key(self):
        ctx = _ctx()
        key1 = ctx.cost_table_key("t")
        ctx.register_datasource("t", _mem_source(groups=8))
        assert ctx.cost_table_key("t") != key1


# -- the adaptive loop end to end -----------------------------------------


class TestAdaptivePlanning:
    def test_scan_and_groups_observed(self):
        ctx = _ctx()
        _rows(ctx)
        st = cost.store()
        tkey = ctx.cost_table_key("t")
        assert st.value(tkey, "scan", "rows_last") == 200
        assert st.value(tkey, "agg:g=k", "groups_last") == 4

    def test_trained_store_presizes_aggregate(self):
        ctx = _ctx()
        r1 = _rows(ctx)  # cold: observes 4 groups
        r2 = _rows(ctx)  # trained: pre-sizes from the learned count
        assert r1 == r2
        ds = [d for d in cost.store().decisions
              if d["decision"] == "agg.capacity"]
        assert ds and "~4 groups" in ds[-1]["reason"]

    def test_join_build_side_swaps_bit_exact(self):
        sm = Schema([Field("id", DataType.FLOAT64, False),
                     Field("name", DataType.UTF8, False)])
        bg = Schema([Field("fk", DataType.FLOAT64, False),
                     Field("x", DataType.FLOAT64, False)])
        d = StringDictionary()
        codes = np.array([d.add(f"n{i}") for i in range(5)], dtype=np.int32)
        small = MemoryDataSource(sm, [make_host_batch(
            sm, [np.arange(5, dtype=np.float64), codes],
            [None, None], [None, d])])
        fk = np.asarray(np.arange(500) % 5, dtype=np.float64)
        big = MemoryDataSource(bg, [make_host_batch(
            bg, [fk, np.arange(500, dtype=np.float64)],
            [None, None], [None, None])])
        sql = ("SELECT name, SUM(x) FROM small JOIN big ON id = fk "
               "GROUP BY name")
        ctx = _ctx({"small": small, "big": big})
        cold = _rows(ctx, sql)  # observes both scans + the build side
        trained = _rows(ctx, sql)  # build side swaps to the small table
        assert cold == trained
        ds = [d0 for d0 in cost.store().decisions
              if d0["decision"] == "join.build_side"]
        assert ds and ds[-1]["chosen"] == "left"

    def test_misestimate_triggers_replan_with_exact_answer(self):
        ctx = _ctx()
        want = _rows(ctx)
        # poison the store: claim this (table, GROUP BY shape) has
        # thousands of groups — the pre-sized plan must abort cheaply
        # and re-derive capacity from actuals
        st = cost.store()
        st.observe(ctx.cost_table_key("t"), "agg:g=k", groups=4000)
        before = METRICS.counts.get("plan.replans", 0)
        assert _rows(ctx) == want
        assert METRICS.counts.get("plan.replans", 0) == before + 1
        rp = list(st.replans)[-1]
        assert rp["what"] == "aggregate.capacity"
        assert rp["estimate"] == 4000 and rp["actual"] <= 8
        # the replan corrected the learned cardinality for next time
        assert st.value(
            ctx.cost_table_key("t"), "agg:g=k", "groups_last") == 4

    def test_replan_ratio_env_knob(self):
        os.environ["DATAFUSION_TPU_COST_REPLAN_RATIO"] = "1000000"
        ctx = _ctx()
        want = _rows(ctx)
        st = cost.store()
        st.observe(ctx.cost_table_key("t"), "agg:g=k", groups=4000)
        before = METRICS.counts.get("plan.replans", 0)
        assert _rows(ctx) == want  # tolerant ratio: no replan fires
        assert METRICS.counts.get("plan.replans", 0) == before

    def test_cost_off_restores_static_planning(self):
        ctx = _ctx()
        want = _rows(ctx)
        os.environ["DATAFUSION_TPU_COST"] = "0"
        assert _rows(ctx) == want
        assert _rows(ctx) == want
        assert not list(cost.store().decisions)
        # observation still flows when decisions are off (the serving
        # path's row weights read the same store)
        assert cost.store().value(
            ctx.cost_table_key("t"), "scan", "rows_last") == 200

    def test_explain_analyze_renders_decisions(self):
        ctx = _ctx()
        _rows(ctx)
        res = ctx.sql("EXPLAIN ANALYZE " + SQL)
        rep = res.report()
        assert "Cost decisions" in rep
        assert "agg.capacity" in rep and "default" in rep
        assert res.cost["decisions"]

    def test_explain_analyze_renders_replans(self):
        ctx = _ctx()
        _rows(ctx)
        cost.store().observe(ctx.cost_table_key("t"), "agg:g=k",
                             groups=4000)
        res = ctx.sql("EXPLAIN ANALYZE " + SQL)
        assert "Replans (" in res.report()
        assert res.cost["replans"]


# -- advisor decision functions (unit) ------------------------------------


class TestAdvisor:
    def test_agg_shape_is_order_insensitive(self):
        assert advisor.agg_shape(["b", "a"]) == advisor.agg_shape(["a", "b"])

    def test_pallas_agg_window_needs_samples(self):
        from datafusion_tpu.exec.pallas import agg_max_groups

        st = CostStore()
        # an empty store keeps the static env window
        assert advisor.pallas_agg_window(st) == agg_max_groups()

    def test_pallas_agg_window_disengages_when_slower(self):
        st = CostStore()
        for _ in range(4):
            advisor.observe_agg_route(st, "pallas", 1024, 1.0, 1000)
            advisor.observe_agg_route(st, "sortmerge", 1024, 0.1, 1000)
        assert advisor.pallas_agg_window(st) == 0

    def test_pallas_agg_window_widens_when_faster(self):
        from datafusion_tpu.exec.pallas import agg_max_groups

        st = CostStore()
        static = agg_max_groups()
        for _ in range(4):
            advisor.observe_agg_route(st, "pallas", static, 0.1, 1000)
            advisor.observe_agg_route(st, "sortmerge", static, 1.0, 1000)
        assert advisor.pallas_agg_window(st) > static

    def test_serve_window_shrinks_for_sparse_arrivals(self):
        st = CostStore()
        st.observe(cost.SERVE_KEY, "arrivals", interval_s=1.0)
        chosen = advisor.serve_window_s(st, 0.002)
        assert chosen < 0.002

    def test_serve_window_widens_for_dense_arrivals(self):
        st = CostStore()
        st.observe(cost.SERVE_KEY, "arrivals", interval_s=0.0001)
        chosen = advisor.serve_window_s(st, 0.002)
        assert chosen > 0.002

    def test_scan_chunk_needs_link_rate(self):
        st = CostStore()
        st.observe("t", "scan", rows=1000, nbytes=8000)
        # no measured link rate -> keep the configured chunking
        assert advisor.scan_chunk_rows(st, "t", "cpu", 1000) is None


# -- guardrails -----------------------------------------------------------


class TestGuardrails:
    def test_schema_preservation_veto(self):
        from datafusion_tpu.analysis.verify import (
            PlanVerificationError,
            assert_schema_preserved,
        )

        a = Schema([Field("x", DataType.FLOAT64, False)])
        b = Schema([Field("x", DataType.FLOAT64, False)])
        assert_schema_preserved(a, b, "cost rewrite")  # equal: fine
        c = Schema([Field("y", DataType.FLOAT64, False)])
        with pytest.raises(PlanVerificationError):
            assert_schema_preserved(a, c, "cost rewrite")

    def test_df005_covers_cost_observe_path(self):
        from datafusion_tpu.analysis import lint

        src = (
            "import threading\n"
            "class CostStore:\n"
            "    def observe(self, k, s, **f):\n"
            "        with self._lock:\n"
            "            pass\n"
        )
        found = lint.lint_source(src, "datafusion_tpu/cost/store.py")
        assert any(f.rule == "DF005" for f in found)
        # the real store passes its own lint
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        real = os.path.join(repo, "datafusion_tpu", "cost", "store.py")
        assert lint.lint_paths([real]) == []

    def test_debug_cost_snapshot_shape(self):
        ctx = _ctx()
        _rows(ctx)
        snap = cost.store().snapshot()
        assert {"path", "entries", "tables", "decisions", "replans"} \
            <= set(snap)
        # JSON-serializable end to end (the /debug/cost contract)
        json.dumps(snap)

    def test_console_cost_command(self):
        import io

        from datafusion_tpu.cli import Console

        ctx = _ctx()
        _rows(ctx)
        _rows(ctx)
        out = io.StringIO()
        con = Console(ctx, out=out)
        assert con.handle_command("\\cost")
        text = out.getvalue()
        assert "Cost store:" in text and "agg:g=k" in text
