"""Console CLI tests, including the reference's golden smoketest.

The golden file `test/data/smoketest-expected.txt` is the output the
pre-rewrite reference console produced (`scripts/smoketest.sh:68-89`
diffs with `diff -bBZ -I seconds`); the rewrite never re-attached it.
Here it passes: DDL executes, geo UDFs exist, rows print.
"""

import io
import os
import subprocess
import sys

from datafusion_tpu.cli import Console, make_context, run_script

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "test", "data")


def _run_sql_text(sql_text: str, tmp_path) -> list[str]:
    script = tmp_path / "script.sql"
    script.write_text(sql_text)
    out = io.StringIO()
    console = Console(make_context(), out=out)
    run_script(console, str(script))
    return out.getvalue().splitlines()


def _strip_timing(lines: list[str]) -> list[str]:
    # the golden harness ignores timing lines (diff -I seconds)
    return [l.rstrip() for l in lines if "seconds" not in l and l.strip()]


class TestGoldenSmoketest:
    def test_smoketest_matches_golden_output(self, tmp_path):
        sql = open(os.path.join(DATA, "smoketest.sql")).read()
        # the docker harness mounted fixtures at /test/data; rewrite to
        # this checkout's path
        sql = sql.replace("'/test/data/", f"'{DATA}/")
        got = _strip_timing(_run_sql_text(sql, tmp_path))
        want = open(os.path.join(DATA, "smoketest-expected.txt")).read().splitlines()
        # the golden file's first line is the banner, printed by main()
        want = [l.rstrip() for l in want if l.strip() and l != "DataFusion Console"]
        assert got == want


class TestConsole:
    def test_ddl_then_query(self, tmp_path):
        lines = _run_sql_text(
            "CREATE EXTERNAL TABLE people (id INT, first_name VARCHAR(100)) "
            f"STORED AS CSV WITH HEADER ROW LOCATION '{DATA}/people.csv';\n"
            "SELECT id, first_name FROM people WHERE id > 1;",
            tmp_path,
        )
        assert lines.count("Executing query ...") == 2
        assert not any(l.startswith("Error") for l in lines)
        data_lines = _strip_timing(lines)[2:]
        assert data_lines and all("\t" in l for l in data_lines)

    def test_error_does_not_kill_console(self, tmp_path):
        lines = _run_sql_text(
            "SELECT * FROM nonexistent;\nSELECT 1 + 1;",
            tmp_path,
        )
        assert any(l.startswith("Error:") for l in lines)

    def test_multiline_statement_accumulates(self, tmp_path):
        lines = _run_sql_text(
            "CREATE EXTERNAL TABLE people (id INT, first_name VARCHAR(100))\n"
            "STORED AS CSV WITH HEADER ROW\n"
            f"LOCATION '{DATA}/people.csv';\n"
            "SELECT COUNT(1)\nFROM people;",
            tmp_path,
        )
        assert lines.count("Executing query ...") == 2
        assert not any(l.startswith("Error") for l in lines)


class TestCliSubprocess:
    def test_script_mode_end_to_end(self, tmp_path):
        script = tmp_path / "s.sql"
        script.write_text(
            "CREATE EXTERNAL TABLE cities (city VARCHAR(100), lat DOUBLE, lng DOUBLE) "
            f"STORED AS CSV WITHOUT HEADER ROW LOCATION '{DATA}/uk_cities.csv';\n"
            "SELECT city, lat + lng FROM cities WHERE lat > 52.0;\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        proc = subprocess.run(
            [sys.executable, "-m", "datafusion_tpu.cli", "--script", str(script)],
            capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout.startswith("DataFusion Console")
        assert proc.stdout.count("Executing query ...") == 2

    def test_interactive_quit(self, tmp_path):
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        proc = subprocess.run(
            [sys.executable, "-m", "datafusion_tpu.cli"],
            input="SELECT 1 + 2;\nquit\n",
            capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        assert "Executing query ..." in proc.stdout

    def test_interactive_pty_ctrl_c_clears_ctrl_d_exits(self, tmp_path):
        """Line-editor behavior under a real terminal (reference
        linereader.rs:47-103): Ctrl-C abandons a half-typed statement
        and returns to a fresh prompt; Ctrl-D exits; history persists
        to the history file."""
        import pty
        import select
        import time as _time

        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO,
            HOME=str(tmp_path),  # history file lands here
        )
        pid, fd = pty.fork()
        if pid == 0:  # child: exec the CLI on the pty
            os.chdir(REPO)
            os.execvpe(
                sys.executable,
                [sys.executable, "-m", "datafusion_tpu.cli"],
                env,
            )
        out = b""

        def read_until(marker: bytes, timeout=60.0):
            nonlocal out
            deadline = _time.monotonic() + timeout
            while marker not in out:
                rest = deadline - _time.monotonic()
                assert rest > 0, f"timeout waiting for {marker!r}; got {out!r}"
                r, _, _ = select.select([fd], [], [], rest)
                if r:
                    try:
                        out += os.read(fd, 4096)
                    except OSError:
                        break
            return out

        try:
            read_until(b"datafusion> ")
            os.write(fd, b"SELECT 1 +\n")  # half a statement
            # the bare continuation prompt only appears after a newline
            # ("datafusion> " would false-match a plain "> " search)
            read_until(b"\n> ")
            # let readline enter its read loop before interrupting (the
            # prompt prints a beat before the handler is in place)
            _time.sleep(0.3)
            os.write(fd, b"\x03")  # Ctrl-C: abandon the buffer
            try:
                read_until(b"^C", timeout=10.0)
            except AssertionError:
                os.write(fd, b"\x03")  # rare: signal landed pre-loop
                read_until(b"^C", timeout=30.0)
            read_until(b"datafusion> ")  # fresh prompt, session alive
            os.write(fd, b"SELECT 2 + 3;\n")
            read_until(b"Executing query ...")
            read_until(b"5")
            read_until(b"datafusion> ")
            _time.sleep(0.3)  # same settle as before Ctrl-C
            os.write(fd, b"\x04")  # Ctrl-D: exit
            deadline = _time.monotonic() + 60
            retried = False
            while True:
                done, status = os.waitpid(pid, os.WNOHANG)
                if done:
                    break
                if not retried and _time.monotonic() > deadline - 50:
                    os.write(fd, b"\x04")
                    retried = True
                assert _time.monotonic() < deadline, "CLI did not exit on Ctrl-D"
                _time.sleep(0.05)
            assert os.waitstatus_to_exitcode(status) == 0
        finally:
            os.close(fd)
            try:
                os.kill(pid, 9)
            except ProcessLookupError:
                pass
        hist = tmp_path / ".datafusion_tpu_history"
        assert hist.exists(), "readline history file not written"
        assert "SELECT 2 + 3;" in hist.read_text()


class TestStatementSplitting:
    def test_semicolon_inside_string_literal(self, tmp_path):
        # a ';' inside a SQL string literal must not terminate the
        # statement (quote-aware splitting): a LOCATION path with ';'
        import shutil

        src = os.path.join(DATA, "people.csv")
        dst = tmp_path / "people;v2.csv"
        shutil.copy(src, dst)
        lines = _run_sql_text(
            "CREATE EXTERNAL TABLE people (id INT, first_name VARCHAR(100)) "
            f"STORED AS CSV WITH HEADER ROW LOCATION '{dst}';\n"
            "SELECT COUNT(1) FROM people;\n",
            tmp_path,
        )
        assert lines.count("Executing query ...") == 2
        assert not any(l.startswith("Error") for l in lines)

    def test_escaped_quote_in_literal(self):
        from datafusion_tpu.sql.parser import split_statements_partial

        stmts, rest = split_statements_partial("SELECT 'it''s;ok'; SELECT 2")
        assert stmts == ["SELECT 'it''s;ok'"]
        assert rest == " SELECT 2"

    def test_comment_with_apostrophe_does_not_open_literal(self):
        from datafusion_tpu.sql.parser import split_statements_partial

        stmts, rest = split_statements_partial(
            "-- don't trip on this\nSELECT 1;\nSELECT 2;\n"
        )
        assert stmts == ["SELECT 1", "SELECT 2"]
        assert rest.strip() == ""
        # a tail ending mid-comment keeps its raw text so appended
        # input continues the comment until a newline arrives
        stmts, rest = split_statements_partial("SELECT 1; -- note")
        assert stmts == ["SELECT 1"]
        assert rest == " -- note"

    def test_block_comment_with_semicolon(self):
        from datafusion_tpu.sql.parser import (
            split_statements,
            split_statements_partial,
        )

        assert split_statements("SELECT /* a;b */ 1;") == ["SELECT  1"]
        # unclosed block comment: raw tail kept so a REPL can close it
        stmts, rest = split_statements_partial("SELECT 1; /* note")
        assert stmts == ["SELECT 1"]
        assert rest == " /* note"

    def test_script_trailing_comment_no_error(self, tmp_path):
        lines = _run_sql_text("SELECT 1 + 1;\n-- trailing comment\n", tmp_path)
        assert lines.count("Executing query ...") == 1
        assert not any(l.startswith("Error") for l in lines)


class TestTimingMode:
    def test_timing_toggle_and_output(self, tmp_path):
        import io

        from datafusion_tpu.cli import Console, make_context

        out = io.StringIO()
        csv = tmp_path / "t.csv"
        csv.write_text("a,b\n1,2.5\n3,4.5\n")
        c = Console(make_context("cpu"), out=out)
        c.execute("\\timing")
        c.execute(
            f"CREATE EXTERNAL TABLE t (a INT, b DOUBLE) STORED AS CSV "
            f"WITH HEADER ROW LOCATION '{csv}'"
        )
        c.execute("SELECT a, b FROM t WHERE a > 0")
        text = out.getvalue()
        assert "Timing is on." in text
        assert "Timing: " in text
        assert "parse=" in text
        assert "Counters: " in text and "scan.rows=2" in text
        c.execute("\\timing")
        assert "Timing is off." in out.getvalue()

    def test_timing_as_bare_script_line(self, tmp_path):
        # psql convention: a backslash command is a LINE, no semicolon —
        # it must not fall into the statement splitter
        import io

        from datafusion_tpu.cli import Console, make_context, run_script

        csv = tmp_path / "t.csv"
        csv.write_text("a\n1\n")
        script = tmp_path / "s.sql"
        script.write_text(
            "\\timing\n"
            f"CREATE EXTERNAL TABLE t (a INT) STORED AS CSV WITH HEADER ROW "
            f"LOCATION '{csv}';\n"
            "SELECT a FROM t;\n"
        )
        out = io.StringIO()
        c = Console(make_context("cpu"), out=out)
        run_script(c, str(script))
        text = out.getvalue()
        assert "Timing is on." in text
        assert "Error" not in text
        assert "Timing: " in text


class TestProfilerTrace:
    def test_trace_writes_profile(self, tmp_path):
        import os

        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.context import ExecutionContext
        from datafusion_tpu.exec.datasource import MemoryDataSource
        from datafusion_tpu.utils.profiling import annotate, trace

        schema = Schema([Field("x", DataType.FLOAT64, False)])
        batch = make_host_batch(schema, [np.arange(100.0)], [None], [None])
        ctx = ExecutionContext(device="cpu")
        ctx.register_datasource("t", MemoryDataSource(schema, [batch]))
        out_dir = str(tmp_path / "prof")
        with trace(out_dir):
            with annotate("q1"):
                ctx.sql_collect("SELECT SUM(x), COUNT(1) FROM t WHERE x > 1")
        # a plugins/profile/<ts>/ tree with at least one trace artifact
        found = []
        for _root, _dirs, files in os.walk(out_dir):
            found.extend(files)
        assert found, "profiler produced no trace files"


class TestReferenceBenches:
    def test_runs_and_reports_all_five_targets(self, capsys):
        # the reference's commented-out bench list, revived
        # (/root/reference/Cargo.toml:50-68)
        import json

        from benchmarks.reference_benches import main

        main()
        out = json.loads(capsys.readouterr().out.strip())
        assert set(out) == {
            "read_csv_ms", "filter_primitive_ms", "sql_ms",
            "dataframe_ms", "udf_udt_ms",
        }
        assert all(v > 0 for v in out.values())
