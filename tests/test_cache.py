"""Plan-fingerprinted query/fragment cache subsystem (datafusion_tpu/cache).

Covers the store mechanics (byte-accounted LRU, TTL, tag invalidation),
fingerprint canonicalization (catalog versions, fragment identity
without query_id, source-file versioning), the coordinator result cache
(repeat query served without re-execution, EXPLAIN ANALYZE cache.hit,
invalidation on re-registration, zero overhead when off), the worker
fragment cache (duplicate dispatches after failover served from memory,
cache-hit flag observed at merge), per-context stats history, and the
background trace flusher.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from datafusion_tpu import cache
from datafusion_tpu.cache.result import CachedResultRelation
from datafusion_tpu.cache.store import CacheStore
from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.datasource import MemoryDataSource
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.utils.metrics import METRICS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- helpers --------------------------------------------------------------

SCHEMA = Schema(
    [Field("k", DataType.UTF8, False), Field("v", DataType.FLOAT64, False)]
)


def _mem_source(keys=("a", "b", "a", "c"), vals=(1.0, 2.0, 3.0, 4.0)):
    d = StringDictionary()
    codes = np.array([d.add(s) for s in keys], dtype=np.int32)
    batch = make_host_batch(
        SCHEMA, [codes, np.asarray(vals, dtype=np.float64)],
        [None, None], [d, None],
    )
    return MemoryDataSource(SCHEMA, [batch])


class CountingSource(MemoryDataSource):
    """MemoryDataSource that counts scans — asserts 'no re-execution'.
    The counter is shared through projection pushdown (with_projection
    builds a new source object)."""

    def __init__(self, schema, batches, counter=None):
        super().__init__(schema, batches)
        self.counter = counter if counter is not None else {"scans": 0}

    @property
    def scans(self):
        return self.counter["scans"]

    def batches(self):
        self.counter["scans"] += 1
        return super().batches()

    def with_projection(self, projection):
        base = super().with_projection(projection)
        return CountingSource(base._schema, base._batches, self.counter)


def _counting_ctx(**kw):
    src = CountingSource(SCHEMA, list(_mem_source()._batches))
    ctx = ExecutionContext(device="cpu", **kw)
    ctx.register_datasource("t", src)
    return ctx, src


SQL = "SELECT k, SUM(v), COUNT(1) FROM t GROUP BY k"


def _rows(ctx, sql=SQL):
    return sorted(collect(ctx.sql(sql)).to_rows())


# -- store ----------------------------------------------------------------


class TestCacheStore:
    def test_lru_eviction_by_bytes(self):
        st = CacheStore(max_bytes=100, name="t1")
        assert st.put("a", 1, 40) and st.put("b", 2, 40)
        assert st.get("a") == 1  # a is now MRU
        assert st.put("c", 3, 40)  # evicts b (LRU)
        assert st.get("b") is None
        assert st.get("a") == 1 and st.get("c") == 3
        assert st.evictions == 1
        assert st.bytes_used == 80

    def test_oversized_value_rejected_not_stored(self):
        st = CacheStore(max_bytes=100, name="t2")
        st.put("small", 1, 10)
        assert not st.put("huge", 2, 1000)
        assert st.get("huge") is None
        assert st.get("small") == 1  # the giant value didn't wipe the cache
        assert st.rejected == 1

    def test_ttl_expiry(self):
        st = CacheStore(max_bytes=100, ttl_s=0.05, name="t3")
        st.put("a", 1, 10)
        assert st.get("a") == 1
        time.sleep(0.08)
        assert st.get("a") is None
        assert st.entries == 0 and st.bytes_used == 0

    def test_tag_invalidation(self):
        st = CacheStore(max_bytes=1000, name="t4")
        st.put("q1", 1, 10, tags=("lineitem",))
        st.put("q2", 2, 10, tags=("lineitem", "orders"))
        st.put("q3", 3, 10, tags=("orders",))
        assert st.invalidate_tag("lineitem") == 2
        assert st.get("q1") is None and st.get("q2") is None
        assert st.get("q3") == 3
        assert st.bytes_used == 10

    def test_replace_updates_bytes(self):
        st = CacheStore(max_bytes=100, name="t5")
        st.put("a", 1, 60)
        st.put("a", 2, 30)
        assert st.bytes_used == 30 and st.entries == 1
        assert st.get("a") == 2

    def test_stats_and_gauges(self):
        st = CacheStore(max_bytes=100, name="t6")
        st.put("a", 1, 10)
        st.get("a")
        st.get("missing")
        s = st.stats()
        assert (s["hits"], s["misses"], s["entries"]) == (1, 1, 1)
        g = st.gauges()
        assert g["cache.t6.bytes"] == 10 and g["cache.t6.entries"] == 1


# -- fingerprints ---------------------------------------------------------


class TestFingerprint:
    def _plan(self, ctx, sql):
        from datafusion_tpu.sql.parser import parse_sql

        return ctx._plan(parse_sql(sql))

    def test_plan_fingerprint_deterministic_and_sensitive(self):
        ctx = ExecutionContext(device="cpu")
        ctx.register_datasource("t", _mem_source())
        p1 = self._plan(ctx, SQL)
        p2 = self._plan(ctx, SQL)
        assert ctx.query_fingerprint(p1) == ctx.query_fingerprint(p2)
        p3 = self._plan(ctx, "SELECT k, SUM(v), COUNT(1) FROM t GROUP BY k "
                             "LIMIT 1")
        assert ctx.query_fingerprint(p1) != ctx.query_fingerprint(p3)
        # a different literal is different work
        a = self._plan(ctx, "SELECT v FROM t WHERE v > 1.0")
        b = self._plan(ctx, "SELECT v FROM t WHERE v > 2.0")
        assert ctx.query_fingerprint(a) != ctx.query_fingerprint(b)

    def test_catalog_version_changes_fingerprint(self):
        ctx = ExecutionContext(device="cpu")
        ctx.register_datasource("t", _mem_source())
        plan = self._plan(ctx, SQL)
        fp1 = ctx.query_fingerprint(plan)
        ctx.register_datasource("t", _mem_source())  # same data, new version
        assert ctx.query_fingerprint(plan) != fp1
        assert ctx.catalog_version("t") == 2

    def test_fragment_fingerprint_ignores_query_id(self, tmp_path):
        from datafusion_tpu.parallel.physical import PlanFragment

        path = tmp_path / "part.csv"
        path.write_text("k,v\na,1.0\nb,2.0\n")
        ctx = ExecutionContext(device="cpu")
        ctx.register_csv("t", str(path), SCHEMA)
        plan = self._plan(ctx, SQL)
        meta = ctx.datasources["t"].to_meta()
        f1 = PlanFragment(0, 2, plan.to_json(), meta, "query-aaa")
        f2 = PlanFragment(0, 2, plan.to_json(), meta, "query-bbb")
        assert cache.fragment_fingerprint(f1) == cache.fragment_fingerprint(f2)
        # shard identity and source-file version DO matter
        f3 = PlanFragment(1, 2, plan.to_json(), meta, "query-aaa")
        assert cache.fragment_fingerprint(f1) != cache.fragment_fingerprint(f3)
        fp_before = cache.fragment_fingerprint(f1)
        st = os.stat(path)
        os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
        assert cache.fragment_fingerprint(f1) != fp_before

    def test_canonical_json_key_order_independent(self):
        assert cache.canonical_json({"b": 1, "a": [2, 3]}) == \
            cache.canonical_json({"a": [2, 3], "b": 1})


# -- coordinator result cache --------------------------------------------


class TestResultCache:
    def test_repeat_query_served_without_reexecution(self):
        ctx, src = _counting_ctx()
        want = _rows(ctx)
        scans = src.scans
        assert scans >= 1
        rel = ctx.sql(SQL)
        assert isinstance(rel, CachedResultRelation)
        assert sorted(collect(rel).to_rows()) == want
        assert src.scans == scans  # datasource untouched on the repeat
        assert ctx.result_cache.hits == 1

    def test_explain_analyze_shows_cache_hit(self):
        ctx, _src = _counting_ctx()
        _rows(ctx)
        report = ctx.sql("EXPLAIN ANALYZE " + SQL).report()
        assert "CachedResult" in report and "cache.hit=True" in report

    def test_explain_analyze_populates_cache(self):
        # the EA run is a real execution — its result fills the cache,
        # so a plain repeat afterwards is a hit
        ctx, src = _counting_ctx()
        res = ctx.sql("EXPLAIN ANALYZE " + SQL)
        scans = src.scans
        rel = ctx.sql(SQL)
        assert isinstance(rel, CachedResultRelation)
        assert sorted(collect(rel).to_rows()) == sorted(res.result.to_rows())
        assert src.scans == scans

    def test_reregistration_invalidates(self):
        ctx, _src = _counting_ctx()
        want1 = _rows(ctx)
        src2 = CountingSource(SCHEMA, list(_mem_source(
            keys=("x", "x"), vals=(10.0, 20.0))._batches))
        ctx.register_datasource("t", src2)
        rel = ctx.sql(SQL)
        assert not isinstance(rel, CachedResultRelation)
        got = sorted(collect(rel).to_rows())
        assert got == [("x", 30.0, 2)] and got != want1
        assert src2.scans >= 1

    def test_ttl_expiry_re_executes(self):
        with cache.configured(ttl_s=0.05):
            ctx, src = _counting_ctx()
            _rows(ctx)
            scans = src.scans
            time.sleep(0.08)
            rel = ctx.sql(SQL)
            assert not isinstance(rel, CachedResultRelation)
            collect(rel)
            assert src.scans > scans

    def test_oversized_result_not_cached(self):
        with cache.configured(max_bytes=64):  # result won't fit
            ctx, src = _counting_ctx()
            _rows(ctx)
            scans = src.scans
            rel = ctx.sql(SQL)
            assert not isinstance(rel, CachedResultRelation)
            collect(rel)
            assert src.scans > scans
            assert ctx.result_cache.entries == 0

    def test_distinct_queries_distinct_entries(self):
        ctx, _src = _counting_ctx()
        _rows(ctx)
        _rows(ctx, "SELECT v FROM t WHERE v > 1.5")
        assert ctx.result_cache.entries == 2
        assert isinstance(ctx.sql(SQL), CachedResultRelation)
        assert isinstance(
            ctx.sql("SELECT v FROM t WHERE v > 1.5"), CachedResultRelation
        )

    def test_utf8_and_validity_roundtrip(self):
        schema = Schema([
            Field("s", DataType.UTF8, True),
            Field("x", DataType.FLOAT64, True),
        ])
        d = StringDictionary()
        codes = np.array([d.add(s) for s in ["aa", "bb", "aa"]], np.int32)
        batch = make_host_batch(
            schema,
            [codes, np.array([1.0, 2.0, 3.0])],
            [np.array([True, False, True]), np.array([False, True, True])],
            [d, None],
        )
        ctx = ExecutionContext(device="cpu")
        ctx.register_datasource("u", MemoryDataSource(schema, [batch]))
        sql = "SELECT s, x FROM u"
        want = collect(ctx.sql(sql)).to_rows()
        rel = ctx.sql(sql)
        assert isinstance(rel, CachedResultRelation)
        assert collect(rel).to_rows() == want
        assert [r[0] for r in want] == ["aa", None, "aa"]

    def test_empty_result_cached(self):
        ctx, _src = _counting_ctx()
        sql = "SELECT v FROM t WHERE v > 100.0"
        assert _rows(ctx, sql) == []
        rel = ctx.sql(sql)
        assert isinstance(rel, CachedResultRelation)
        assert sorted(collect(rel).to_rows()) == []

    def test_udf_registration_invalidates_by_fingerprint(self):
        ctx, _src = _counting_ctx()
        _rows(ctx)
        ctx.register_udf(
            "twice", [DataType.FLOAT64], DataType.FLOAT64, lambda x: x * 2
        )
        # the functions_version rode the fingerprint: same SQL re-plans
        assert not isinstance(ctx.sql(SQL), CachedResultRelation)

    def test_off_means_off(self):
        with cache.configured(enabled=False):
            ctx, src = _counting_ctx()
            assert ctx.result_cache is None
            _rows(ctx)
            scans = src.scans
            rel = ctx.sql(SQL)
            assert not isinstance(rel, CachedResultRelation)
            assert not hasattr(rel, "_result_cache_fill")
            collect(rel)
            assert src.scans > scans

    def test_explicit_false_overrides_env_default(self):
        ctx = ExecutionContext(device="cpu", result_cache=False)
        assert ctx.result_cache is None

    def test_externally_rewritten_file_not_served_stale(self, tmp_path):
        # the result fingerprint folds in the backing file's
        # (mtime, size): rewriting the file out from under the catalog
        # must miss, exactly like the uncached engine re-scanning it
        path = tmp_path / "t.csv"
        path.write_text("k,v\na,1.0\nb,2.0\n")
        ctx = ExecutionContext(device="cpu")
        ctx.register_csv("t", str(path), SCHEMA)
        sql = "SELECT k, v FROM t"
        assert sorted(r[0] for r in _rows(ctx, sql)) == ["a", "b"]
        path.write_text("k,v\nz,9.0\n")
        st = os.stat(path)
        os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
        rel = ctx.sql(sql)
        assert not isinstance(rel, CachedResultRelation)
        assert [r[0] for r in collect(rel).to_rows()] == ["z"]

    def test_concurrent_queries_one_context(self):
        # the root/recursion guard is per-thread: parallel queries on a
        # shared context must each see correct (and cacheable) results
        import threading

        ctx, _src = _counting_ctx()
        sqls = [SQL, "SELECT v FROM t WHERE v > 1.5", "SELECT k FROM t"]
        wants = [_rows(ctx, s) for s in sqls]
        results: dict[int, list] = {}

        def run(i):
            out = []
            for _ in range(5):
                out.append(_rows(ctx, sqls[i]))
            results[i] = out

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(sqls))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, want in enumerate(wants):
            assert all(got == want for got in results[i])


# -- stats history --------------------------------------------------------


class TestStatsHistory:
    def test_warm_and_cold_runs_recorded(self):
        ctx, _src = _counting_ctx()
        _rows(ctx)
        fp = ctx.last_fingerprint
        _rows(ctx)
        hist = ctx.stats_history(fp)
        assert [h["cache_hit"] for h in hist] == [False, True]
        assert all(h["rows"] == 3 for h in hist)
        assert all(h["wall_s"] >= 0 for h in hist)
        assert fp in ctx.stats_history()

    def test_instrumented_run_records_operators(self):
        ctx, _src = _counting_ctx()
        ctx.sql("EXPLAIN ANALYZE " + SQL)
        fp = ctx.last_fingerprint
        hist = ctx.stats_history(fp)
        assert hist and "operators" in hist[0]
        ops = [o["op"] for o in hist[0]["operators"]]
        assert any("Aggregate" in o for o in ops)

    def test_history_bounded(self):
        ctx, _src = _counting_ctx()
        ctx._history_cap = 4
        for _ in range(8):
            _rows(ctx)
        assert len(ctx.stats_history(ctx.last_fingerprint)) == 4


# -- worker fragment cache (distributed) ----------------------------------


def _write_partitions(tmp_path, n_parts=2, rows_per=200):
    rng = np.random.default_rng(7)
    regions = ["north", "south", "east", "west"]
    paths = []
    for p in range(n_parts):
        path = tmp_path / f"part{p}.csv"
        with open(path, "w", encoding="utf-8") as f:
            f.write("region,v\n")
            for _ in range(rows_per):
                f.write(f"{regions[rng.integers(0, 4)]},"
                        f"{int(rng.integers(-1000, 1000))}\n")
        paths.append(str(path))
    return paths


DSCHEMA = Schema([
    Field("region", DataType.UTF8, False),
    Field("v", DataType.INT64, False),
])
DSQL = ("SELECT region, SUM(v), COUNT(1), MIN(v), MAX(v) "
        "FROM t GROUP BY region")


def _spawn_worker(fault_plan=None, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    if fault_plan is not None:
        env["DATAFUSION_TPU_FAULTS"] = json.dumps(fault_plan)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "datafusion_tpu.worker",
         "--bind", "127.0.0.1:0", "--device", "cpu"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    line = proc.stdout.readline()
    assert "listening on" in line, line
    host, port = line.strip().rsplit(" ", 1)[1].rsplit(":", 1)
    return proc, (host, int(port))


def _register_parts(ctx, paths):
    from datafusion_tpu.exec.datasource import CsvDataSource
    from datafusion_tpu.parallel.partition import PartitionedDataSource

    ctx.register_datasource(
        "t",
        PartitionedDataSource(
            [CsvDataSource(p, DSCHEMA, True, 131072) for p in paths]
        ),
    )
    return ctx


def _frag_hits() -> int:
    return METRICS.snapshot()["counts"].get("coord.fragment_cache_hits", 0)


class TestWorkerFragmentCache:
    def test_replayed_fragment_served_from_cache(self, tmp_path):
        """Lost-response failover: the worker already executed the
        fragment; the replay (and the repeat query) must be served from
        its fragment cache — the cache-hit flag observed at merge."""
        from datafusion_tpu.parallel.coordinator import DistributedContext
        from datafusion_tpu.testing import faults

        paths = _write_partitions(tmp_path)
        want = sorted(
            collect(
                _register_parts(ExecutionContext(device="cpu"), paths).sql(DSQL)
            ).to_rows()
        )
        proc, addr = _spawn_worker()
        try:
            dctx = _register_parts(
                DistributedContext([addr], result_cache=False), paths
            )
            base = _frag_hits()
            assert sorted(collect(dctx.sql(DSQL)).to_rows()) == want
            assert _frag_hits() == base  # cold run: no cached serves
            # drop the first fragment response at the coordinator: the
            # worker is marked down, re-probed, and the replay must be
            # answered from its fragment cache (no partition re-scan)
            with faults.scoped({"rules": [
                {"site": "wire.recv", "op": "raise",
                 "exc": "ConnectionResetError", "after": 1, "count": 1},
            ]}) as plan:
                assert sorted(collect(dctx.sql(DSQL)).to_rows()) == want
                assert plan.snapshot()[0]["fired"] == 1
            assert _frag_hits() - base >= 2
            snap = METRICS.snapshot()["counts"]
            assert snap.get("coord.fragment_reassigned", 0) >= 1
            status = dctx.worker_status()[f"{addr[0]}:{addr[1]}"]
            frag_stats = status["cache"]["fragment"]
            assert frag_stats["hits"] >= 2
            # satellite: one status scrape carries the Prometheus text
            # with counter lines and the cache/span-buffer gauges
            prom = status["prometheus"]
            assert "datafusion_tpu_events_total" in prom
            # dotted gauge names keep their dots post-sanitization-fix
            assert 'name="cache.fragment.bytes"' in prom
            assert 'name="obs.span_buffer_depth"' in prom
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_chaos_kill_served_from_surviving_cache(self, tmp_path):
        """Worker death chaos: with a kill rule armed on the next real
        fragment execution, the repeat query must complete with at
        least one fragment served from a fragment cache — either the
        crashy worker answers from memory (no execution, no kill), or
        it dies mid-fragment and the survivor serves the replay from
        its own cache."""
        from datafusion_tpu.parallel.coordinator import DistributedContext

        paths = _write_partitions(tmp_path)
        want = sorted(
            collect(
                _register_parts(ExecutionContext(device="cpu"), paths).sql(DSQL)
            ).to_rows()
        )
        crashy, crashy_addr = _spawn_worker(fault_plan={"rules": [
            {"site": "worker.fragment", "op": "kill", "after": 2},
        ]})
        healthy, healthy_addr = _spawn_worker()
        try:
            dctx = _register_parts(
                DistributedContext([crashy_addr, healthy_addr],
                                   result_cache=False),
                paths,
            )
            base = _frag_hits()
            # q1: both workers execute one fragment each (kill arms at
            # the crashy worker's SECOND execution)
            assert sorted(collect(dctx.sql(DSQL)).to_rows()) == want
            # q2: every fragment is already cached on SOME worker; a
            # kill (if it fires) hits a fragment the survivor has
            assert sorted(collect(dctx.sql(DSQL)).to_rows()) == want
            assert _frag_hits() - base >= 1
            if crashy.poll() is not None:
                assert crashy.returncode == 17  # died by injected kill
                assert not dctx.workers[0].alive
        finally:
            for p in (crashy, healthy):
                if p.poll() is None:
                    p.terminate()
            for p in (crashy, healthy):
                p.wait(timeout=10)

    def test_coordinator_result_cache_skips_dispatch(self, tmp_path):
        from datafusion_tpu.parallel.coordinator import DistributedContext

        paths = _write_partitions(tmp_path)
        proc, addr = _spawn_worker()
        try:
            dctx = _register_parts(DistributedContext([addr]), paths)
            want = sorted(collect(dctx.sql(DSQL)).to_rows())
            key = f"{addr[0]}:{addr[1]}"
            q_before = dctx.worker_status()[key]["queries"]
            rel = dctx.sql(DSQL)
            assert isinstance(rel, CachedResultRelation)
            assert sorted(collect(rel).to_rows()) == want
            assert dctx.worker_status()[key]["queries"] == q_before
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_fragment_cache_off_in_worker(self, tmp_path):
        from datafusion_tpu.parallel.coordinator import DistributedContext

        paths = _write_partitions(tmp_path)
        proc, addr = _spawn_worker(extra_env={"DATAFUSION_TPU_CACHE": "0"})
        try:
            dctx = _register_parts(
                DistributedContext([addr], result_cache=False), paths
            )
            base = _frag_hits()
            a = sorted(collect(dctx.sql(DSQL)).to_rows())
            b = sorted(collect(dctx.sql(DSQL)).to_rows())
            assert a == b
            assert _frag_hits() == base  # nothing served from cache
            status = dctx.worker_status()[f"{addr[0]}:{addr[1]}"]
            assert status["cache"]["fragment"] is None
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_rows_fragments_cached_too(self, tmp_path):
        from datafusion_tpu.parallel.coordinator import DistributedContext

        paths = _write_partitions(tmp_path)
        sql = "SELECT region, v FROM t WHERE v > 0"
        want = sorted(
            collect(
                _register_parts(ExecutionContext(device="cpu"), paths).sql(sql)
            ).to_rows()
        )
        proc, addr = _spawn_worker()
        try:
            dctx = _register_parts(
                DistributedContext([addr], result_cache=False), paths
            )
            base = _frag_hits()
            assert sorted(collect(dctx.sql(sql)).to_rows()) == want
            assert sorted(collect(dctx.sql(sql)).to_rows()) == want
            assert _frag_hits() - base >= 2
        finally:
            proc.terminate()
            proc.wait(timeout=10)


# -- background trace flusher ---------------------------------------------


class TestTraceFlusher:
    def test_flusher_appends_span_jsonl(self, tmp_path):
        from datafusion_tpu.obs import trace

        path = str(tmp_path / "spans.jsonl")
        assert trace.start_flusher(path, interval_s=0.02)
        try:
            with trace.session():
                with trace.span("flush.me", n=1):
                    pass
                with trace.span("flush.me.too"):
                    pass
            deadline = time.monotonic() + 5
            names: set = set()
            while time.monotonic() < deadline and not (
                {"flush.me", "flush.me.too"} <= names
            ):
                time.sleep(0.03)
                if os.path.exists(path):
                    with open(path, "r", encoding="utf-8") as f:
                        names = {json.loads(ln)["name"] for ln in f if ln.strip()}
            assert {"flush.me", "flush.me.too"} <= names
        finally:
            trace.stop_flusher(flush=False)

    def test_stop_flushes_to_started_path(self, tmp_path):
        # stop_flusher must flush to the path start_flusher was given
        # (not only the env var), and a stopped flusher must leave the
        # file JSONL — earlier flushed spans survive
        from datafusion_tpu.obs import trace

        path = str(tmp_path / "tail.jsonl")
        assert trace.start_flusher(path, interval_s=60)  # never ticks
        try:
            with trace.session():
                with trace.span("tail.span"):
                    pass
        finally:
            trace.stop_flusher(flush=True)
        with open(path, "r", encoding="utf-8") as f:
            names = [json.loads(ln)["name"] for ln in f if ln.strip()]
        assert "tail.span" in names

    def test_stop_is_idempotent(self):
        from datafusion_tpu.obs import trace

        trace.stop_flusher(flush=False)
        trace.stop_flusher(flush=False)
