"""Serving front door (datafusion_tpu/serve): admission control,
HBM-pinned resident tables, cross-query megabatching.

The concurrency contract under test:
- N client threads x mixed hot/cold tables -> exactly-once, correct
  results per client;
- admission-counter conservation: admitted + shed == submitted;
- pinned-table H2D skip: warm queries move ZERO bytes host->device
  (``device.h2d.transfers`` flat);
- eviction under a small ``DATAFUSION_TPU_HBM_BYTES`` cap, by pin
  priority/recency, with ``hbm`` sheds once nothing fits;
- megabatching: compatible concurrent plans fuse into one launch and
  de-multiplex per client;
- default-off: no serving behavior engages unless a Server is built.
"""

from __future__ import annotations

import gc
import os
import threading

import numpy as np
import pytest

from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.errors import QueryShedError
from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.datasource import MemoryDataSource
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.obs.device import LEDGER
from datafusion_tpu.utils.metrics import METRICS


def _table(seed: int, rows: int = 4096, batches: int = 4,
           groups: int = 16):
    rng = np.random.default_rng(seed)
    schema = Schema([
        Field("k", DataType.UTF8, False),
        Field("v", DataType.FLOAT64, False),
        Field("p", DataType.FLOAT64, False),
    ])
    d = StringDictionary()
    out = []
    for _ in range(batches):
        codes = d.encode([f"g{j}" for j in rng.integers(0, groups, rows)])
        v = np.round(rng.uniform(0, 100, rows), 2)
        p = np.round(rng.uniform(0, 1, rows), 3)
        out.append(make_host_batch(schema, [codes, v, p],
                                   dicts=[d, None, None]))
    return schema, MemoryDataSource(schema, out)


def _ctx(tables: dict) -> ExecutionContext:
    ctx = ExecutionContext(result_cache=False)
    for name, (schema, ds) in tables.items():
        ctx.register_datasource(name, ds)
    return ctx


def _q(table: str, lit: float) -> str:
    return (f"SELECT k, SUM(v), COUNT(1) FROM {table} "
            f"WHERE p < {lit} GROUP BY k")


@pytest.fixture(autouse=True)
def _no_hbm_cap():
    """Each test owns the capacity knob; start clean, restore after."""
    prior = os.environ.pop("DATAFUSION_TPU_HBM_BYTES", None)
    yield
    if prior is None:
        os.environ.pop("DATAFUSION_TPU_HBM_BYTES", None)
    else:
        os.environ["DATAFUSION_TPU_HBM_BYTES"] = prior


class TestServing:
    def test_megabatched_answers_match_serialized(self):
        ctx = _ctx({"t": _table(1)})
        lits = [0.2 + 0.05 * i for i in range(6)]
        want = {
            lit: sorted(collect(ctx.sql(_q("t", lit))).to_rows())
            for lit in lits
        }
        before = METRICS.counts.get("serve.megabatch_launches", 0)
        srv = ctx.serve(workers=2, window_s=0.02, megabatch_max=16)
        try:
            tickets = [(lit, srv.submit(_q("t", lit))) for lit in lits]
            for lit, t in tickets:
                got = sorted(t.result(timeout=60).to_rows())
                assert got == want[lit]
        finally:
            srv.stop()
        assert METRICS.counts.get("serve.megabatch_launches", 0) > before
        assert srv.admitted + srv.shed == srv.submitted

    def test_concurrent_clients_mixed_tables_exactly_once(self):
        ctx = _ctx({"hot": _table(2), "cold": _table(3)})
        # warm the hot table's pin + device copies first
        srv = ctx.serve(workers=2, window_s=0.005)
        results: dict = {}
        errors: list = []

        def client(i: int):
            table = "hot" if i % 3 else "cold"
            lit = 0.25 + 0.01 * i
            try:
                t = srv.submit(_q(table, lit))
                results[i] = sorted(t.result(timeout=120).to_rows())
            except Exception as e:  # noqa: BLE001 — asserted below
                errors.append((i, e))

        try:
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(12)]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=120)
        finally:
            srv.stop()
        assert not errors, errors
        assert len(results) == 12  # exactly one result per client
        for i, rows in results.items():
            table = "hot" if i % 3 else "cold"
            lit = 0.25 + 0.01 * i
            assert rows == sorted(
                collect(ctx.sql(_q(table, lit))).to_rows()
            ), f"client {i}"
        assert srv.admitted + srv.shed == srv.submitted
        assert srv.admitted == 12

    def test_warm_pinned_table_skips_h2d_entirely(self):
        ctx = _ctx({"t": _table(4)})
        srv = ctx.serve(workers=1, window_s=0.005)
        try:
            srv.submit(_q("t", 0.4)).result(timeout=60)  # cold: pins
            srv.submit(_q("t", 0.45)).result(timeout=60)  # warms ids
            before = METRICS.counts.get("device.h2d.transfers", 0)
            bytes_before = METRICS.counts.get("h2d.bytes", 0)
            for i in range(4):
                srv.submit(_q("t", 0.5 + 0.01 * i)).result(timeout=60)
            assert METRICS.counts.get("device.h2d.transfers", 0) == before
            assert METRICS.counts.get("h2d.bytes", 0) == bytes_before
        finally:
            srv.stop()
        assert "table:t" in LEDGER.pins_snapshot()

    def test_eviction_under_small_hbm_cap(self):
        from datafusion_tpu.serve import PinnedSource

        ctx = _ctx({"a": _table(5), "b": _table(6)})
        # drop pins left by earlier tests: eviction order must have
        # exactly one candidate (a) for the assertion below
        for fp in list(LEDGER.pins_snapshot()):
            LEDGER.unpin(fp)
        gc.collect()
        srv = ctx.serve(workers=1, window_s=0.005)
        try:
            # no cap yet: headroom unknown -> admission stays dormant
            srv.submit(_q("a", 0.4)).result(timeout=60)
            assert "table:a" in LEDGER.pins_snapshot()
            # cap sized so b cannot fit beside the current residency:
            # admitting b REQUIRES evicting a (the LEDGER is process
            # global, so the cap is measured relative to live bytes)
            est_b = PinnedSource(ctx.datasources["b"],
                                 "b").estimated_bytes()
            os.environ["DATAFUSION_TPU_HBM_BYTES"] = str(
                LEDGER.live_bytes() + est_b // 2
            )
            ev_before = METRICS.counts.get("device.pin_evictions", 0)
            srv.submit(_q("b", 0.4)).result(timeout=60)
            gc.collect()
            pins = LEDGER.pins_snapshot()
            assert "table:b" in pins and "table:a" not in pins
            assert METRICS.counts.get("device.pin_evictions", 0) \
                > ev_before
            # and with a cap nothing fits under, admission sheds "hbm"
            os.environ["DATAFUSION_TPU_HBM_BYTES"] = "1000"
            schema, ds = _table(7)
            ctx.register_datasource("c", ds)
            with pytest.raises(QueryShedError) as ei:
                srv.submit(_q("c", 0.4))
            assert ei.value.reason == "hbm"
        finally:
            srv.stop()
        assert srv.admitted + srv.shed == srv.submitted

    def test_queue_depth_shed(self):
        ctx = _ctx({"t": _table(8)})
        srv = ctx.serve(workers=1, window_s=0.005, queue_depth=2)
        try:
            # fill the queue beyond depth without letting the window
            # flush (submissions race the 5 ms window, so submit fast)
            shed = 0
            tickets = []
            for i in range(12):
                try:
                    tickets.append(srv.submit(_q("t", 0.3 + 0.01 * i)))
                except QueryShedError as e:
                    assert e.reason == "queue"
                    shed += 1
            for t in tickets:
                t.result(timeout=60)
        finally:
            srv.stop()
        assert shed >= 1
        assert srv.admitted + srv.shed == srv.submitted
        assert METRICS.counts.get("queries_shed", 0) >= shed

    def test_deadline_shed(self):
        ctx = _ctx({"t": _table(9)})
        srv = ctx.serve(workers=1, window_s=0.005)
        try:
            srv.submit(_q("t", 0.4)).result(timeout=60)  # seed the EWMA
            with pytest.raises(QueryShedError) as ei:
                srv.submit(_q("t", 0.41), deadline_s=0.0)
            assert ei.value.reason == "deadline"
        finally:
            srv.stop()
        assert srv.admitted + srv.shed == srv.submitted

    def test_megabatch_counters_and_launch_amortization(self):
        ctx = _ctx({"t": _table(10)})
        srv = ctx.serve(workers=1, window_s=0.05, megabatch_max=16)
        try:
            srv.submit(_q("t", 0.3)).result(timeout=60)  # pin + compile
            launches0 = METRICS.counts.get("device.launches", 0)
            mega0 = METRICS.counts.get("serve.megabatch_launches", 0)
            n = 8
            tickets = [srv.submit(_q("t", 0.4 + 0.01 * i))
                       for i in range(n)]
            for t in tickets:
                t.result(timeout=120)
            launches = METRICS.counts.get("device.launches", 0) - launches0
            assert METRICS.counts.get("serve.megabatch_launches", 0) \
                > mega0
            # the batched phase runs N queries in fewer than N launches
            assert launches < n, f"{launches} launches for {n} queries"
        finally:
            srv.stop()

    def test_stop_sheds_queued_tickets_promptly(self):
        """A ticket still in the batching window when the server stops
        must fail promptly with a shutdown shed, not hang its client
        (the loop can exit before draining pending callbacks)."""
        import time

        ctx = _ctx({"t": _table(12)})
        # a huge window keeps the ticket parked in the dispatcher
        srv = ctx.serve(workers=1, window_s=30.0, megabatch_max=64)
        t = srv.submit(_q("t", 0.4))
        time.sleep(0.05)  # let the loop thread enqueue it
        srv.stop()
        with pytest.raises(QueryShedError) as ei:
            t.result(timeout=5.0)
        assert ei.value.reason == "shutdown"
        assert srv.admitted + srv.shed == srv.submitted

    def test_plan_error_keeps_conservation(self):
        """A statement that never plans (unknown table) enters neither
        side of admitted + shed == submitted."""
        from datafusion_tpu.errors import DataFusionError

        ctx = _ctx({"t": _table(13)})
        srv = ctx.serve(workers=1, window_s=0.005)
        try:
            with pytest.raises(DataFusionError):
                srv.submit("SELECT k FROM no_such_table GROUP BY k")
            assert (srv.submitted, srv.admitted, srv.shed) == (0, 0, 0)
            srv.submit(_q("t", 0.4)).result(timeout=60)
            assert srv.admitted + srv.shed == srv.submitted == 1
        finally:
            srv.stop()

    def test_default_off_path_untouched(self):
        """Without a Server, nothing serving-related engages: no pins,
        no serve counters, plain execution only."""
        ctx = _ctx({"t": _table(11)})
        pins0 = dict(LEDGER.pins_snapshot())
        q0 = METRICS.counts.get("queries_queued", 0)
        s0 = METRICS.counts.get("queries_shed", 0)
        rows = collect(ctx.sql(_q("t", 0.4))).to_rows()
        assert rows
        assert LEDGER.pins_snapshot() == pins0
        assert METRICS.counts.get("queries_queued", 0) == q0
        assert METRICS.counts.get("queries_shed", 0) == s0
        from datafusion_tpu.exec.datasource import MemoryDataSource

        assert type(ctx.datasources["t"]) is MemoryDataSource
