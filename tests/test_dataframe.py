"""DataFrame API tests (the reference's fluent-builder seed grown to a
full surface; the golden `test_df_udf_udt.csv` runs through it)."""

import os

import pytest

from datafusion_tpu import DataType, Field, Schema, lit, f
from datafusion_tpu.exec.context import ExecutionContext

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "test", "data")

UK_SCHEMA = Schema(
    [
        Field("city", DataType.UTF8, False),
        Field("lat", DataType.FLOAT64, False),
        Field("lng", DataType.FLOAT64, False),
    ]
)


@pytest.fixture()
def ctx():
    c = ExecutionContext(batch_size=4096)
    c.register_csv("uk_cities", os.path.join(DATA, "uk_cities.csv"),
                   UK_SCHEMA, has_header=False)
    return c


class TestDataFrame:
    def test_select_filter_matches_sql(self, ctx):
        df = ctx.table("uk_cities")
        got = (
            df.filter(df.col("lat").gt(lit(51.0)).and_(df.col("lat").lt(lit(53.0))))
            .select("city", "lat", "lng", df.col("lat") + df.col("lng"))
            .collect()
        )
        want = ctx.sql_collect(
            "SELECT city, lat, lng, lat + lng FROM uk_cities "
            "WHERE lat > 51.0 AND lat < 53"
        )
        assert got.to_rows() == want.to_rows()

    def test_aggregate_matches_sql(self, ctx):
        df = ctx.table("uk_cities")
        got = df.aggregate([], [f.min(df.col("lat")), f.max(df.col("lat")),
                                f.count(), f.avg(df.col("lng"))]).collect()
        want = ctx.sql_collect(
            "SELECT MIN(lat), MAX(lat), COUNT(1), AVG(lng) FROM uk_cities"
        )
        assert got.to_rows() == want.to_rows()

    def test_sort_limit(self, ctx):
        df = ctx.table("uk_cities")
        got = df.select("city", "lat").sort(df.col("lat").sort(asc=False)).limit(3).collect()
        want = ctx.sql_collect(
            "SELECT city, lat FROM uk_cities ORDER BY lat DESC LIMIT 3"
        )
        assert got.to_rows() == want.to_rows()

    def test_grouped_aggregate(self, tmp_path):
        path = tmp_path / "t.csv"
        path.write_text("k,v\na,1\nb,2\na,3\nb,4\nb,5\n")
        schema = Schema([Field("k", DataType.UTF8, False), Field("v", DataType.INT64, False)])
        c = ExecutionContext()
        c.register_csv("t", str(path), schema)
        df = c.table("t")
        got = df.aggregate(["k"], [f.sum(df.col("v")), f.count(df.col("v"))]).collect()
        assert sorted(got.to_rows()) == [("a", 4, 2), ("b", 11, 3)]

    def test_explain_pretty_print(self, ctx):
        df = ctx.table("uk_cities")
        text = df.filter(df.col("lat").gt(lit(51.0))).select("city").explain()
        assert "Projection" in text and "Selection" in text and "TableScan" in text

    def test_col_errors(self, ctx):
        from datafusion_tpu.errors import DataFusionError

        with pytest.raises(DataFusionError):
            ctx.table("uk_cities").col("nope")

    def test_df_udf_udt_golden(self):
        """The DataFrame twin of the golden test_sql_udf_udt query."""
        from datafusion_tpu.cli import make_context

        c = make_context()
        c.register_csv("uk_cities", os.path.join(DATA, "uk_cities.csv"),
                       UK_SCHEMA, has_header=False)
        df = c.table("uk_cities")
        pt = df.function("ST_Point", df.col("lat"), df.col("lng"))
        got = df.select(pt).collect()
        want = [l for l in open(os.path.join(DATA, "expected", "test_df_udf_udt.csv"),
                                encoding="utf-8").read().splitlines() if l]
        assert [r[0] for r in got.to_rows()] == want
