"""Cluster control plane (datafusion_tpu/cluster).

Covers the lease-KV state machine (grants, refresh piggyback, lazy
expiry with injectable time, epoch bumps on join/leave, event-log
truncation), client parity (the in-process client and the TCP service
run the same `handle_request`), the coordinator `MembershipView` (epoch
subscription, push watches, stale-view tolerance, gauges), the shared
result tier (wire snapshot roundtrip, binary-segment publish,
read-through install, write-behind publish, cross-coordinator warm
hit), the invalidation broadcast (worker fragment caches drop tagged
entries on the next lease refresh, well before TTL), multi-coordinator
convergence after a worker kill, and the chaos variants under
`testing/faults` (service partition, lease expiry, stale watch).

HA coverage (`TestReplication` / `TestFailoverChaos`): log-shipping
standbys, snapshot catch-up after truncation, lease-based election on
primary silence, term fencing (standby write rejection, stale-term
writes, revived-old-primary demotion), multi-endpoint client failover,
lease survival across promotion, post-failover warm shared-tier hits,
and automatic worker sync on membership epoch changes.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from datafusion_tpu.cache.result import CachedResult, CachedResultRelation
from datafusion_tpu.cache.store import CacheStore
from datafusion_tpu.errors import ExecutionError
from datafusion_tpu.cluster import (
    ClusterNode,
    ClusterState,
    LocalClusterClient,
    connect,
)
from datafusion_tpu.cluster.agent import WorkerClusterAgent
from datafusion_tpu.cluster.membership import MembershipView
from datafusion_tpu.cluster.shared_cache import (
    SharedResultTier,
    decode_result,
    encode_result,
)
from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.datasource import CsvDataSource
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.parallel.coordinator import (
    DistributedContext,
    HeartbeatMonitor,
)
from datafusion_tpu.parallel.partition import PartitionedDataSource
from datafusion_tpu.parallel.worker import serve
from datafusion_tpu.testing import faults
from datafusion_tpu.utils.metrics import METRICS


# -- state machine --------------------------------------------------------


class TestClusterState:
    def test_lease_bound_key_dies_with_lease(self):
        st = ClusterState()
        g = st.lease_grant(10.0, now=0.0)
        st.put("workers/a:1", {"addr": "a:1"}, lease=g["lease"], now=0.0)
        assert st.get("workers/a:1", now=5.0) is not None
        # past the TTL: lazy expiry sweeps the lease and its keys
        assert st.get("workers/a:1", now=10.5) is None
        assert st.membership(now=10.5)["workers"] == {}

    def test_refresh_extends_and_piggybacks_events(self):
        st = ClusterState()
        g = st.lease_grant(10.0, now=0.0)
        st.put("workers/a:1", {}, lease=g["lease"], now=0.0)
        out = st.lease_refresh(g["lease"], since=g["rev"], now=9.0)
        assert out["found"] and out["epoch"] == 1
        # the join event for our own key rides the refresh
        assert [e["kind"] for e in out["events"]] == ["join"]
        # refresh at t=9 extends to t=19
        assert st.get("workers/a:1", now=18.0) is not None
        assert st.get("workers/a:1", now=19.5) is None

    def test_epoch_bumps_on_join_and_leave_only(self):
        st = ClusterState()
        assert st.membership(now=0.0)["epoch"] == 0
        g = st.lease_grant(5.0, now=0.0)
        st.put("workers/a:1", {}, lease=g["lease"], now=0.0)
        assert st.membership(now=0.0)["epoch"] == 1
        # non-member keys and value updates don't move the epoch
        st.put("config/x", 1, now=0.0)
        st.put("workers/a:1", {"v": 2}, lease=g["lease"], now=0.0)
        assert st.membership(now=0.0)["epoch"] == 1
        st.lease_revoke(g["lease"], now=1.0)
        assert st.membership(now=1.0)["epoch"] == 2

    def test_expiry_emits_leave_event_with_reason(self):
        st = ClusterState()
        g = st.lease_grant(1.0, now=0.0)
        st.put("workers/a:1", {}, lease=g["lease"], now=0.0)
        out = st.events_since(0, now=2.0)
        kinds = [(e["kind"], e.get("reason")) for e in out["events"]]
        assert ("join", None) in kinds
        assert ("leave", "lease_expired") in kinds

    def test_event_log_truncation_flagged(self):
        st = ClusterState()
        for i in range(1100):
            st.invalidate(f"t{i}", now=0.0)
        out = st.events_since(1, now=0.0)
        assert out.get("truncated") is True
        assert len(out["events"]) <= 1024

    def test_invalidate_drops_tagged_results(self):
        st = ClusterState()
        st.result_put("fp1", {"snapshot": 1}, 10, tables=("t",))
        st.result_put("fp2", {"snapshot": 2}, 10, tables=("u",))
        out = st.invalidate("t", now=0.0)
        assert out["dropped"] == 1
        assert st.result_get("fp1") is None
        assert st.result_get("fp2") is not None

    def test_unknown_lease_put_rejected(self):
        st = ClusterState()
        with pytest.raises(KeyError):
            st.put("workers/a:1", {}, lease="nope", now=0.0)


# -- clients (in-process and TCP run the same handler) --------------------


class TestClients:
    def test_local_client_roundtrip(self):
        c = LocalClusterClient(ClusterState())
        assert c.ping()
        g = c.lease_grant(30.0)
        c.put("workers/x:1", {"addr": "x:1"}, lease=g["lease"])
        view = c.membership()
        assert view["epoch"] == 1 and "x:1" in view["workers"]
        assert c.get("workers/x:1")["addr"] == "x:1"
        assert c.range("workers/") == {"workers/x:1": {"addr": "x:1"}}
        assert c.lease_revoke(g["lease"])
        assert c.membership()["workers"] == {}

    def test_tcp_service_parity(self):
        from datafusion_tpu.cluster.service import serve as serve_cluster

        server = serve_cluster("127.0.0.1:0")
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            host, port = server.server_address[:2]
            c = connect(f"{host}:{port}")
            assert c.ping()
            g = c.lease_grant(30.0)
            c.put("workers/y:2", {"addr": "y:2"}, lease=g["lease"])
            assert c.membership()["workers"].keys() == {"y:2"}
            # the shared tier over TCP: value survives the wire
            assert c.result_put("fp", {"snapshot": {"n": 1}}, 8, ("t",))
            out = c.result_get("fp")
            assert out["found"] and out["value"]["snapshot"] == {"n": 1}
            assert c.invalidate("t")["dropped"] == 1
            status = c.status()
            assert status["epoch"] == 1
            assert 'name="cluster.epoch"' in status["prometheus"]
        finally:
            server.shutdown()
            server.server_close()

    def test_connect_shapes(self):
        st = ClusterState()
        local = connect(st)
        assert isinstance(local, LocalClusterClient)
        assert connect(local) is local
        with pytest.raises(TypeError):
            connect(42)

    def test_request_fault_site_is_a_partition(self):
        c = LocalClusterClient(ClusterState())
        with faults.scoped({"rules": [
            {"site": "cluster.request", "op": "raise",
             "exc": "ConnectionRefusedError", "count": 1},
        ]}):
            assert not c.ping()  # partition reports unhealthy, no raise
        assert c.ping()


# -- membership view ------------------------------------------------------


class TestMembershipView:
    def _cluster_with_worker(self):
        st = ClusterState()
        c = LocalClusterClient(st)
        g = c.lease_grant(30.0)
        c.put("workers/w:1", {"addr": "w:1"}, lease=g["lease"])
        return st, c, g

    def test_refresh_tracks_epoch_and_workers(self):
        _, c, g = self._cluster_with_worker()
        view = MembershipView(c)
        assert view.epoch == -1
        view.refresh()
        assert view.epoch == 1 and view.live_addresses() == {"w:1"}
        c.lease_revoke(g["lease"])
        view.refresh()
        assert view.epoch == 2 and view.live_addresses() == set()

    def test_poll_keeps_stale_view_through_partition(self):
        _, c, _ = self._cluster_with_worker()
        view = MembershipView(c)
        view.refresh()
        with faults.scoped({"rules": [
            {"site": "cluster.watch", "op": "raise",
             "exc": "ConnectionResetError", "count": 1},
        ]}):
            assert not view.poll()
        # stale view preserved, error counted, gauges stay coherent
        assert view.live_addresses() == {"w:1"}
        assert view.refresh_errors == 1
        g = view.gauges()
        assert g["cluster.workers_live"] == 1
        assert g["cluster.watch_errors"] == 1
        assert g["cluster.watch_lag_s"] >= 0
        assert view.poll()

    def test_view_matches_workers_by_resolved_address(self):
        """A handle configured as 'localhost' must match a worker that
        registered its bound '127.0.0.1' — a spelling mismatch would
        flap a live worker down every cycle."""
        from datafusion_tpu.parallel.coordinator import WorkerHandle

        st = ClusterState()
        c = LocalClusterClient(st)
        g = c.lease_grant(30.0)
        c.put("workers/127.0.0.1:9000", {}, lease=g["lease"])
        w = WorkerHandle("localhost", 9000)
        mon = HeartbeatMonitor([w], membership=MembershipView(c))
        mon.poll_once()
        assert w.alive

    def test_heartbeat_monitor_consumes_view(self):
        from datafusion_tpu.parallel.coordinator import WorkerHandle

        _, c, g = self._cluster_with_worker()
        view = MembershipView(c)
        w = WorkerHandle("w", 1)
        mon = HeartbeatMonitor([w], membership=view)
        mon.poll_once()
        assert w.alive
        c.lease_revoke(g["lease"])
        mon.poll_once()
        assert not w.alive  # no probe ran; the shared view decided
        # rejoin: a fresh lease re-admits without probation counting
        g2 = c.lease_grant(30.0)
        c.put("workers/w:1", {"addr": "w:1"}, lease=g2["lease"])
        mon.poll_once()
        assert w.alive


# -- shared result tier ---------------------------------------------------


def _snapshot(num_rows=3):
    return CachedResult(
        [np.arange(num_rows, dtype=np.int64),
         np.asarray([0, 1, 0][:num_rows], np.int32)],
        [None, np.asarray([True, False, True][:num_rows])],
        [None, ("x", "y")],
        num_rows,
        64,
    )


class TestSharedResultTier:
    def test_snapshot_wire_roundtrip(self):
        entry = _snapshot()
        back = decode_result(encode_result(entry))
        assert back.shared is True and back.num_rows == 3
        np.testing.assert_array_equal(back.columns[0], entry.columns[0])
        np.testing.assert_array_equal(back.validity[1], entry.validity[1])
        assert back.dict_values == [None, ("x", "y")]

    def test_read_through_installs_locally_without_echo(self):
        c = LocalClusterClient(ClusterState())
        tier = SharedResultTier(c)
        c.result_put(
            "fp", {"snapshot": encode_result(_snapshot()), "tables": ["t"]},
            64, ("t",),
        )
        store = CacheStore(1 << 20, name="rt")
        store.shared = tier
        published = METRICS.counts.get("coord.shared_cache_published", 0)
        got = store.get("fp")
        assert got is not None and got.shared
        assert store.entries == 1 and store.shared_hits == 1
        # the install must not re-publish (shared snapshots skip store())
        tier.flush()
        assert METRICS.counts.get(
            "coord.shared_cache_published", 0) == published
        # second get: purely local
        assert store.get("fp") is not None and store.shared_hits == 1
        tier.close()

    def test_write_behind_publishes(self):
        st = ClusterState()
        tier = SharedResultTier(LocalClusterClient(st))
        store = CacheStore(1 << 20, name="wb")
        store.shared = tier
        store.put("fp", _snapshot(), 64, tags=("t",))
        assert tier.flush(timeout_s=10.0)
        assert st.result_get("fp") is not None
        # a second store with a fresh local cache reads it back
        other = CacheStore(1 << 20, name="wb2")
        other.shared = SharedResultTier(LocalClusterClient(st))
        assert other.get("fp").shared
        tier.close()

    def test_partitioned_service_degrades_to_miss(self):
        tier = SharedResultTier(LocalClusterClient(ClusterState()))
        store = CacheStore(1 << 20, name="pt")
        store.shared = tier
        with faults.scoped({"rules": [
            {"site": "cluster.request", "op": "raise",
             "exc": "ConnectionResetError", "count": 1},
        ]}):
            assert store.get("fp") is None  # error -> miss, not raise
        tier.close()

    def test_non_snapshot_values_not_published(self):
        st = ClusterState()
        tier = SharedResultTier(LocalClusterClient(st))
        store = CacheStore(1 << 20, name="ns")
        store.shared = tier
        store.put("raw", {"not": "a snapshot"}, 8)
        tier.flush()
        assert st.result_get("raw") is None
        tier.close()


# -- chunked replay (satellite) -------------------------------------------


class TestChunkedReplay:
    def test_replay_respects_batch_size(self):
        entry = CachedResult(
            [np.arange(10, dtype=np.int64)], [None], [None], 10, 80
        )
        schema = Schema([Field("v", DataType.INT64, False)])
        rel = CachedResultRelation(schema, entry, "fp", batch_size=4)
        batches = list(rel.batches())
        assert [b.num_rows for b in batches] == [4, 4, 2]
        out = np.concatenate(
            [np.asarray(b.data[0])[: b.num_rows] for b in batches]
        )
        np.testing.assert_array_equal(out, np.arange(10))
        assert rel.stats.attrs.get("cache.batches") == 3

    def test_cached_repeat_streams_chunks_and_matches(self, tmp_path):
        schema = Schema([Field("v", DataType.INT64, False)])
        path = str(tmp_path / "v.csv")
        with open(path, "w") as f:
            f.write("v\n" + "\n".join(str(i) for i in range(1000)) + "\n")
        from datafusion_tpu import cache as qcache

        with qcache.configured(enabled=True):
            ctx = ExecutionContext(device="cpu", batch_size=256)
            ctx.register_csv("t", path, schema)
            cold = sorted(collect(ctx.sql("SELECT v FROM t WHERE v < 999")).to_rows())
            rel = ctx.sql("SELECT v FROM t WHERE v < 999")
            assert isinstance(rel, CachedResultRelation)
            batches = list(rel.batches())
            assert len(batches) == 4  # 999 rows in 256-row chunks
            assert all(b.num_rows <= 256 for b in batches)
            rel2 = ctx.sql("SELECT v FROM t WHERE v < 999")
            assert sorted(collect(rel2).to_rows()) == cold


# -- integration: workers + coordinators over one control plane ----------


DSCHEMA = Schema(
    [Field("region", DataType.UTF8, False), Field("v", DataType.INT64, False)]
)
DSQL = "SELECT region, COUNT(1), SUM(v) FROM t GROUP BY region"


def _write_parts(tmp_path, n=2, rows=400):
    rng = np.random.default_rng(11)
    paths = []
    for p in range(n):
        path = tmp_path / f"part{p}.csv"
        with open(path, "w") as f:
            f.write("region,v\n")
            for _ in range(rows):
                f.write(f"r{rng.integers(0, 4)},{rng.integers(-50, 50)}\n")
        paths.append(str(path))
    return paths


def _register(ctx, paths):
    ctx.register_datasource(
        "t",
        PartitionedDataSource(
            [CsvDataSource(p, DSCHEMA, True, 131072) for p in paths]
        ),
    )
    return ctx


class _Cluster:
    """Two in-process workers registered on one shared ClusterState."""

    def __init__(self, ttl_s=1.0):
        self.state = ClusterState()
        self.client = LocalClusterClient(self.state)
        self.servers = []
        for _ in range(2):
            server = serve("127.0.0.1:0", device="cpu",
                           cluster=self.client, lease_ttl_s=ttl_s)
            threading.Thread(target=server.serve_forever, daemon=True).start()
            self.servers.append(server)

    def agent(self, i):
        return self.servers[i].worker_state.cluster_agent

    def kill(self, i):
        """Abrupt worker death: no lease revocation — the TTL must
        notice (SIGKILL semantics, in-process)."""
        self.agent(i).stop()
        self.servers[i].shutdown()
        self.servers[i].server_close()

    def close(self):
        for server in self.servers:
            agent = server.worker_state.cluster_agent
            if agent is not None:
                agent.close()
            try:
                server.shutdown()
                server.server_close()
            except OSError:
                pass


@pytest.fixture()
def cluster():
    c = _Cluster()
    try:
        yield c
    finally:
        c.close()


class TestClusterIntegration:
    def test_worker_discovery_from_membership(self, cluster, tmp_path):
        paths = _write_parts(tmp_path)
        want = sorted(
            collect(_register(ExecutionContext(device="cpu"), paths).sql(DSQL))
            .to_rows()
        )
        with DistributedContext(cluster=cluster.client,
                                result_cache=False) as ctx:
            assert len(ctx.workers) == 2  # no explicit worker list
            _register(ctx, paths)
            assert sorted(collect(ctx.sql(DSQL)).to_rows()) == want

    def test_two_coordinators_converge_after_kill(self, cluster, tmp_path):
        """The acceptance bar: a worker dies abruptly; both coordinators
        observe the SAME bumped epoch within one lease TTL, and their
        heartbeat monitors flip the dead worker without probing."""
        paths = _write_parts(tmp_path)
        ca = DistributedContext(cluster=cluster.client, result_cache=False)
        cb = DistributedContext(cluster=cluster.client, result_cache=False)
        try:
            e0 = ca.cluster_epoch()
            assert e0 == cb.cluster_epoch() == 2  # two joins
            killed_addr = cluster.agent(0).addr
            cluster.kill(0)
            deadline = time.monotonic() + 5.0  # TTL 1s + CI slack
            while time.monotonic() < deadline:
                ca.cluster_epoch()
                if killed_addr not in ca.membership.live_addresses():
                    break
                time.sleep(0.05)
            # both coordinators observe the same bumped epoch from the
            # same shared view (>= tolerates unrelated churn of the
            # survivor's lease under a stalled CI machine)
            assert ca.cluster_epoch() >= e0 + 1
            assert cb.cluster_epoch() == ca.cluster_epoch()
            assert killed_addr not in cb.membership.live_addresses()
            mon_a = HeartbeatMonitor(ca.workers, membership=ca.membership)
            mon_a.poll_once()
            assert sum(w.alive for w in ca.workers) == 1
            # queries keep working on the survivor
            want = sorted(
                collect(
                    _register(ExecutionContext(device="cpu"), paths).sql(DSQL)
                ).to_rows()
            )
            _register(ca, paths)
            assert sorted(collect(ca.sql(DSQL)).to_rows()) == want
        finally:
            ca.close()
            cb.close()

    def test_shared_tier_warm_hit_across_coordinators(self, cluster, tmp_path):
        """A query warm in coordinator A's result cache is a shared-tier
        hit in coordinator B: no fragment dispatch, `cache.shared=True`
        in the replay relation, `coord.shared_cache_hits` counted."""
        from datafusion_tpu import cache as qcache

        paths = _write_parts(tmp_path)
        with qcache.configured(enabled=True):
            ca = DistributedContext(cluster=cluster.client)
            cb = DistributedContext(cluster=cluster.client)
            try:
                _register(ca, paths)
                _register(cb, paths)
                want = sorted(collect(ca.sql(DSQL)).to_rows())
                assert ca._shared_tier.flush(timeout_s=10.0)
                base = METRICS.counts.get("coord.shared_cache_hits", 0)
                rel = cb.sql(DSQL)
                assert isinstance(rel, CachedResultRelation)
                assert rel.entry.shared
                assert "cache.shared" in rel.stats.attrs
                assert sorted(collect(rel).to_rows()) == want
                assert METRICS.counts["coord.shared_cache_hits"] == base + 1
                # B's stats history records the warm run as a hit
                runs = cb.stats_history(cb.last_fingerprint)
                assert runs and runs[-1]["cache_hit"] is True
            finally:
                ca.close()
                cb.close()

    def test_invalidation_broadcast_beats_ttl(self, cluster, tmp_path):
        """A worker's stale fragment-cache entry dies on the lease
        refresh FOLLOWING the broadcast — the fragment cache TTL (5
        minutes by default) never has to pass."""
        paths = _write_parts(tmp_path)
        with DistributedContext(cluster=cluster.client,
                                result_cache=False) as ctx:
            _register(ctx, paths)
            collect(ctx.sql(DSQL))
            caches = [s.worker_state.fragment_cache for s in cluster.servers]
            assert sum(c.entries for c in caches) >= 2  # one per partition
            dropped_shared = ctx.broadcast_invalidate("t")
            assert dropped_shared == 0  # result cache off in this test
            for i in range(2):
                cluster.agent(i).poll_once()  # the next heartbeat
            assert all(c.entries == 0 for c in caches)
            assert METRICS.counts.get(
                "worker.cluster_invalidations_applied", 0) >= 2

    def test_reregistration_broadcasts(self, cluster, tmp_path):
        paths = _write_parts(tmp_path)
        with DistributedContext(cluster=cluster.client,
                                result_cache=False) as ctx:
            _register(ctx, paths)
            collect(ctx.sql(DSQL))
            caches = [s.worker_state.fragment_cache for s in cluster.servers]
            assert sum(c.entries for c in caches) >= 2
            _register(ctx, paths)  # re-register the same name
            for i in range(2):
                cluster.agent(i).poll_once()
            assert all(c.entries == 0 for c in caches)

    def test_lease_expiry_chaos_reregisters(self, cluster):
        """Chaos: injected heartbeat failures outlast the TTL; the lease
        expires (leave event, epoch bump), and the recovering agent
        re-registers with a cleared fragment cache (it may have missed
        invalidations while deregistered)."""
        agent = cluster.agent(0)
        agent.stop()  # drive the heartbeat by hand
        cache = cluster.servers[0].worker_state.fragment_cache
        cache.put("stale", b"x", 1)
        view = MembershipView(cluster.client).refresh()
        e0 = view.epoch
        with faults.scoped({"rules": [
            {"site": "cluster.lease.refresh", "op": "raise",
             "exc": "ConnectionResetError", "count": 3,
             "where": {"addr": agent.addr}},
        ]}):
            for _ in range(3):
                with pytest.raises(ConnectionError):
                    agent.poll_once()
        # hold the OTHER worker's lease alive while this one lapses
        time.sleep(1.1)
        cluster.agent(1).poll_once()
        view = MembershipView(cluster.client).refresh()
        assert view.epoch > e0  # the leave was observed fleet-wide
        assert agent.addr not in view.live_addresses()
        agent.poll_once()  # recovery: re-register
        assert agent.reregistrations == 1
        assert cache.entries == 0  # suspect cache cleared on resync
        view.refresh()
        assert agent.addr in view.live_addresses()

    def test_off_means_off(self, tmp_path, monkeypatch):
        """No cluster configured: no client, no membership, no shared
        tier, no new threads — the existing paths byte-identical."""
        monkeypatch.delenv("DATAFUSION_TPU_CLUSTER", raising=False)
        ctx = DistributedContext([("127.0.0.1", 1)], result_cache=False)
        assert ctx.cluster is None and ctx.membership is None
        assert ctx._shared_tier is None
        with pytest.raises(ExecutionError):
            ctx.cluster_epoch()
        assert ctx.sync_workers() == []
        assert ctx.broadcast_invalidate("t") == 0
        server = serve("127.0.0.1:0", device="cpu")
        try:
            assert server.worker_state.cluster_agent is None
        finally:
            server.server_close()

    def test_worker_status_and_gauges_carry_cluster_block(self, cluster):
        state = cluster.servers[0].worker_state
        snap = state.status()["cluster"]
        assert snap["registered"] and snap["lease_age_s"] is not None
        gauges = state._gauges()
        assert gauges["cluster.lease_ttl_s"] == 1.0
        assert gauges["cluster.lease_age_s"] >= 0

    def test_coordinator_metrics_text_has_cluster_gauges(self, cluster):
        with DistributedContext(cluster=cluster.client,
                                result_cache=False) as ctx:
            text = ctx.metrics_text()
            assert 'name="cluster.epoch"' in text
            assert 'name="cluster.watch_lag_s"' in text
            # the fleet telemetry gauges ride the same scrape
            assert 'name="fleet.nodes"' in text

    def test_sync_workers_discovers_late_joiner(self, cluster):
        with DistributedContext(cluster=cluster.client,
                                result_cache=False) as ctx:
            assert len(ctx.workers) == 2
            server = serve("127.0.0.1:0", device="cpu",
                           cluster=cluster.client, lease_ttl_s=1.0)
            try:
                added = ctx.sync_workers()
                assert len(added) == 1 and len(ctx.workers) == 3
                assert ctx.sync_workers() == []  # idempotent
            finally:
                server.worker_state.cluster_agent.close()
                server.server_close()


# -- replication / failover (control-plane HA) ----------------------------


def _pair(election_timeout_s=1.0):
    """Primary + standby nodes over separate states, in-process."""
    a = ClusterNode(addr="a:1")
    b = ClusterNode(addr="b:2", standby_of=a,
                    election_timeout_s=election_timeout_s)
    return a, b, LocalClusterClient([a, b])


class TestReplication:
    def test_standby_tails_primary_log(self):
        a, b, client = _pair()
        g = client.lease_grant(30.0)
        client.put("workers/w:9", {"addr": "w:9"}, lease=g["lease"])
        client.put("config/x", 42)
        client.invalidate("t")
        applied = b.replicate_once()
        assert applied >= 4  # grant + join + put + invalidate
        assert b.state._rev == a.state._rev
        assert b.state.get("config/x") == 42
        assert b.state.membership()["workers"].keys() == {"w:9"}
        assert b.state.membership()["epoch"] == a.state.membership()["epoch"]
        assert b.replication_lag_revisions == 0

    def test_result_tier_replicates_with_values(self):
        a, b, client = _pair()
        entry = _snapshot()
        client.result_publish("fp", entry, 64, ("t",))
        b.replicate_once()
        stored = b.state.result_get("fp")
        assert stored is not None
        np.testing.assert_array_equal(
            stored["snapshot"]["columns"][0], entry.columns[0]
        )

    def test_snapshot_catchup_after_truncation(self):
        a, b, client = _pair()
        g = client.lease_grant(30.0)
        client.put("workers/w:9", {"addr": "w:9"}, lease=g["lease"])
        for i in range(1200):  # blow past the 1024-event window
            client.invalidate(f"t{i}")
        assert b.replicate_once() == -1  # full snapshot, not a tail
        assert b.snapshots_applied == 1
        assert b.state._rev == a.state._rev
        assert b.state.membership()["workers"].keys() == {"w:9"}
        # incremental shipping resumes after the snapshot
        client.put("config/x", 1)
        assert b.replicate_once() >= 1
        assert b.state.get("config/x") == 1

    def test_standby_rejects_reads_and_writes(self):
        a, b, _ = _pair()
        out = b.handle_request({"type": "kv_put", "key": "k", "value": 1})
        assert out.get("code") == "not_primary"
        assert out.get("primary") == "a:1"  # the redirect hint
        out = b.handle_request({"type": "membership"})
        assert out.get("code") == "not_primary"
        # ping and status still answer (health checks, operators)
        assert b.handle_request({"type": "ping"})["type"] == "pong"
        assert b.handle_request({"type": "status"})["role"] == "standby"

    def test_promotion_on_primary_silence_rearms_leases(self):
        a, b, client = _pair(election_timeout_s=1.0)
        g = client.lease_grant(2.0)
        client.put("workers/w:9", {}, lease=g["lease"])
        b.replicate_once()
        a.partitioned = True
        now = time.monotonic()
        with pytest.raises(ConnectionError):
            b.replicate_once()
        assert not b.maybe_promote(now=now)  # silence too short
        assert b.maybe_promote(now=now + 1.5)
        assert b.role == "primary" and b.term == 2
        # the replicated lease survived the takeover with a fresh TTL
        resp = LocalClusterClient(b).lease_refresh(g["lease"])
        assert resp["found"] and resp["term"] == 2

    def test_election_fault_site_aborts_promotion(self):
        a, b, _ = _pair(election_timeout_s=0.5)
        a.partitioned = True
        now = time.monotonic() + 10.0
        with faults.scoped({"rules": [
            {"site": "cluster.election", "op": "raise",
             "exc": "ExecutionError", "count": 1},
        ]}):
            with pytest.raises(ExecutionError):
                b.maybe_promote(now=now)
            assert b.role == "standby"  # the aborted round changed nothing
        assert b.maybe_promote(now=now)

    def test_replicate_fault_site_is_transient(self):
        a, b, _ = _pair()
        a.state.put("config/x", 1)
        with faults.scoped({"rules": [
            {"site": "cluster.replicate", "op": "raise",
             "exc": "ConnectionResetError", "count": 1},
        ]}):
            with pytest.raises(ConnectionError):
                b.replicate_once()
        b.replicate_once()  # the next round catches up
        assert b.state.get("config/x") == 1

    def test_stale_term_write_rejected_and_old_primary_demoted(self):
        """The split-brain fence: standby promotes past a partitioned
        primary; the revived old primary is demoted on its first term
        exchange, and a write stamped with its stale term is refused."""
        from datafusion_tpu.errors import StaleTermError

        a, b, client = _pair(election_timeout_s=0.5)
        client.put("config/x", 1)
        b.replicate_once()
        a.partitioned = True
        assert b.maybe_promote(now=time.monotonic() + 10.0)
        a.partitioned = False  # the old primary revives, still term 1
        old_term = a.term
        assert a.role == "primary" and old_term < b.term
        # a write carrying the deposed term is fenced
        out = b.handle_request({"type": "kv_put", "key": "boom",
                                "value": 1, "term": old_term})
        assert out.get("code") == "stale_term"
        with pytest.raises(StaleTermError):
            LocalClusterClient(b).request(
                {"type": "kv_put", "key": "boom", "value": 1,
                 "term": old_term}
            )
        assert b.state.get("boom") is None
        assert METRICS.counts.get("cluster.stale_term_writes_rejected", 0) >= 1
        # the term exchange demotes the old primary...
        b.handle_request({"type": "replicate_pull", "since": a.state._rev,
                          "term": a.term, "addr": "a:1"})  # b keeps primacy
        a.handle_request({"type": "peer_status", "term": b.term,
                          "role": "primary", "addr": "b:2"})
        assert a.role == "standby" and a.term == b.term
        # ...and it resyncs FROM the new primary via a full snapshot
        a.retarget(b)  # in-process: dial the node, not "b:2"
        assert a.replicate_once() == -1
        assert a.state._rev == b.state._rev

    def test_standby_refuses_replication_pulls(self):
        """A deposed/never-primary node must not feed the log: the
        puller gets the redirect hint instead of silently tailing a
        non-primary (which would also defer its election forever)."""
        a, b, _ = _pair()
        out = a.handle_request({"type": "replicate_pull", "since": 0,
                                "term": b.term, "addr": "b:2"})
        assert out["type"] == "replicate"  # primary serves pulls
        out = b.handle_request({"type": "replicate_pull", "since": 0,
                                "term": 1, "addr": "c:3"})
        assert out.get("code") == "not_primary"
        assert out.get("primary") == "a:1"  # chase this instead

    def test_configured_workers_never_auto_retired(self):
        """Explicitly configured handles are the operator's call: an
        epoch change must not remove them even when the membership
        view has never seen them (only flip them via the monitor)."""
        st = ClusterState()
        c = LocalClusterClient(st)
        g1, g2 = c.lease_grant(30.0), c.lease_grant(30.0)
        c.put("workers/10.0.0.8:1", {}, lease=g1["lease"])
        c.put("workers/10.0.0.9:1", {}, lease=g2["lease"])
        ctx = DistributedContext([("203.0.113.7", 4)], cluster=c,
                                 result_cache=False)
        try:
            assert len(ctx.workers) == 1 and not ctx.workers[0].discovered
            ctx.sync_workers()  # folds the registered workers in
            addrs = {f"{w.host}:{w.port}" for w in ctx.workers}
            assert addrs == {"203.0.113.7:4", "10.0.0.8:1", "10.0.0.9:1"}
            c.lease_revoke(g2["lease"])  # one registered worker leaves
            ctx.sync_workers()
            addrs = {f"{w.host}:{w.port}" for w in ctx.workers}
            # discovered leaver retired; configured handle untouched
            # even though the (non-empty) view has never seen it
            assert addrs == {"203.0.113.7:4", "10.0.0.8:1"}
        finally:
            ctx.close()

    def test_rev_regression_after_failover_clears_worker_cache(self):
        """A failover can land on a standby whose log was BEHIND the
        revision a worker had already consumed; events the new primary
        issues inside that gap are filtered out of every future tail
        (`since` is too high) — unobservable, like a truncation — so
        the worker must treat its fragment cache as suspect."""

        class _FakeWorkerState:
            batch_size = 4
            fragment_cache = CacheStore(1 << 20, name="rvreg")

        a, b, client = _pair(election_timeout_s=0.5)
        ws = _FakeWorkerState()
        agent = WorkerClusterAgent(client, "w:1", ws, ttl_s=30.0)
        agent.poll_once()  # register on the primary
        b.replicate_once()  # standby mirrors the registration...
        for i in range(5):  # ...but NOT these: the unreplicated tail
            client.invalidate(f"gap{i}")
        agent.poll_once()  # the worker consumed the tail (last_rev high)
        ws.fragment_cache.put("stale", b"x", 1, tags=("events",))
        a.partitioned = True
        assert b.maybe_promote(now=time.monotonic() + 10.0)
        # an invalidation on the new primary lands INSIDE the gap the
        # worker's cursor already skipped past
        client.invalidate("events")
        assert b.state._rev < agent.last_rev
        agent.poll_once()
        assert ws.fragment_cache.entries == 0  # suspect cache cleared
        assert METRICS.counts.get("worker.cluster_rev_regressions", 0) >= 1

    def test_client_failover_and_redirect(self):
        a, b, client = _pair(election_timeout_s=0.5)
        b.replicate_once()
        a.partitioned = True
        assert b.maybe_promote(now=time.monotonic() + 10.0)
        base = METRICS.counts.get("cluster.client_failovers", 0)
        # endpoint sweep: a (dead) -> b (promoted) without the caller
        # seeing anything but the answer
        rev = client.put("config/y", 7)
        assert rev > 0 and b.state.get("config/y") == 7
        assert METRICS.counts.get("cluster.client_failovers", 0) > base
        # subsequent requests start at the promoted endpoint (sticky)
        assert client.nodes[client._active % 2] is b

    def test_redirect_hint_follows_primary(self):
        a, b, client = _pair()
        b.replicate_once()
        # ask the standby FIRST: the not_primary redirect must land on a
        client._active = 1
        assert client.put("config/z", 3) > 0
        assert a.state.get("config/z") == 3
        assert METRICS.counts.get("cluster.client_redirects", 0) >= 1

    def test_watch_unparks_on_event(self):
        a, _, client = _pair()
        rev0 = a.state._rev
        got = {}

        def park():
            got.update(client.watch(rev0, timeout_s=5.0))

        t = threading.Thread(target=park)
        t.start()
        time.sleep(0.1)
        t0 = time.monotonic()
        client.invalidate("t")
        t.join(timeout=5.0)
        assert time.monotonic() - t0 < 2.0  # pushed, not polled
        assert got.get("fired") is True
        assert [e["kind"] for e in got["events"]] == ["invalidate"]
        assert "workers" in got  # membership piggybacks on the answer

    def test_watch_timeout_returns_fresh_membership(self):
        a, _, client = _pair()
        g = client.lease_grant(30.0)
        client.put("workers/w:9", {}, lease=g["lease"])
        rev0 = a.state._rev
        out = client.watch(rev0, timeout_s=0.05)
        assert out.get("fired") is False
        assert out["events"] == [] and "w:9" in out["workers"]

    def test_membership_view_watch_and_subscribe(self):
        a, _, client = _pair()
        view = MembershipView(client)
        view.refresh()
        seen = []
        view.subscribe(lambda v: seen.append(v.epoch))
        g = client.lease_grant(30.0)

        def join_later():
            time.sleep(0.1)
            client.put("workers/w:9", {}, lease=g["lease"])

        t = threading.Thread(target=join_later)
        t.start()
        assert view.watch(timeout_s=5.0)
        t.join()
        if not seen:  # the watch can race the put; one more park settles it
            assert view.watch(timeout_s=5.0)
        assert seen and view.live_addresses() == {"w:9"}
        assert view.term >= 1

    def test_replicated_state_serves_clients_after_promotion(self):
        """The acceptance path in miniature: writes land on the primary,
        the standby promotes, and every consumer-visible read (KV,
        membership, events, shared tier) answers identically."""
        a, b, client = _pair(election_timeout_s=0.5)
        g = client.lease_grant(30.0)
        client.put("workers/w:9", {"addr": "w:9"}, lease=g["lease"])
        client.result_publish("fp", _snapshot(), 64, ("t",))
        b.replicate_once()
        a.partitioned = True
        assert b.maybe_promote(now=time.monotonic() + 10.0)
        assert client.membership()["workers"].keys() == {"w:9"}
        fetched = client.result_fetch("fp")
        assert fetched is not None and fetched[0].shared
        tail = client.events_since(0)
        assert any(e["kind"] == "join" for e in tail["events"])


class TestWatchResume:
    """Watch resumption tokens: every answer carries {term, rev}; a
    watcher replaying it gets `resumed: True` iff the answering node
    can PROVE no client-visible events were missed."""

    def test_answer_carries_token_and_client_replays_it(self):
        state = ClusterState()
        client = LocalClusterClient(state)
        out = client.watch(0, timeout_s=0)
        tok = out["resume"]
        assert tok["rev"] == state._rev and tok["term"] == state.term
        assert "resumed" not in out  # first watch: nothing to prove
        client.invalidate("t")
        out2 = client.watch(tok["rev"], timeout_s=0)
        assert out2["resumed"] is True  # proof: log covers the token
        assert out2["fired"] and out2["events"]
        assert client.last_watch_resume == out2["resume"]

    def test_resume_proves_continuity_across_promotion(self):
        a, b, client = _pair()
        client.invalidate("warm")
        out = client.watch(0, timeout_s=0)
        assert out["resume"]["term"] == 1
        b.replicate_once()  # promoted log holds every acked revision
        a.partitioned = True
        assert b.maybe_promote(now=time.monotonic() + 10.0)
        out2 = client.watch(out["resume"]["rev"], timeout_s=0)
        # the failover sweep landed on b, which proves continuity
        assert out2["resumed"] is True
        assert out2["term"] == 2 and out2["resume"]["term"] == 2

    def test_resume_fails_on_lagging_promoted_log(self):
        a, b, client = _pair()
        b.replicate_once()
        client.invalidate("acked-but-unreplicated")
        out = client.watch(0, timeout_s=0)
        a.partitioned = True  # b never saw the last events
        assert b.maybe_promote(now=time.monotonic() + 10.0)
        out2 = client.watch(out["resume"]["rev"], timeout_s=0)
        assert out2["resumed"] is False  # proof fails: must resync
        assert METRICS.counts.get("cluster.client_watch_resyncs", 0) >= 1

    def test_resume_fails_past_truncated_window(self):
        state = ClusterState()
        client = LocalClusterClient(state)
        client.invalidate("t0")
        out = client.watch(0, timeout_s=0)
        for i in range(1200):  # blow past the 1024-event window
            client.invalidate(f"t{i}")
        out2 = client.watch(out["resume"]["rev"], timeout_s=0)
        assert out2["resumed"] is False
        assert out2.get("truncated")


class TestBinaryPublish:
    def test_tcp_publish_uses_raw_segments_not_base64(self):
        """Satellite: shared-tier snapshots cross the wire as binary RAW
        segments; `coord.shared_cache_publish_bytes` proves the cost is
        ~the raw bytes, not raw * 4/3."""
        from datafusion_tpu.cluster.service import serve as serve_cluster

        server = serve_cluster("127.0.0.1:0")
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            host, port = server.server_address[:2]
            client = connect(f"{host}:{port}")
            cols = [np.arange(100_000, dtype=np.int64)]
            raw_bytes = cols[0].nbytes
            entry = CachedResult(cols, [None], [None], 100_000, raw_bytes)
            tier = SharedResultTier(client)
            store = CacheStore(1 << 24, name="bin")
            store.shared = tier
            base = METRICS.counts.get("coord.shared_cache_publish_bytes", 0)
            store.put("fp-big", entry, raw_bytes, tags=("t",))
            assert tier.flush(timeout_s=20.0)
            sent = METRICS.counts["coord.shared_cache_publish_bytes"] - base
            assert 0 < sent < raw_bytes * 1.05  # base64 would be ~1.33x
            # and the fetch roundtrips through the binary frames
            other = CacheStore(1 << 24, name="bin2")
            other.shared = SharedResultTier(client)
            got = other.get("fp-big")
            assert got is not None and got.shared
            np.testing.assert_array_equal(got.columns[0], cols[0])
            tier.close()
        finally:
            server.shutdown()
            server.server_close()


class TestFailoverChaos:
    """Satellite: kill the primary mid-workload under seeded faults and
    prove the fleet never notices — standby promotes within one lease
    TTL, no lease is lost, the warm shared tier survives, and the
    revived old primary is fenced."""

    def test_primary_kill_mid_workload(self, tmp_path):
        from datafusion_tpu import cache as qcache

        paths = _write_parts(tmp_path)
        a = ClusterNode(addr="a:1")
        b = ClusterNode(addr="b:2", standby_of=a, election_timeout_s=0.5)
        client = LocalClusterClient([a, b])
        servers = []
        with qcache.configured(enabled=True):
            for _ in range(2):
                server = serve("127.0.0.1:0", device="cpu",
                               cluster=client, lease_ttl_s=1.0)
                threading.Thread(target=server.serve_forever,
                                 daemon=True).start()
                servers.append(server)
            ctx = DistributedContext(cluster=client)
            try:
                _register(ctx, paths)
                want = sorted(collect(ctx.sql(DSQL)).to_rows())
                assert ctx._shared_tier.flush(timeout_s=10.0)
                b.replicate_once()
                leases = [s.worker_state.cluster_agent.lease
                          for s in servers]
                # seeded chaos riding along: the standby's first
                # replication pull after the kill fails transiently
                with faults.scoped({"seed": 11, "rules": [
                    {"site": "cluster.replicate", "op": "raise",
                     "exc": "ConnectionResetError", "count": 1},
                ]}):
                    a.partitioned = True  # SIGKILL, in-process
                    with pytest.raises(ConnectionError):
                        b.replicate_once()
                    assert b.maybe_promote(now=time.monotonic() + 1.0)
                assert b.term == 2
                # every worker heartbeat lands on the new primary with
                # its ORIGINAL lease — nothing was lost in the handoff
                for server, lease in zip(servers, leases):
                    agent = server.worker_state.cluster_agent
                    agent.poll_once()
                    assert agent.lease == lease
                    assert agent.reregistrations == 0
                    assert agent.term == 2
                # membership rode over: same worker set, same epoch
                assert ctx.cluster_epoch() == 2
                assert len(ctx.membership.live_addresses()) == 2
                # a second coordinator's warm shared-tier hit still
                # lands — the replicated result tier survived the kill
                cb = DistributedContext(cluster=client)
                try:
                    _register(cb, paths)
                    rel = cb.sql(DSQL)
                    assert isinstance(rel, CachedResultRelation)
                    assert rel.entry.shared
                    assert sorted(collect(rel).to_rows()) == want
                finally:
                    cb.close()
                # queries keep completing post-failover (zero failed):
                # a FRESH fingerprint forces a real fragment dispatch
                cold = ctx.sql(
                    "SELECT region, COUNT(1) FROM t GROUP BY region"
                )
                assert not isinstance(cold, CachedResultRelation)
                assert len(collect(cold).to_rows()) == len(want)
                # the revived old primary is fenced, not obeyed
                a.partitioned = False
                out = b.handle_request({"type": "kv_put", "key": "boom",
                                        "value": 1, "term": 1})
                assert out.get("code") == "stale_term"
                a.handle_request({"type": "peer_status", "term": b.term,
                                  "role": "primary", "addr": "b:2"})
                assert a.role == "standby"
            finally:
                ctx.close()
                for server in servers:
                    agent = server.worker_state.cluster_agent
                    if agent is not None:
                        agent.close()
                    server.shutdown()
                    server.server_close()

    def test_auto_worker_sync_on_epoch_change(self, cluster):
        """Satellite: the epoch-change callback folds joiners in and
        retires leavers without any sync_workers() call."""
        with DistributedContext(cluster=cluster.client,
                                result_cache=False) as ctx:
            assert len(ctx.workers) == 2
            late = serve("127.0.0.1:0", device="cpu",
                         cluster=cluster.client, lease_ttl_s=1.0)
            threading.Thread(target=late.serve_forever, daemon=True).start()
            try:
                # any view consumer observes the epoch move; the
                # subscription folds the joiner — no sync_workers()
                deadline = time.monotonic() + 5.0
                while len(ctx.workers) < 3:
                    ctx.cluster_epoch()
                    if time.monotonic() > deadline:
                        raise AssertionError(f"never folded: {ctx.workers}")
                    time.sleep(0.05)
                assert len(ctx.workers) == 3
            finally:
                late.worker_state.cluster_agent.close()
                late.shutdown()
                late.server_close()
            # the leaver is retired from the rotation automatically too
            deadline = time.monotonic() + 5.0
            while len(ctx.workers) > 2:
                ctx.cluster_epoch()
                if time.monotonic() > deadline:
                    raise AssertionError(f"never retired: {ctx.workers}")
                time.sleep(0.05)
            assert len(ctx.workers) == 2


# -- replica sets: quorum-acked writes, ranked elections, deadlines -------


def _replica_set(quorum=2, election_timeout_s=0.5):
    """3-node in-process replica set: a primary + two ranked standbys,
    quorum pushes armed, every node peering with the others."""
    a = ClusterNode(addr="a:1", write_quorum=quorum)
    b = ClusterNode(addr="b:2", standby_of=a, write_quorum=quorum,
                    rank=0, election_timeout_s=election_timeout_s)
    c = ClusterNode(addr="c:3", standby_of=a, write_quorum=quorum,
                    rank=1, election_timeout_s=election_timeout_s)
    a.peers = [b, c]
    b.peers = [a, c]
    c.peers = [a, b]
    return a, b, c, LocalClusterClient([a, b, c])


class TestReplicaSetQuorum:
    def test_acked_write_is_on_quorum_before_the_client_sees_it(self):
        a, b, c, client = _replica_set()
        rev = client.put("config/x", 42)
        # the ack implies BOTH standbys already hold the event (the
        # primary pushes to all, quorum gates the ack)
        assert b.state.get("config/x") == 42
        assert c.state.get("config/x") == 42
        assert b.state._rev >= rev and c.state._rev >= rev
        assert METRICS.counts.get("cluster.quorum_writes_acked", 0) >= 1

    def test_quorum_survives_one_dead_replica(self):
        a, b, c, client = _replica_set()
        c.partitioned = True
        rev = client.put("config/x", 1)  # 2/2 acks: a + b
        assert rev > 0 and b.state.get("config/x") == 1
        assert c.state.get("config/x") is None  # catches up via pull
        c.partitioned = False
        assert c.replicate_once() != 0  # events, or a first-pull snapshot
        assert c.state.get("config/x") == 1
        assert c.state._rev == a.state._rev

    def test_quorum_loss_refuses_the_ack_transiently(self):
        from datafusion_tpu.errors import ClusterQuorumError

        a, b, c, _ = _replica_set()
        b.partitioned = True
        c.partitioned = True
        out = a.handle_request({"type": "kv_put", "key": "k", "value": 1})
        assert out.get("code") == "quorum_unavailable"
        assert out.get("acks") == 1 and out.get("quorum") == 2
        with pytest.raises(ClusterQuorumError):
            LocalClusterClient(a).put("k2", 2)
        assert METRICS.counts.get("cluster.quorum_write_failures", 0) >= 2
        # replicas return: the next write acks AND ships the backlog
        b.partitioned = False
        c.partitioned = False
        assert LocalClusterClient(a).put("k3", 3) > 0
        assert b.state.get("k") == 1  # the un-acked write replicated too
        assert b.state.get("k3") == 3

    def test_sustained_writes_batch_quorum_push_rounds(self):
        """An invalidation/write storm piggybacks pending event tails
        onto the in-flight push round: total push rounds stay BELOW
        the event count (naively it would be events x replicas), and
        every acked write still lands on both replicas."""
        a, b, c, client = _replica_set()
        base_rounds = METRICS.counts.get("cluster.replicate_push_rounds", 0)
        base_piggy = METRICS.counts.get(
            "cluster.replicate_push_piggybacked", 0)
        n = 8
        barrier = threading.Barrier(n)
        errors: list = []

        def put(i):
            try:
                barrier.wait(timeout=10)
                client.put(f"storm/{i}", i)
            except Exception as e:  # noqa: BLE001 — surfaced via the assert below
                errors.append(e)

        # delay the first push round per link: the other 7 writers
        # apply their events while it holds the link lock, so the
        # delayed round's payload (built after the sleep) carries the
        # whole storm and they all piggyback
        with faults.scoped({"rules": [
            {"site": "cluster.replicate", "op": "delay",
             "seconds": 0.25, "count": 2},
        ]}):
            threads = [threading.Thread(target=put, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
        assert not errors, errors
        rounds = METRICS.counts.get(
            "cluster.replicate_push_rounds", 0) - base_rounds
        piggy = METRICS.counts.get(
            "cluster.replicate_push_piggybacked", 0) - base_piggy
        assert piggy >= 1
        assert rounds < n  # push-round count < event count
        for i in range(n):
            assert b.state.get(f"storm/{i}") == i
            assert c.state.get(f"storm/{i}") == i

    def test_lease_refresh_heartbeats_skip_the_quorum_round_trip(self):
        a, b, c, client = _replica_set()
        g = client.lease_grant(30.0)  # mutation: needs quorum (and got it)
        b.partitioned = True
        c.partitioned = True
        # refreshes append no events, so a partitioned replica set must
        # not fail (or slow) the worker heartbeat path
        resp = client.lease_refresh(g["lease"])
        assert resp["found"] is True

    def test_ranked_succession_with_election_quorum(self):
        a, b, c, client = _replica_set()
        g = client.lease_grant(30.0)
        client.put("workers/w:9", {"addr": "w:9"}, lease=g["lease"])
        a.partitioned = True
        now = time.monotonic()
        # rank 1 defers inside its stagger window while rank 0 claims
        assert not c.maybe_promote(now=now + 0.6)
        assert b.maybe_promote(now=now + 0.6)
        assert b.role == "primary" and b.term == 2
        # rank 1 then observes the new term and follows instead of racing
        assert not c.maybe_promote(now=now + 10.0)
        assert c.role == "standby" and c.term == 2
        assert c._primary_hint() == "b:2"
        # the new primary serves the replicated membership
        assert client.membership()["workers"].keys() == {"w:9"}

    def test_election_defers_without_quorum_reachability(self):
        a, b, c, _ = _replica_set()
        a.partitioned = True
        c.partitioned = True  # b can reach 1 < (3 - 2 + 1) = 2 nodes
        assert not b.maybe_promote(now=time.monotonic() + 10.0)
        assert b.role == "standby"
        assert b.elections_deferred >= 1
        # reachability restored: the same candidate now wins
        c.partitioned = False
        assert b.maybe_promote(now=time.monotonic() + 10.0)
        assert b.role == "primary"

    def test_promoted_log_contains_every_acked_revision(self):
        """The acceptance property: writes acked while one standby was
        partitioned (quorum met via the OTHER standby) survive a
        primary kill even when the LAGGING standby is the ranked
        successor — its election catches up from the best responder
        before promoting."""
        a, b, c, client = _replica_set()
        client.put("config/base", 0)
        b.partitioned = True  # b lags; acks come from a + c
        acked = {}
        for i in range(5):
            key = f"config/k{i}"
            acked[key] = i
            assert client.put(key, i) > 0
        b.partitioned = False
        assert b.state.get("config/k0") is None  # genuinely behind
        a.partitioned = True  # SIGKILL the primary
        assert b.maybe_promote(now=time.monotonic() + 10.0)
        # zero acked-write loss: the promoted node replayed c's log
        for key, val in acked.items():
            assert b.state.get(key) == val, key
        # adopted c's whole log, +1 for b's own "promoted" event
        assert b.state._rev == c.state._rev + 1
        assert METRICS.counts.get("cluster.election_catchups", 0) >= 1

    def test_push_and_pull_race_stays_idempotent(self):
        a, b, c, client = _replica_set()
        for i in range(4):
            client.put(f"config/r{i}", i)  # pushed synchronously
        # the pull loop replays the same tail: zero double-applies
        assert b.replicate_once() == 0
        revs = [e["rev"] for e in b.state._events]
        assert len(revs) == len(set(revs))  # no duplicated log entries
        assert b.state._rev == a.state._rev

    def test_lagging_replica_resyncs_by_snapshot_push(self):
        a, b, c, client = _replica_set()
        client.put("config/seed", 1)
        b.partitioned = True
        for i in range(1100):  # blow past the retained log window
            client.invalidate(f"t{i}")
        b.partitioned = False
        snaps_before = b.snapshots_applied
        # clear the dead-replica push cooldown (quorum rounds skip a
        # recently-failed link while the OTHER replica covers quorum;
        # this test wants the push-path resync specifically, without
        # sleeping out the real cooldown window)
        for link in a._links.values():
            link.last_error_at = None
        assert client.put("config/after", 2) > 0
        assert b.snapshots_applied == snaps_before + 1
        assert b.state.get("config/after") == 2
        assert b.state._rev == a.state._rev

    def test_quorum_path_replicate_fault_site(self):
        """cluster.replicate now also guards the primary's push path:
        an injected push failure costs the ack (transient), not state."""
        from datafusion_tpu.errors import ClusterQuorumError

        a, b, c, _ = _replica_set()
        client = LocalClusterClient(a)
        with faults.scoped({"rules": [
            {"site": "cluster.replicate", "op": "raise",
             "exc": "ConnectionResetError", "count": 2},
        ]}):
            # one request = one quorum round = 2 push-site hits; the
            # service answers quorum_unavailable for exactly that round
            out = a.handle_request({"type": "kv_put", "key": "k",
                                    "value": 1})
            assert out.get("code") == "quorum_unavailable"
        # the CLIENT retries quorum failures in place: exhaust its whole
        # budget (3 attempts x 2 pushes) and the typed error surfaces
        with faults.scoped({"rules": [
            {"site": "cluster.replicate", "op": "raise",
             "exc": "ConnectionResetError", "count": 6},
        ]}):
            with pytest.raises(ClusterQuorumError):
                client.request({"type": "kv_put", "key": "kx", "value": 1})
        assert METRICS.counts.get("cluster.client_quorum_retries", 0) >= 2
        assert client.put("k2", 2) > 0  # faults drained: acks flow again


class TestLeaseDeadlineShipping:
    def test_pull_ships_remaining_deadlines(self):
        a, b, client = _pair()
        g = client.lease_grant(30.0)
        client.put("workers/w:9", {}, lease=g["lease"])
        b.replicate_once()
        shipped = b.state._shipped_deadlines
        assert g["lease"] in shipped
        assert 0.0 < shipped[g["lease"]] <= 30.0

    def test_promote_rearms_to_shipped_deadline_not_full_ttl(self):
        a, b, client = _pair()
        g = client.lease_grant(10.0)
        client.put("workers/w:9", {}, lease=g["lease"])
        b.replicate_once()
        # the primary's clock says 2.5s remain (a holder that had been
        # silent for 7.5s of its 10s TTL — half-dead, not fresh)
        b.state.note_lease_deadlines({g["lease"]: 2.5})
        b.state.promote(2, now=1000.0)
        lease = b.state._leases[g["lease"]]
        assert lease.expires == pytest.approx(1002.5)
        # still alive inside the shipped budget...
        assert b.state.lease_refresh(g["lease"], now=1002.0)["found"]

    def test_promote_expires_past_deadline_holder_promptly(self):
        a, b, client = _pair()
        g = client.lease_grant(10.0)
        client.put("workers/w:9", {}, lease=g["lease"])
        b.replicate_once()
        b.state.note_lease_deadlines({g["lease"]: 0.0})  # already dead
        b.state.promote(2, now=1000.0)
        # the next sweep collects it — no full-TTL masking of a corpse
        assert not b.state.lease_refresh(g["lease"], now=1000.1)["found"]
        assert b.state.membership(now=1000.1)["workers"] == {}

    def test_promote_caps_shipped_deadline_at_ttl(self):
        a, b, client = _pair()
        g = client.lease_grant(5.0)
        client.put("workers/w:9", {}, lease=g["lease"])
        b.replicate_once()
        b.state.note_lease_deadlines({g["lease"]: 99.0})  # bogus upstream
        b.state.promote(2, now=1000.0)
        assert b.state._leases[g["lease"]].expires <= 1005.0

    def test_unshipped_lease_falls_back_to_full_ttl(self):
        a, b, client = _pair()
        g = client.lease_grant(5.0)
        client.put("workers/w:9", {}, lease=g["lease"])
        b.replicate_once()
        b.state.note_lease_deadlines({})  # legacy upstream: nothing shipped
        b.state.promote(2, now=1000.0)
        assert b.state._leases[g["lease"]].expires == pytest.approx(1005.0)


class TestDeltaPublish:
    def _tcp_tier(self):
        from datafusion_tpu.cluster.service import serve as serve_cluster

        server = serve_cluster("127.0.0.1:0")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        host, port = server.server_address[:2]
        return server, connect(f"{host}:{port}")

    def _entry(self, seed=0):
        rng = np.random.default_rng(7)
        cols = [np.arange(200_000, dtype=np.int64),
                rng.integers(0, 100, 200_000).astype(np.int64) + seed]
        nbytes = sum(c.nbytes for c in cols)
        return CachedResult(cols, [None, None], [None, None],
                           200_000, nbytes), nbytes

    def test_warm_republish_ships_only_changed_segments(self):
        server, client = self._tcp_tier()
        tier = SharedResultTier(client)
        try:
            entry, nbytes = self._entry(seed=0)
            sent_full = tier._publish_one("fp-delta", entry, nbytes, ("t",))
            assert sent_full > nbytes  # full snapshot crossed the wire
            # identical republish: digests only, no column bytes
            sent_same = tier._publish_one("fp-delta", entry, nbytes, ("t",))
            assert sent_same < nbytes * 0.01
            # one of two columns changes: ~half the bytes ship
            entry2, _ = self._entry(seed=1)
            sent_half = tier._publish_one("fp-delta", entry2, nbytes, ("t",))
            assert nbytes * 0.4 < sent_half < nbytes * 0.7
            assert METRICS.counts.get(
                "coord.shared_cache_delta_published", 0) >= 2
            # the assembled entry round-trips exactly
            fetched = client.result_fetch("fp-delta")
            assert fetched is not None
            np.testing.assert_array_equal(
                fetched[0].columns[1], entry2.columns[1]
            )
            np.testing.assert_array_equal(
                fetched[0].columns[0], entry2.columns[0]
            )
        finally:
            server.shutdown()
            server.server_close()

    def test_delta_falls_back_to_full_when_service_lost_the_base(self):
        server, client = self._tcp_tier()
        tier = SharedResultTier(client)
        try:
            entry, nbytes = self._entry()
            tier._publish_one("fp-fb", entry, nbytes, ("t",))
            client.invalidate("t")  # service dropped the entry
            assert client.result_fetch("fp-fb") is None
            misses = METRICS.counts.get("cluster.result_delta_misses", 0)
            sent = tier._publish_one("fp-fb", entry, nbytes, ("t",))
            assert sent > nbytes  # need_full -> full snapshot shipped
            assert METRICS.counts.get(
                "cluster.result_delta_misses", 0) == misses + 1
            assert client.result_fetch("fp-fb") is not None
        finally:
            server.shutdown()
            server.server_close()

    def test_in_process_delta_replicates_to_standby(self):
        a, b, _ = _pair()
        client = LocalClusterClient([a, b])
        tier = SharedResultTier(client)
        entry, nbytes = self._entry()
        tier._publish_one("fp-repl", entry, nbytes, ("t",))
        entry2, _ = self._entry(seed=3)
        tier._publish_one("fp-repl", entry2, nbytes, ("t",))
        b.replicate_once()
        stored = b.state.result_get("fp-repl")
        assert stored is not None
        np.testing.assert_array_equal(
            stored["snapshot"]["columns"][1], entry2.columns[1]
        )


class TestWatchChurnChaos:
    def test_watch_parked_across_promotion_under_seeded_faults(self, tmp_path):
        """Satellite: a watch parked across a SIGKILL election wakes on
        the promoted node with the correct term/epoch and neither
        duplicates nor skips events — with chaos riding the election
        and replication paths."""
        import signal as _signal  # noqa: F401 — documents the smoke's TCP twin

        servers = []
        addrs = []
        try:
            # 3-replica TCP set in-process: a primary + 2 ranked standbys
            from datafusion_tpu.cluster.service import serve as serve_cluster

            pri = serve_cluster("127.0.0.1:0", write_quorum=2)
            threading.Thread(target=pri.serve_forever, daemon=True).start()
            servers.append(pri)
            pri_addr = "%s:%d" % pri.server_address[:2]
            addrs.append(pri_addr)
            for rank in (0, 1):
                stb = serve_cluster(
                    "127.0.0.1:0", standby_of=pri_addr, write_quorum=2,
                    rank=rank, election_timeout_s=0.5,
                )
                threading.Thread(target=stb.serve_forever,
                                 daemon=True).start()
                servers.append(stb)
                addrs.append("%s:%d" % stb.server_address[:2])
            for srv in servers:
                srv.cluster_node.peers = list(addrs)
            writer = connect(",".join(addrs))
            watcher = connect(",".join(addrs))

            # acked pre-kill state + one consumed event
            g = writer.lease_grant(30.0)
            writer.put("workers/w:9", {"addr": "w:9"}, lease=g["lease"])
            writer.invalidate("seen")
            since = writer.membership()["rev"]

            got: dict = {}

            def park():
                deadline = time.monotonic() + 15.0
                while time.monotonic() < deadline:
                    try:
                        out = watcher.watch(since, timeout_s=3.0)
                    except (ConnectionError, OSError, ExecutionError):
                        time.sleep(0.05)
                        continue
                    if out.get("events"):
                        got.update(out)
                        return

            t = threading.Thread(target=park)
            t.start()
            time.sleep(0.3)  # let the watch park on the primary

            with faults.scoped({"seed": 23, "rules": [
                {"site": "cluster.election", "op": "raise",
                 "exc": "ExecutionError", "count": 1},
                {"site": "cluster.replicate", "op": "raise",
                 "exc": "ConnectionResetError", "count": 1},
            ]}):
                # SIGKILL the primary (in-process twin: hard server stop;
                # the OS-process + real-signal version runs in
                # scripts/scale_smoke.py)
                pri.shutdown()
                pri.server_close()
                # the acked invalidation lands on the PROMOTED node;
                # the writer sweeps endpoints until the election settles
                deadline = time.monotonic() + 15.0
                while True:
                    try:
                        writer.invalidate("churn")
                        break
                    except (ConnectionError, OSError, ExecutionError):
                        if time.monotonic() > deadline:
                            raise
                        time.sleep(0.1)
            t.join(timeout=15.0)
            assert not t.is_alive(), "watch never woke after the election"
            kinds = [(e["kind"], e.get("table")) for e in got["events"]]
            # exactly the post-cursor event: no duplicate of "seen", no
            # skipped "churn"
            assert kinds == [("invalidate", "churn")], kinds
            assert got["term"] >= 2  # answered by the promoted node
            assert "w:9" in got["workers"]  # membership survived intact
        finally:
            for srv in servers:
                try:
                    srv.shutdown()
                    srv.server_close()
                except OSError:
                    pass

    def test_dead_replica_cooldown_skips_push_while_quorum_holds(self):
        """One dead replica must not tax every write: after a failed
        push the link cools down and quorum rounds skip it (the other
        replica covers quorum); it is dialed again once needed or once
        the cooldown lapses."""
        a, b, c, client = _replica_set()
        client.put("config/x", 1)  # links warm, all healthy
        b.partitioned = True
        assert client.put("config/y", 2) > 0  # quorum via a + c
        blink = next(l for l in a._links.values() if l.target is b)
        assert blink.last_error_at is not None  # cooling
        b.partitioned = False
        assert client.put("config/z", 3) > 0
        # quorum was met by c, so the cooling link was skipped — b is
        # still behind and relies on its pull loop
        assert b.state.get("config/z") is None
        assert b.replicate_once() != 0
        assert b.state.get("config/z") == 3
        # but if the OTHER replica dies, the cooling link IS dialed
        # (quorum beats the cooldown)
        c.partitioned = True
        assert client.put("config/w", 4) > 0  # acks: a + b (re-probed)
        assert b.state.get("config/w") == 4
        assert blink.last_error_at is None  # healthy again
