"""Sort / TopK / high-cardinality aggregation tests.

Covers the two device sort paths (`exec/sort.py`): the streaming TopK
(`ORDER BY ... LIMIT k`) and the run-sort + host-merge full sort, plus
the sort-merge aggregation path at 10^5 groups (`exec/aggregate.py`).
The reference planned Sort/Limit but left them `unimplemented!()`
(`/root/reference/src/execution/context.rs:161`), so expected values
come from numpy on identical inputs.
"""

import numpy as np
import pytest

from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.datasource import MemoryDataSource


def _ctx_with(name, schema, cols, valids=None, dicts=None, batch_rows=1000):
    """Context over an in-memory table split into batch_rows-row batches."""
    n = len(cols[0])
    valids = valids if valids is not None else [None] * len(cols)
    dicts = dicts if dicts is not None else [None] * len(cols)
    batches = []
    for i in range(0, n, batch_rows):
        batches.append(
            make_host_batch(
                schema,
                [c[i : i + batch_rows] for c in cols],
                [None if v is None else v[i : i + batch_rows] for v in valids],
                dicts,
            )
        )
    ctx = ExecutionContext()
    ctx.register_datasource(name, MemoryDataSource(schema, batches))
    return ctx


class TestStreamingTopK:
    def test_multibatch_asc_desc(self):
        rng = np.random.default_rng(0)
        n = 50_000
        v = rng.permutation(n).astype(np.int64)
        x = rng.uniform(-1, 1, n)
        schema = Schema(
            [Field("v", DataType.INT64, False), Field("x", DataType.FLOAT64, False)]
        )
        ctx = _ctx_with("t", schema, [v, x], batch_rows=4096)

        t = ctx.sql_collect("SELECT v, x FROM t ORDER BY v LIMIT 7")
        order = np.argsort(v)[:7]
        assert list(t.column_values(0)) == v[order].tolist()
        np.testing.assert_allclose(np.asarray(t.column_values(1)), x[order])

        t = ctx.sql_collect("SELECT v FROM t ORDER BY v DESC LIMIT 5")
        assert list(t.column_values(0)) == sorted(v.tolist(), reverse=True)[:5]

    def test_multikey_with_ties(self):
        rng = np.random.default_rng(1)
        n = 20_000
        a = rng.integers(0, 50, n).astype(np.int32)
        b = rng.uniform(0, 1, n)
        schema = Schema(
            [Field("a", DataType.INT32, False), Field("b", DataType.FLOAT64, False)]
        )
        ctx = _ctx_with("t", schema, [a, b], batch_rows=3000)
        t = ctx.sql_collect("SELECT a, b FROM t ORDER BY a DESC, b LIMIT 100")
        # expected: lexsort on (-a, b)
        order = np.lexsort((b, -a.astype(np.int64)))[:100]
        np.testing.assert_array_equal(np.asarray(t.column_values(0)), a[order])
        np.testing.assert_allclose(np.asarray(t.column_values(1)), b[order])

    def test_nulls_last(self):
        v = np.asarray([5, 2, 9, 1, 7], np.int64)
        valid = np.asarray([True, False, True, True, False])
        schema = Schema([Field("v", DataType.INT64, True)])
        ctx = _ctx_with("t", schema, [v], valids=[valid], batch_rows=2)
        t = ctx.sql_collect("SELECT v FROM t ORDER BY v LIMIT 5")
        vals = t.to_rows()
        assert [r[0] for r in vals[:3]] == [1, 5, 9]
        assert vals[3][0] is None and vals[4][0] is None

    def test_limit_larger_than_input(self):
        v = np.asarray([3, 1, 2], np.int64)
        schema = Schema([Field("v", DataType.INT64, False)])
        ctx = _ctx_with("t", schema, [v])
        t = ctx.sql_collect("SELECT v FROM t ORDER BY v LIMIT 50")
        assert list(t.column_values(0)) == [1, 2, 3]

    def test_string_keys_dict_growth(self):
        # batch 2 introduces words that sort before batch 1's whole
        # dictionary: rank tables must be recomputed per version
        rng = np.random.default_rng(2)
        d = StringDictionary()
        words = []
        for lo, hi in ((13, 26), (0, 26)):
            words.extend(
                chr(97 + rng.integers(lo, hi)) + f"{rng.integers(0, 1000):03d}"
                for _ in range(5000)
            )
        codes = d.encode(words)
        schema = Schema([Field("s", DataType.UTF8, False)])
        ctx = _ctx_with("t", schema, [codes], dicts=[d], batch_rows=5000)
        t = ctx.sql_collect("SELECT s FROM t ORDER BY s LIMIT 20")
        assert list(t.column_values(0)) == sorted(words)[:20]
        t = ctx.sql_collect("SELECT s FROM t ORDER BY s DESC LIMIT 20")
        assert list(t.column_values(0)) == sorted(words, reverse=True)[:20]


class TestFullSort:
    def test_multirun_merge_exact_order(self, monkeypatch):
        # force small runs so multiple device-sorted runs merge on host
        # (the default single-sort threshold is far larger)
        monkeypatch.setenv("DATAFUSION_TPU_SORT_RUN_ROWS", "16384")
        rng = np.random.default_rng(3)
        n = 120_000
        a = rng.integers(0, 1000, n).astype(np.int64)
        b = rng.permutation(n).astype(np.int64)
        schema = Schema(
            [Field("a", DataType.INT64, False), Field("b", DataType.INT64, False)]
        )
        ctx = _ctx_with("t", schema, [a, b], batch_rows=8192)
        t = ctx.sql_collect("SELECT a, b FROM t ORDER BY a, b DESC")
        order = np.lexsort((-b, a))
        np.testing.assert_array_equal(np.asarray(t.column_values(0)), a[order])
        np.testing.assert_array_equal(np.asarray(t.column_values(1)), b[order])

    def test_full_sort_with_nulls_and_strings(self):
        rng = np.random.default_rng(4)
        n = 30_000
        d = StringDictionary()
        words = [f"w{rng.integers(0, 500):03d}" for _ in range(n)]
        codes = d.encode(words)
        v = rng.integers(-100, 100, n).astype(np.int64)
        valid = rng.random(n) < 0.9
        schema = Schema(
            [Field("s", DataType.UTF8, False), Field("v", DataType.INT64, True)]
        )
        ctx = _ctx_with(
            "t", schema, [codes, v], valids=[None, valid], dicts=[d, None],
            batch_rows=4096,
        )
        t = ctx.sql_collect("SELECT s, v FROM t ORDER BY s DESC, v")
        # expected: s DESC, then v ASC with NULLs last
        warr = np.asarray(words)
        vkey = np.where(valid, v, np.iinfo(np.int64).max)
        # np.lexsort is ascending; invert string order via negated ranks
        svals, sranks = np.unique(warr, return_inverse=True)
        order = np.lexsort((vkey, -sranks))
        assert list(t.column_values(0)) == warr[order].tolist()
        got_v = t.to_rows()
        exp_v = [int(v[i]) if valid[i] else None for i in order]
        assert [r[1] for r in got_v] == exp_v

    def test_limit_above_topk_max_uses_run_merge(self, monkeypatch):
        import datafusion_tpu.exec.sort as sort_mod

        monkeypatch.setattr(sort_mod, "TOPK_MAX", 4)
        rng = np.random.default_rng(5)
        n = 5_000
        v = rng.permutation(n).astype(np.int64)
        schema = Schema([Field("v", DataType.INT64, False)])
        ctx = _ctx_with("t", schema, [v], batch_rows=512)
        t = ctx.sql_collect("SELECT v FROM t ORDER BY v LIMIT 10")
        assert list(t.column_values(0)) == list(range(10))

    def test_uint64_full_range(self):
        # keys above 2^63: ordering must survive the sign-flip trick
        v = np.asarray(
            [0, 1, 2**63 - 1, 2**63, 2**64 - 1, 42], dtype=np.uint64
        )
        schema = Schema([Field("v", DataType.UINT64, False)])
        ctx = _ctx_with("t", schema, [v], batch_rows=2)
        t = ctx.sql_collect("SELECT v FROM t ORDER BY v DESC")
        assert list(t.column_values(0)) == sorted(v.tolist(), reverse=True)
        t = ctx.sql_collect("SELECT v FROM t ORDER BY v LIMIT 3")
        assert list(t.column_values(0)) == sorted(v.tolist())[:3]

    def test_empty_input(self):
        schema = Schema([Field("v", DataType.INT64, False)])
        ctx = _ctx_with("t", schema, [np.empty(0, np.int64)])
        t = ctx.sql_collect("SELECT v FROM t ORDER BY v")
        assert t.num_rows == 0
        t = ctx.sql_collect("SELECT v FROM t ORDER BY v LIMIT 5")
        assert t.num_rows == 0


class TestHighCardinalityAggregate:
    @pytest.mark.parametrize("n_groups", [100_000])
    def test_sum_count_min_max_100k_groups(self, n_groups):
        rng = np.random.default_rng(6)
        n = 400_000
        k = rng.integers(0, n_groups, n).astype(np.int64)
        v = rng.integers(-1000, 1000, n).astype(np.int64)
        schema = Schema(
            [Field("k", DataType.INT64, False), Field("v", DataType.INT64, False)]
        )
        ctx = _ctx_with("t", schema, [k, v], batch_rows=65536)
        t = ctx.sql_collect(
            "SELECT k, SUM(v), COUNT(1), MIN(v), MAX(v) FROM t GROUP BY k"
        )
        uniq = np.unique(k)
        assert t.num_rows == len(uniq)
        sums = np.zeros(n_groups, np.int64)
        np.add.at(sums, k, v)
        cnts = np.bincount(k, minlength=n_groups)
        mins = np.full(n_groups, np.iinfo(np.int64).max)
        np.minimum.at(mins, k, v)
        maxs = np.full(n_groups, np.iinfo(np.int64).min)
        np.maximum.at(maxs, k, v)
        got = {r[0]: r[1:] for r in t.to_rows()}
        for g in uniq.tolist():
            assert got[g] == (sums[g], cnts[g], mins[g], maxs[g])

    def test_avg_float_100k_groups_matches_dense_semantics(self):
        rng = np.random.default_rng(7)
        n, n_groups = 300_000, 120_000
        k = rng.integers(0, n_groups, n).astype(np.int64)
        v = rng.uniform(-1, 1, n)
        schema = Schema(
            [Field("k", DataType.INT64, False), Field("v", DataType.FLOAT64, False)]
        )
        ctx = _ctx_with("t", schema, [k, v], batch_rows=65536)
        t = ctx.sql_collect("SELECT k, AVG(v), SUM(v) FROM t GROUP BY k")
        sums = np.zeros(n_groups)
        np.add.at(sums, k, v)
        cnts = np.bincount(k, minlength=n_groups)
        got = {r[0]: r[1:] for r in t.to_rows()}
        uniq = np.unique(k)
        assert t.num_rows == len(uniq)
        for g in rng.choice(uniq, 500, replace=False).tolist():
            a, s = got[g]
            np.testing.assert_allclose(s, sums[g], rtol=1e-9)
            np.testing.assert_allclose(a, sums[g] / cnts[g], rtol=1e-9)


class TestSentinelCollisions:
    """Real extreme values must not collide with the NULL/padding
    markers: ~int64.min == int64.max and -(-inf) == +inf, so nulls ride
    a separate dead-flag sort operand instead of value sentinels."""

    def test_int64_min_desc_with_nulls(self):
        schema = Schema([Field("x", DataType.INT64, True)])
        vals = np.array([0, np.iinfo(np.int64).min, 5], dtype=np.int64)
        valid = np.array([False, True, True])
        ctx = _ctx_with("t", schema, [vals], valids=[valid])

        t = ctx.sql_collect("SELECT x FROM t ORDER BY x DESC")
        assert t.column_values(0) == [5, np.iinfo(np.int64).min, None]

        t = ctx.sql_collect("SELECT x FROM t ORDER BY x DESC LIMIT 2")
        assert t.column_values(0) == [5, np.iinfo(np.int64).min]

    def test_int64_extremes_asc(self):
        schema = Schema([Field("x", DataType.INT64, True)])
        vals = np.array(
            [np.iinfo(np.int64).max, 0, np.iinfo(np.int64).min], dtype=np.int64
        )
        valid = np.array([True, False, True])
        ctx = _ctx_with("t", schema, [vals], valids=[valid])
        t = ctx.sql_collect("SELECT x FROM t ORDER BY x")
        assert t.column_values(0) == [
            np.iinfo(np.int64).min, np.iinfo(np.int64).max, None,
        ]
        t = ctx.sql_collect("SELECT x FROM t ORDER BY x LIMIT 3")
        assert t.column_values(0) == [
            np.iinfo(np.int64).min, np.iinfo(np.int64).max, None,
        ]

    def test_float_inf_desc_with_nulls(self):
        schema = Schema([Field("x", DataType.FLOAT64, True)])
        vals = np.array([-np.inf, 1.0, np.inf, 0.0])
        valid = np.array([True, True, True, False])
        ctx = _ctx_with("t", schema, [vals], valids=[valid])
        t = ctx.sql_collect("SELECT x FROM t ORDER BY x DESC")
        assert t.column_values(0) == [np.inf, 1.0, -np.inf, None]
        t = ctx.sql_collect("SELECT x FROM t ORDER BY x DESC LIMIT 3")
        assert t.column_values(0) == [np.inf, 1.0, -np.inf]

    def test_uint64_max_asc_with_nulls(self):
        schema = Schema([Field("x", DataType.UINT64, True)])
        vals = np.array([np.iinfo(np.uint64).max, 1, 0], dtype=np.uint64)
        valid = np.array([True, True, False])
        ctx = _ctx_with("t", schema, [vals], valids=[valid])
        t = ctx.sql_collect("SELECT x FROM t ORDER BY x")
        assert t.column_values(0) == [1, np.iinfo(np.uint64).max, None]

    def test_wide_f64_topk_matches_numpy(self):
        # float64 single-key TopK rides the wide lax.top_k path (host
        # bit-image); parity against numpy stable sort incl. ties
        rng = np.random.default_rng(21)
        n = 30_000
        x = np.round(rng.uniform(-1e6, 1e6, n), 1)  # ties likely
        pay = np.arange(n, dtype=np.int64)
        schema = Schema(
            [Field("x", DataType.FLOAT64, False), Field("p", DataType.INT64, False)]
        )
        ctx = _ctx_with("t", schema, [x, pay], batch_rows=4096)
        for sql, order in [
            ("SELECT x, p FROM t ORDER BY x LIMIT 50", np.argsort(x, kind="stable")[:50]),
            (
                "SELECT x, p FROM t ORDER BY x DESC LIMIT 50",
                np.argsort(-x, kind="stable")[:50],
            ),
        ]:
            t = ctx.sql_collect(sql)
            assert t.column_values(0) == x[order].tolist()
            assert t.column_values(1) == pay[order].tolist()

    def test_wide_f64_topk_nan_and_nulls(self):
        # ladder: real values > NaN > NULL; all must fill a big LIMIT
        schema = Schema([Field("x", DataType.FLOAT64, True)])
        vals = np.array([3.0, np.nan, -np.inf, 0.0, np.inf, 1.0])
        valid = np.array([True, True, True, False, True, True])
        ctx = _ctx_with("t", schema, [vals], valids=[valid])
        t = ctx.sql_collect("SELECT x FROM t ORDER BY x DESC LIMIT 6")
        got = t.column_values(0)
        assert got[:4] == [np.inf, 3.0, 1.0, -np.inf]
        assert np.isnan(got[4]) and got[5] is None
        t = ctx.sql_collect("SELECT x FROM t ORDER BY x LIMIT 6")
        got = t.column_values(0)
        assert got[:4] == [-np.inf, 1.0, 3.0, np.inf]
        assert np.isnan(got[4]) and got[5] is None

    def test_wide_int64_collision_fallback_fires(self):
        # int64.min under DESC lands on the sentinel ladder: the wide
        # path must detect the collision and replay via the exact sort
        from datafusion_tpu.utils.metrics import METRICS

        schema = Schema([Field("x", DataType.INT64, False)])
        vals = np.array([7, np.iinfo(np.int64).min, -3, 12], dtype=np.int64)
        ctx = _ctx_with("t", schema, [vals])
        METRICS.reset()
        t = ctx.sql_collect("SELECT x FROM t ORDER BY x DESC LIMIT 4")
        assert t.column_values(0) == [12, 7, -3, np.iinfo(np.int64).min]
        assert METRICS.snapshot()["counts"].get("sort.wide_fallbacks", 0) >= 1
        # and without extremes the fast path serves alone
        vals2 = np.array([7, -5, -3, 12], dtype=np.int64)
        ctx2 = _ctx_with("t", schema, [vals2])
        METRICS.reset()
        t2 = ctx2.sql_collect("SELECT x FROM t ORDER BY x DESC LIMIT 2")
        assert t2.column_values(0) == [12, 7]
        assert METRICS.snapshot()["counts"].get("sort.wide_fallbacks", 0) == 0

    def test_full_sort_multirun_int64_min(self, monkeypatch):
        # force the run-merge path (no LIMIT, multiple small runs)
        monkeypatch.setenv("DATAFUSION_TPU_SORT_RUN_ROWS", "1024")
        rng = np.random.default_rng(5)
        n = 3000
        vals = rng.integers(-1000, 1000, n).astype(np.int64)
        vals[0] = np.iinfo(np.int64).min
        vals[n // 2] = np.iinfo(np.int64).max
        valid = np.ones(n, bool)
        valid[1::7] = False
        schema = Schema([Field("x", DataType.INT64, True)])
        ctx = _ctx_with("t", schema, [vals], valids=[valid], batch_rows=1000)
        t = ctx.sql_collect("SELECT x FROM t ORDER BY x DESC")
        got = t.column_values(0)
        want = sorted(vals[valid].tolist(), reverse=True) + [None] * int(
            (~valid).sum()
        )
        assert got == want


class TestOrderByHiddenColumn:
    """ORDER BY a column not in the SELECT list: planned as a hidden
    projection column + final strip (the reference resolves only
    against the projection schema, `sqlplanner.rs:139-151`, and fails)."""

    def test_order_by_unselected_column(self):
        schema = Schema(
            [Field("name", DataType.UTF8, False), Field("v", DataType.INT64, False)]
        )
        d = StringDictionary()
        names = np.array([d.add(s) for s in ["b", "c", "a"]], dtype=np.int32)
        v = np.array([2, 3, 1], dtype=np.int64)
        ctx = _ctx_with("t", schema, [names, v], dicts=[d, None])
        t = ctx.sql_collect("SELECT name FROM t ORDER BY v DESC")
        assert t.column_values(0) == ["c", "b", "a"]
        assert len(t.schema) == 1  # hidden column stripped

        t = ctx.sql_collect("SELECT name FROM t ORDER BY v LIMIT 2")
        assert t.column_values(0) == ["a", "b"]

    def test_order_by_alias_still_works(self):
        schema = Schema([Field("v", DataType.INT64, False)])
        ctx = _ctx_with("t", schema, [np.array([3, 1, 2], dtype=np.int64)])
        t = ctx.sql_collect("SELECT v AS w FROM t ORDER BY w")
        assert t.column_values(0) == [1, 2, 3]


class TestSingleKeyFastPath:
    """Single-key TopK rides lax.top_k with an exact int64 score image
    (floats, ints <= 32 bits, strings); results must match the general
    sort path exactly."""

    @pytest.mark.parametrize(
        "dtype,lo,hi",
        [(np.int32, -(2**31), 2**31 - 1), (np.int16, -100, 100),
         (np.uint32, 0, 2**32 - 1)],
    )
    def test_small_int_keys(self, dtype, lo, hi):
        rng = np.random.default_rng(3)
        v = rng.integers(lo, hi, 5000, dtype=dtype)
        v[0], v[1] = lo, hi  # extremes must survive
        valid = np.ones(5000, bool)
        valid[2::11] = False
        dt = {np.int32: DataType.INT32, np.int16: DataType.INT16,
              np.uint32: DataType.UINT32}[dtype]
        schema = Schema([Field("v", dt, True)])
        ctx = _ctx_with("t", schema, [v], valids=[valid], batch_rows=1024)
        for order, rev in (("", False), (" DESC", True)):
            t = ctx.sql_collect(f"SELECT v FROM t ORDER BY v{order} LIMIT 40")
            want = sorted(v[valid].tolist(), reverse=rev)[:40]
            assert t.column_values(0) == want, (dtype, order)

    def test_float_extremes_and_ties(self):
        # float32: the fast-path-eligible float width
        rng = np.random.default_rng(4)
        v = np.round(rng.uniform(-1e6, 1e6, 20000), 2).astype(np.float32)
        v[5], v[6], v[7] = np.inf, -np.inf, v[8]  # dupes + infinities
        # small-magnitude mixed signs: the region where a naive
        # sign-flip bit image breaks monotonicity
        v[100:120] = np.linspace(-1.5, 1.5, 20, dtype=np.float32)
        v[120], v[121] = -0.0, 0.0
        valid = rng.random(20000) > 0.05
        schema = Schema([Field("v", DataType.FLOAT32, True)])
        ctx = _ctx_with("t", schema, [v], valids=[valid], batch_rows=4096)
        for order, rev in (("", False), (" DESC", True)):
            t = ctx.sql_collect(f"SELECT v FROM t ORDER BY v{order} LIMIT 100")
            want = sorted(v[valid].tolist(), reverse=rev)[:100]
            np.testing.assert_array_equal(
                np.asarray(t.column_values(0)), np.asarray(want), err_msg=order
            )

    def test_limit_exceeds_batch_capacity(self):
        # LIMIT (bucketed to k=2048) > the 1024-row batch capacity:
        # lax.top_k(full, k) would demand k <= capacity and crash; the
        # kernel must clamp its per-batch pick and pad with dead slots
        rng = np.random.default_rng(9)
        v = rng.permutation(5000).astype(np.int32)
        schema = Schema([Field("v", DataType.INT32, False)])
        ctx = _ctx_with("t", schema, [v], batch_rows=1000)
        t = ctx.sql_collect("SELECT v FROM t ORDER BY v LIMIT 2000")
        assert t.column_values(0) == list(range(2000))
        t = ctx.sql_collect("SELECT v FROM t ORDER BY v DESC LIMIT 2000")
        assert t.column_values(0) == list(range(4999, 2999, -1))

    def test_limit_exceeds_live_rows(self):
        # dead sentinel slots must not displace real NULL-key rows
        # (FLOAT32: fast-path eligible, so this pins the score ladder)
        schema = Schema([Field("v", DataType.FLOAT32, True)])
        vals = np.array([3.5, 1.25, 2.0, 0.0, 9.0])
        valid = np.array([True, True, True, False, False])
        ctx = _ctx_with("t", schema, [vals], valids=[valid])
        t = ctx.sql_collect("SELECT v FROM t ORDER BY v LIMIT 5")
        assert t.column_values(0) == [1.25, 2.0, 3.5, None, None]


class TestTopKFinalFold:
    """The TopK result's (live-mask, row-ids) pull is folded INTO the
    fused group launch: a warm pass is ONE counted device launch
    (`device.launches.topk.final`), with no separate blob-pack launch
    for the mask — and parity against the unfused path holds."""

    def _ctx(self):
        rng = np.random.default_rng(21)
        schema = Schema([
            Field("a", DataType.INT32, False),
            Field("b", DataType.FLOAT64, False),
        ])
        cols = [rng.integers(0, 100000, 5000).astype(np.int32),
                rng.uniform(0, 1, 5000)]
        batches = [
            make_host_batch(schema, [c[i:i + 1000] for c in cols])
            for i in range(0, 5000, 1000)
        ]
        # result cache OFF: the warm run must re-execute the pass (the
        # launch count is the thing under test)
        ctx = ExecutionContext(result_cache=False)
        ctx.register_datasource("t", MemoryDataSource(schema, batches))
        return ctx, "SELECT a, b FROM t ORDER BY a LIMIT 10"

    def test_warm_pass_is_one_launch(self):
        from datafusion_tpu.exec.materialize import collect
        from datafusion_tpu.utils.metrics import METRICS

        ctx, q = self._ctx()
        want = collect(ctx.sql(q)).to_rows()
        collect(ctx.sql(q))  # warm device copies + compiled programs
        before = dict(METRICS.counts)
        got = collect(ctx.sql(q)).to_rows()
        delta = {
            k: v - before.get(k, 0) for k, v in METRICS.counts.items()
        }
        assert got == want
        assert delta.get("device.launches.topk.final", 0) == 1
        assert delta.get("device.launches", 0) == 1

    def test_parity_with_fuse_off(self):
        import os

        from datafusion_tpu.exec.materialize import collect

        ctx, q = self._ctx()
        want = collect(ctx.sql(q)).to_rows()
        os.environ["DATAFUSION_TPU_FUSE"] = "0"
        try:
            assert collect(ctx.sql(q)).to_rows() == want
        finally:
            os.environ.pop("DATAFUSION_TPU_FUSE", None)

    def test_empty_scan_and_wide_keys_still_fold(self):
        from datafusion_tpu.exec.materialize import collect

        rng = np.random.default_rng(22)
        schema = Schema([
            Field("a", DataType.INT64, False),
            Field("b", DataType.FLOAT64, False),
        ])
        ctx = _ctx_with(
            "t", schema,
            [rng.integers(-(2**60), 2**60, 3000).astype(np.int64),
             rng.uniform(0, 1, 3000)],
        )
        # wide int64 key: the collision flag rides the folded header
        got = collect(ctx.sql(
            "SELECT a FROM t ORDER BY a DESC LIMIT 7"
        )).to_rows()
        want = sorted(
            (int(v),) for v in
            collect(ctx.sql("SELECT a FROM t")).columns[0]
        )[-7:][::-1]
        assert got == want
        # LIMIT over an all-filtered scan: the empty path still answers
        empty = collect(ctx.sql(
            "SELECT a FROM t WHERE a > 4611686018427387904 "
            "AND a < -4611686018427387904 ORDER BY a LIMIT 3"
        ))
        assert empty.num_rows == 0


class TestTopKExactPayloads:
    """TopK carries global row indices, not payload columns: payloads
    gather host-side from the source batches, so ORDER BY ... LIMIT
    equals the no-LIMIT sort prefix BIT-FOR-BIT even on emulated-f64
    devices (round-3 ADVICE item)."""

    def _src(self, rows=20_000):
        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        rng = np.random.default_rng(99)
        schema = Schema([
            Field("a", DataType.FLOAT64, False),
            Field("b", DataType.INT64, False),
            Field("x", DataType.FLOAT64, False),
        ])
        batches = []
        for lo in range(0, rows, 4096):
            n = min(4096, rows - lo)
            batches.append(make_host_batch(schema, [
                rng.uniform(-1e6, 1e6, n),
                rng.integers(-1000, 1000, n),
                rng.uniform(-1e9, 1e9, n),
            ]))
        return schema, MemoryDataSource(schema, batches)

    @pytest.mark.parametrize("sql_key", ["a DESC", "a", "b, a DESC"])
    def test_limit_equals_full_sort_prefix_bitwise(self, sql_key):
        import numpy as np

        from datafusion_tpu.exec.context import ExecutionContext
        from datafusion_tpu.exec.materialize import collect

        _, src = self._src()
        ctx = ExecutionContext()
        ctx.register_datasource("t", src)
        limited = collect(ctx.sql(f"SELECT a, b, x FROM t ORDER BY {sql_key} LIMIT 137"))
        full = collect(ctx.sql(f"SELECT a, b, x FROM t ORDER BY {sql_key}"))
        for i in range(3):
            want = np.asarray(full.columns[i][:137])
            got = np.asarray(limited.columns[i])
            if want.dtype.kind == "f":
                assert np.array_equal(
                    got.view(np.int64), want.view(np.int64)
                ), f"col {i} not bit-identical"
            else:
                assert np.array_equal(got, want)

    def test_state_carries_no_payload_columns(self):
        # structural: the streaming state is (keys, live, rows[, flag])
        from datafusion_tpu.exec.context import ExecutionContext
        from datafusion_tpu.exec.materialize import collect

        _, src = self._src(rows=5000)
        ctx = ExecutionContext()
        ctx.register_datasource("t", src)
        rel = ctx.sql("SELECT a, b, x FROM t ORDER BY a DESC LIMIT 10")
        init = rel._topk_init(128, rel.child.schema)
        # wide single-key path: (keys, live, rows, flag)
        assert len(init) == 4
        keys, live, rows = init[0], init[1], init[2]
        assert rows.dtype.name == "int64"
        collect(rel)  # executes end to end


class TestHostRoutedRunSort:
    """Link-aware full-sort placement (SortRelation._host_run_sort):
    on a slow measured link the run permutation computes on the host
    via np.lexsort; the stable orders must match the device path
    exactly."""

    def _src(self, nulls=False, nans=False):
        import numpy as np

        from datafusion_tpu import DataType, ExecutionContext, Field, Schema
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        rng = np.random.default_rng(21)
        n = 4096
        schema = Schema([
            Field("a", DataType.FLOAT64, True),
            Field("b", DataType.INT64, False),
            Field("s", DataType.UTF8, False),
        ])
        a = np.round(rng.uniform(-100, 100, n), 2)
        if nans:
            a[::97] = np.nan
        valid_a = rng.random(n) > 0.1 if nulls else None
        b = rng.integers(-50, 50, n)
        from datafusion_tpu.exec.batch import StringDictionary

        d = StringDictionary()
        codes = d.encode([f"v{int(x) % 13}" for x in b])
        batches = []
        half = n // 2
        for lo, hi in ((0, half), (half, n)):
            batches.append(make_host_batch(
                schema,
                [a[lo:hi], b[lo:hi], codes[lo:hi]],
                [None if valid_a is None else valid_a[lo:hi], None, None],
                [None, None, d],
            ))
        ctx = ExecutionContext(batch_size=half)
        ctx.register_datasource("t", MemoryDataSource(schema, batches))
        return ctx

    def _run(self, ctx, sql, env, monkeypatch):
        from datafusion_tpu.exec.materialize import collect

        for k, v in env.items():
            monkeypatch.setenv(k, v)
        return collect(ctx.sql(sql)).to_rows()

    @pytest.mark.parametrize("sql", [
        "SELECT a, b, s FROM t ORDER BY a, b",
        "SELECT a, b, s FROM t ORDER BY b DESC, a",
        "SELECT s, a FROM t ORDER BY s, a DESC",
    ])
    def test_host_sort_matches_device(self, sql, monkeypatch):
        from datafusion_tpu.utils.metrics import METRICS

        slow = {"DATAFUSION_TPU_WIRE": "always", "DATAFUSION_TPU_LINK_MBPS": "0.001"}
        fast = {"DATAFUSION_TPU_WIRE": "always", "DATAFUSION_TPU_LINK_MBPS": "1e9"}
        METRICS.reset()
        got = self._run(self._src(nulls=True), sql, slow, monkeypatch)
        assert METRICS.snapshot()["counts"].get("sort.host_routed_runs")
        want = self._run(self._src(nulls=True), sql, fast, monkeypatch)
        assert got == want

    def test_nan_keys_stay_on_device(self, monkeypatch):
        from datafusion_tpu.utils.metrics import METRICS

        slow = {"DATAFUSION_TPU_WIRE": "always", "DATAFUSION_TPU_LINK_MBPS": "0.001"}
        METRICS.reset()
        self._run(self._src(nans=True), "SELECT a, b FROM t ORDER BY a DESC", slow, monkeypatch)
        assert not METRICS.snapshot()["counts"].get("sort.host_routed_runs")

    def test_signed_zero_keys_stay_on_device(self, monkeypatch):
        # XLA's total order splits -0.0 < +0.0; np.lexsort ties them —
        # with both present the host route must bail (same contract as
        # the NaN bail-out)
        import numpy as np

        from datafusion_tpu.exec.sort import SortRelation

        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        monkeypatch.setenv("DATAFUSION_TPU_LINK_MBPS", "0.001")
        rel = object.__new__(SortRelation)
        rel.device = None

        def keys_for(vals):
            v = np.asarray(vals, np.float64)
            return [np.zeros(len(v), bool), v]

        both = keys_for([3.0, -0.0, 1.0, 0.0])
        assert rel._host_run_sort(both, 4) is None
        only_pos = keys_for([3.0, 0.0, 1.0, 0.0])
        assert rel._host_run_sort(only_pos, 4) is not None
        only_neg = keys_for([3.0, -0.0, 1.0, -0.0])
        assert rel._host_run_sort(only_neg, 4) is not None
        no_zero = keys_for([3.0, 2.0, 1.0, 4.0])
        assert rel._host_run_sort(no_zero, 4) is not None

    def test_signed_zero_sort_matches_device(self, monkeypatch):
        # end to end: a float key containing both signed zeros, with the
        # cost model begging for the host route — output order must
        # equal the device path's (payload column detects divergence)
        import numpy as np

        from datafusion_tpu import DataType, ExecutionContext, Field, Schema
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource
        from datafusion_tpu.exec.materialize import collect

        rng = np.random.default_rng(9)
        n = 512
        a = rng.uniform(-1, 1, n)
        a[::7] = 0.0
        a[::11] = -0.0
        schema = Schema([
            Field("a", DataType.FLOAT64, False),
            Field("tag", DataType.INT64, False),
        ])

        def run(env):
            for k, v in env.items():
                monkeypatch.setenv(k, v)
            b = make_host_batch(
                schema, [a.copy(), np.arange(n, dtype=np.int64)],
                [None, None], [None, None],
            )
            ctx = ExecutionContext(batch_size=n)
            ctx.register_datasource("t", MemoryDataSource(schema, [b]))
            return collect(ctx.sql("SELECT a, tag FROM t ORDER BY a")).to_rows()

        slow = run({"DATAFUSION_TPU_WIRE": "always",
                    "DATAFUSION_TPU_LINK_MBPS": "0.001"})
        fast = run({"DATAFUSION_TPU_WIRE": "always",
                    "DATAFUSION_TPU_LINK_MBPS": "1e9"})
        assert slow == fast

    def test_host_perm_cached_on_warm_requery(self, monkeypatch):
        # satellite: the host-routed permutation joins the same warm
        # cache as device key uploads — the third batches() pass on one
        # relation (seen, admitted, hit) skips the np.lexsort
        from datafusion_tpu.exec.materialize import collect
        from datafusion_tpu.utils.metrics import METRICS

        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        monkeypatch.setenv("DATAFUSION_TPU_LINK_MBPS", "0.001")
        ctx = self._src(nulls=False)
        rel = ctx.sql("SELECT a, b, s FROM t ORDER BY a, b")
        METRICS.reset()
        first = collect(rel).to_rows()
        assert METRICS.snapshot()["counts"].get("sort.host_routed_runs")
        collect(rel)  # second pass: key admitted to the cache
        before = METRICS.snapshot()["counts"].get("sort.perm_cache_hits", 0)
        third = collect(rel).to_rows()
        after = METRICS.snapshot()["counts"].get("sort.perm_cache_hits", 0)
        assert after > before
        assert third == first

    def test_full_sort_with_large_limit_host_route(self, monkeypatch):
        # LIMIT above TOPK_MAX takes the full-sort path; the host-routed
        # permutation must honor the prefix take
        import numpy as np

        from datafusion_tpu import DataType, ExecutionContext, Field, Schema
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource
        from datafusion_tpu.exec.materialize import collect
        from datafusion_tpu.exec.sort import TOPK_MAX
        from datafusion_tpu.utils.metrics import METRICS

        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        monkeypatch.setenv("DATAFUSION_TPU_LINK_MBPS", "0.001")
        rng = np.random.default_rng(3)
        n = TOPK_MAX + 4096
        schema = Schema([Field("a", DataType.INT64, False)])
        b = make_host_batch(schema, [rng.integers(0, 10**6, n)], [None], [None])
        ctx = ExecutionContext(batch_size=n)
        ctx.register_datasource("t", MemoryDataSource(schema, [b]))
        METRICS.reset()
        lim = TOPK_MAX + 1
        out = collect(ctx.sql(f"SELECT a FROM t ORDER BY a LIMIT {lim}"))
        assert METRICS.snapshot()["counts"].get("sort.host_routed_runs")
        vals = [r[0] for r in out.to_rows()]
        want = sorted(np.asarray(b.data[0])[: b.num_rows].tolist())[:lim]
        assert vals == want
