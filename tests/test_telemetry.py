"""Fleet telemetry plane (datafusion_tpu/obs/): flight-recorder ring
semantics (wraparound, concurrency, lock-free emit cost), OTLP/JSON
schema round-trip, Prometheus exposition format lock, fleet histogram
aggregation, SLO burn rates, and the slow/failed-query artifact
capture end to end (single-process and across real worker
subprocesses)."""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.obs import aggregate, otlp, recorder, slo
from datafusion_tpu.utils.metrics import METRICS, Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = Schema(
    [
        Field("region", DataType.UTF8, False),
        Field("v", DataType.INT64, False),
    ]
)


def _write_csv(path, rows=200, seed=3):
    rng = np.random.default_rng(seed)
    regions = ["north", "south", "east", "west"]
    with open(path, "w", encoding="utf-8") as f:
        f.write("region,v\n")
        for _ in range(rows):
            f.write(f"{regions[rng.integers(0, 4)]},"
                    f"{int(rng.integers(-100, 100))}\n")
    return str(path)


@pytest.fixture()
def flight(tmp_path):
    """Flight recorder scoped to this test: fresh ring, tmp dump dir,
    no throttle; every knob restored afterward so the always-on
    defaults hold for the rest of the suite."""
    saved = (recorder._ENABLED, recorder._CAP, recorder._SLOW_S,
             recorder._DIR, recorder._DUMP_INTERVAL_S)
    recorder.configure(enabled=True, directory=str(tmp_path),
                       dump_interval_s=0.0)
    recorder.clear()
    yield recorder
    recorder.configure(enabled=saved[0], capacity=saved[1],
                       slow_s=saved[2], directory=saved[3],
                       dump_interval_s=saved[4])
    recorder.clear()


class TestFlightRecorder:
    def test_emit_snapshot_and_trace_correlation(self, flight):
        from datafusion_tpu.obs import trace

        recorder.record("a", x=1)
        with trace.session() as tc:
            recorder.record("b", y="z")
        trace.drain(tc.trace_id)
        ev = recorder.events()
        assert [e["kind"] for e in ev] == ["a", "b"]
        assert ev[0]["attrs"] == {"x": 1}
        assert "trace_id" not in ev[0]
        assert ev[1]["trace_id"] == tc.trace_id
        # trace filter returns exactly the correlated events
        assert [e["kind"] for e in recorder.events(tc.trace_id)] == ["b"]

    def test_ring_wraparound(self, flight):
        recorder.configure(capacity=16)
        for i in range(40):
            recorder.record("e", i=i)
        ev = recorder.events()
        assert len(ev) == 16
        assert [e["attrs"]["i"] for e in ev] == list(range(24, 40))
        assert recorder.emitted() == 40  # total survives the wrap

    def test_concurrent_emit(self, flight):
        recorder.configure(capacity=1024)
        n_threads, per = 8, 2000
        errors = []

        def emit(t):
            try:
                for i in range(per):
                    recorder.record("c", t=t, i=i)
            except Exception as e:  # noqa: BLE001 — collected and asserted empty
                errors.append(e)

        threads = [threading.Thread(target=emit, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        # itertools.count is GIL-atomic: no emission is ever lost
        assert recorder.emitted() == n_threads * per
        ev = recorder.events()
        assert len(ev) == 1024
        assert all(e["kind"] == "c" for e in ev)

    def test_emit_is_cheap(self, flight):
        """The ≤2% warm-path budget: emit must stay in single-digit
        microseconds (bound is generous for CI noise — typical is
        ~1µs; a warm query emits ~10 events against a multi-ms wall)."""
        recorder.configure(capacity=4096)
        n = 20000
        t0 = time.perf_counter()
        for i in range(n):
            recorder.record("perf", i=i)
        per_emit = (time.perf_counter() - t0) / n
        assert per_emit < 50e-6, f"emit cost {per_emit * 1e6:.1f}µs"

    def test_disabled_is_noop(self, flight):
        recorder.configure(enabled=False)
        before = recorder.emitted()
        recorder.record("x")
        assert recorder.emitted() == before
        assert recorder.auto_capture("nope") is None

    def test_dump_and_throttle(self, flight, tmp_path):
        recorder.record("a")
        path = recorder.dump("manual")
        doc = json.loads(open(path, encoding="utf-8").read())
        assert doc["reason"] == "manual"
        assert doc["events"][0]["kind"] == "a"
        assert doc["node"].split(":")[0] in ("main", "worker")
        # throttle: with a long interval only the first auto dump lands
        recorder.configure(dump_interval_s=1000.0)
        assert recorder.auto_capture("one") is not None
        assert recorder.auto_capture("two") is None
        assert METRICS.counts.get("flight.dumps_throttled", 0) >= 1

    def test_crash_hook_dumps_and_chains(self, flight):
        calls = []
        prev, recorder._hook_installed = sys.excepthook, False
        sys.excepthook = lambda *a: calls.append(a)
        try:
            recorder.install_crash_hook()
            recorder.record("before-crash")
            try:
                raise ValueError("boom")
            except ValueError:
                sys.excepthook(*sys.exc_info())
            assert len(calls) == 1  # chained to the previous hook
            dumps = glob.glob(os.path.join(recorder.dump_dir(),
                                           "flight-*.json"))
            docs = [json.loads(open(p, encoding="utf-8").read())
                    for p in dumps]
            assert any(d["reason"] == "crash"
                       and "boom" in d.get("error", "") for d in docs)
        finally:
            sys.excepthook = prev
            recorder._hook_installed = False
            recorder._prev_excepthook = None


class TestOtlp:
    SPANS = [
        {"name": "query", "trace_id": "aa11", "span_id": "bb22",
         "parent_id": None, "start_ns": 100, "end_ns": 900,
         "attrs": {"n": 3, "f": 0.5, "ok": True, "s": "x"},
         "tid": 9, "proc": "main:1"},
        {"name": "worker.fragment", "trace_id": "aa11", "span_id": "cc33",
         "parent_id": "bb22", "start_ns": 200, "end_ns": 800,
         "attrs": {}, "tid": 4, "proc": "worker:2"},
    ]

    def test_schema_shape(self):
        doc = otlp.spans_to_otlp(self.SPANS)
        assert len(doc["resourceSpans"]) == 2  # one per process
        sp = doc["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
        assert len(sp["traceId"]) == 32 and len(sp["spanId"]) == 16
        assert sp["traceId"].endswith("aa11")
        assert isinstance(sp["startTimeUnixNano"], str)  # int64-as-string
        # attribute typing follows the OTLP value union
        vals = {a["key"]: a["value"] for a in sp["attributes"]}
        assert vals["n"] == {"intValue": "3"}
        assert vals["f"] == {"doubleValue": 0.5}
        assert vals["ok"] == {"boolValue": True}
        assert vals["s"] == {"stringValue": "x"}
        res = {a["key"]: a["value"]["stringValue"]
               for a in doc["resourceSpans"][0]["resource"]["attributes"]}
        assert res["service.name"] == "datafusion_tpu.main"
        assert res["service.instance.id"] == "main:1"

    def test_round_trip(self):
        back = otlp.otlp_to_spans(otlp.spans_to_otlp(self.SPANS))
        by_name = {s["name"]: s for s in back}
        assert set(by_name) == {"query", "worker.fragment"}
        q = by_name["query"]
        assert q["attrs"] == self.SPANS[0]["attrs"]
        assert q["tid"] == 9 and q["proc"] == "main:1"
        assert q["start_ns"] == 100 and q["end_ns"] == 900
        frag = by_name["worker.fragment"]
        # parent/child linkage survives (modulo canonical padding)
        assert frag["parent_id"] == q["span_id"]
        assert frag["trace_id"] == q["trace_id"]

    def test_export_file_env(self, tmp_path, monkeypatch):
        path = str(tmp_path / "otlp.jsonl")
        monkeypatch.setenv("DATAFUSION_TPU_OTLP_FILE", path)
        monkeypatch.delenv("DATAFUSION_TPU_OTLP_ENDPOINT", raising=False)
        assert otlp.export_spans(self.SPANS) == path
        assert otlp.export_spans(self.SPANS) == path  # appends
        lines = open(path, encoding="utf-8").read().strip().splitlines()
        assert len(lines) == 2
        assert len(otlp.otlp_to_spans(json.loads(lines[0]))) == 2

    def test_export_http_post(self, monkeypatch):
        import gzip
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        bodies = []
        encodings = []

        class _H(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
                n = int(self.headers["Content-Length"])
                raw = self.rfile.read(n)
                encodings.append(self.headers.get("Content-Encoding"))
                if self.headers.get("Content-Encoding") == "gzip":
                    raw = gzip.decompress(raw)
                bodies.append(json.loads(raw))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            endpoint = f"http://127.0.0.1:{srv.server_address[1]}/v1/traces"
            # direct POST gzips by default...
            status = otlp.post_otlp(endpoint, self.SPANS)
            assert status == 200
            assert bodies and "resourceSpans" in bodies[0]
            assert encodings[-1] == "gzip"
            # ...and plain JSON on request
            assert otlp.post_otlp(endpoint, self.SPANS,
                                  compress=False) == 200
            assert encodings[-1] is None
            # env-routed export batches: two queries' spans enqueue,
            # ONE flush POSTs them as one merged document
            monkeypatch.delenv("DATAFUSION_TPU_OTLP_FILE", raising=False)
            monkeypatch.setenv("DATAFUSION_TPU_OTLP_ENDPOINT", endpoint)
            otlp.flush()  # drain any prior state
            where = otlp.export_spans(self.SPANS)
            assert "batched" in where, where
            assert otlp.export_spans(self.SPANS) is not None
            assert otlp.pending() == 2 * len(self.SPANS)
            n_posts = len(bodies)
            assert otlp.flush() == 200
            assert otlp.pending() == 0
            assert len(bodies) == n_posts + 1  # one POST for both queries
            batched = otlp.otlp_to_spans(bodies[-1])
            assert len(batched) == 2 * len(self.SPANS)
            # a dead endpoint is swallowed at flush, never raised into
            # the query path
            monkeypatch.setenv("DATAFUSION_TPU_OTLP_ENDPOINT",
                               "http://127.0.0.1:9/v1/traces")
            assert otlp.export_spans(self.SPANS) is not None  # enqueued
            assert otlp.flush() is None
            assert METRICS.counts.get("obs.otlp_errors", 0) >= 1
            # endpoint vanishing between enqueue and flush is counted
            # loss, not silent idle
            monkeypatch.setenv("DATAFUSION_TPU_OTLP_ENDPOINT", endpoint)
            assert otlp.export_spans(self.SPANS) is not None  # enqueued
            assert otlp.pending() > 0
            monkeypatch.delenv("DATAFUSION_TPU_OTLP_ENDPOINT")
            errs = METRICS.counts.get("obs.otlp_errors", 0)
            assert otlp.flush() is None
            assert otlp.pending() == 0
            assert METRICS.counts.get("obs.otlp_errors", 0) == errs + 1
        finally:
            srv.shutdown()


class TestExpositionFormat:
    """Locks the Prometheus text format after the `_metric_name` fix:
    identifiers sanitize, label values ESCAPE (dots survive)."""

    def test_dotted_names_keep_dots_in_labels(self):
        m = Metrics()
        m.add("cache.result.hits", 2)
        m.add("cache_result_hits", 5)  # must NOT collide post-fix
        from datafusion_tpu.obs.export import prometheus_text

        text = prometheus_text(m)
        assert 'datafusion_tpu_events_total{name="cache.result.hits"} 2' \
            in text
        assert 'datafusion_tpu_events_total{name="cache_result_hits"} 5' \
            in text

    def test_label_values_escape(self):
        m = Metrics()
        m.add('odd"name\\with\nnasties', 1)
        from datafusion_tpu.obs.export import prometheus_text

        text = prometheus_text(m)
        line = next(ln for ln in text.splitlines() if "odd" in ln)
        assert line == (
            'datafusion_tpu_events_total{name="odd\\"name\\\\with\\nnasties"} 1'
        )

    def test_metric_name_identifier_rules(self):
        from datafusion_tpu.obs.export import _metric_name

        assert _metric_name("a.b-c") == "a_b_c"
        assert _metric_name("a..b") == "a_b"  # runs collapse
        assert _metric_name("9lives") == "_9lives"  # no leading digit
        assert _metric_name("") == "_"

    def test_every_sample_line_parses(self):
        m = Metrics()
        m.add("x.y")
        m.observe("stage-a", 0.25)
        m.gauge("g.h", 1.5)
        from datafusion_tpu.obs.export import prometheus_text

        import re

        sample = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*\{[a-z]+="[^\n]*"\} [-0-9.e+]+$'
        )
        for line in prometheus_text(m).strip().splitlines():
            if not line.startswith("#"):
                assert sample.match(line), line


class TestHistogramAggregation:
    def test_quantiles_and_merge(self):
        h = aggregate.LatencyHistogram()
        for _ in range(98):
            h.observe(0.001)
        for _ in range(2):
            h.observe(2.0)
        assert h.quantile(0.5) == pytest.approx(0.001024, rel=0.01)
        assert h.quantile(0.99) > 1.0
        other = aggregate.LatencyHistogram()
        other.observe(0.001)
        other.merge(h.snapshot())  # merge accepts wire-form dicts
        assert other.count == 101
        assert other.sum_s == pytest.approx(h.sum_s + 0.001)

    def test_overflow_quantile_is_a_lower_bound(self):
        # 98 fast queries + 2 hung ones whose latency exceeds every
        # finite bucket: the p99 lands in the +inf overflow slot.  The
        # report must be a LOWER bound on the tail (the largest finite
        # bucket edge, ~67s), never the whole-population mean (~4s) that
        # would hide a hang behind the fast majority.
        h = aggregate.LatencyHistogram()
        for _ in range(98):
            h.observe(0.001)
        for _ in range(2):
            h.observe(200.0)
        p99 = h.quantile(0.99)
        assert p99 >= aggregate.bucket_upper_bound_s(26)  # largest finite
        mean = h.sum_s / h.count
        assert p99 > mean  # not the mean-of-everything dodge
        # when overflow members dominate, the mean exceeds the edge and
        # becomes the tighter lower bound
        h2 = aggregate.LatencyHistogram()
        for _ in range(10):
            h2.observe(500.0)
        assert h2.quantile(0.99) == pytest.approx(500.0)

    def test_fleet_merge_and_gauges(self):
        agg = aggregate.FleetAggregator(include_local=False)
        h1 = aggregate.LatencyHistogram()
        h2 = aggregate.LatencyHistogram()
        for _ in range(50):
            h1.observe(0.002)
        for _ in range(50):
            h2.observe(0.5)
        now = time.time()
        agg.ingest("w1:1", {"ts": now,
                            "histograms": {"fragment.latency": h1.snapshot()},
                            "counts": {"cache.fragment.hits": 30,
                                       "cache.fragment.misses": 10},
                            "gauges": {}})
        agg.ingest("w2:2", {"ts": now,
                            "histograms": {"fragment.latency": h2.snapshot()},
                            "counts": {"cache.fragment.hits": 10,
                                       "cache.fragment.misses": 10},
                            "gauges": {}})
        fleet = agg.fleet()
        assert fleet["nodes"] == 2
        merged = fleet["histograms"]["fragment.latency"]
        assert merged.count == 100
        # the fleet p99 sees w2's slow half even though w1 is fast
        assert merged.quantile(0.99) > 0.25
        assert fleet["derived"]["fragment_cache_hit_rate"] == \
            pytest.approx(40 / 60)
        gauges = agg.gauges()
        assert gauges["fleet.nodes"] == 2
        assert gauges["fleet.fragment.latency.count"] == 100
        assert "fleet.fragment.latency.p99_s" in gauges
        top = agg.top_text()
        assert "w1:1" in top and "w2:2" in top and "fleet: 2 node(s)" in top

    def test_stale_snapshots_drop_out(self):
        agg = aggregate.FleetAggregator(stale_s=0.01, include_local=False)
        agg.ingest("old:1", {"ts": time.time() - 10, "histograms": {},
                             "counts": {}, "gauges": {}})
        assert agg.fleet()["nodes"] == 0

    def test_malformed_snapshot_ignored(self):
        agg = aggregate.FleetAggregator(include_local=False)
        agg.ingest("bad:1", None)
        agg.ingest("bad:2", {"no": "histograms"})
        assert agg.fleet()["nodes"] == 0


class TestSlo:
    def test_env_declaration(self):
        objs = slo.objectives_from_env({
            "DATAFUSION_TPU_SLO_WARM_Q1_P99": "0.5",
            "DATAFUSION_TPU_SLO_INGEST_P50": "2.0",
            "DATAFUSION_TPU_SLO_ERROR_RATE": "0.01",
            "DATAFUSION_TPU_SLO_WINDOW_S": "60",  # knob, not objective
            "DATAFUSION_TPU_SLO_BOGUS": "zzz",    # unparseable: skipped
            # out-of-domain thresholds skip too (this parser runs at
            # module import — an env typo must not fail every query)
            "DATAFUSION_TPU_SLO_ZERO_P99": "0",
            "DATAFUSION_TPU_SLO_NEG_ERROR_RATE": "-1",
        })
        by_name = {o.name: o for o in objs}
        assert set(by_name) == {"warm_q1", "ingest", "error_rate"}
        assert by_name["warm_q1"].kind == "p99"
        assert by_name["warm_q1"].threshold == 0.5
        assert by_name["error_rate"].kind == "error_rate"

    def test_error_rate_burn(self):
        wd = slo.SloWatchdog(min_samples=10, capture_on_breach=False)
        wd.add(slo.Objective("err", "error_rate", 0.01))
        for i in range(100):
            wd.observe(0.001, error=(i % 10 == 0))  # 10% failures
        row = wd.evaluate()[0]
        assert row["value"] == pytest.approx(0.10)
        assert row["burn_rate"] == pytest.approx(10.0)
        assert row["breached"]
        assert METRICS.gauges["slo.err.burn_rate"] == pytest.approx(10.0)
        assert METRICS.gauges["slo.err.breached"] == 1

    def test_latency_burn_healthy_and_breached(self):
        wd = slo.SloWatchdog(min_samples=10, capture_on_breach=False)
        wd.add(slo.Objective("lat", "p99", 0.1))
        for _ in range(100):
            wd.observe(0.01)
        row = wd.evaluate()[0]
        assert row["burn_rate"] == 0.0 and not row["breached"]
        for _ in range(5):
            wd.observe(0.5)  # ~4.8% now over the p99 threshold
        row = wd.evaluate()[0]
        assert row["burn_rate"] > 1.0 and row["breached"]

    def test_min_samples_quorum(self):
        wd = slo.SloWatchdog(min_samples=50, capture_on_breach=False)
        wd.add(slo.Objective("q", "p99", 0.001))
        for _ in range(10):
            wd.observe(1.0)  # 100% bad, but below quorum
        assert not wd.evaluate()[0]["breached"]

    def test_breach_captures_flight_dump(self, flight):
        wd = slo.SloWatchdog(min_samples=5, capture_on_breach=True)
        wd.add(slo.Objective("cap", "error_rate", 0.01))
        for _ in range(10):
            wd.observe(0.001, error=True)
        assert wd.evaluate()[0]["breached"]
        dumps = glob.glob(os.path.join(recorder.dump_dir(),
                                       "flight-*.json"))
        docs = [json.loads(open(p, encoding="utf-8").read())
                for p in dumps]
        assert any(d["reason"] == "slo_breach"
                   and d["slo"]["name"] == "cap" for d in docs)


class TestQueryFunnel:
    @pytest.fixture()
    def ctx(self, tmp_path):
        c = ExecutionContext(device="cpu")
        c.register_csv("t", _write_csv(tmp_path / "t.csv"), SCHEMA)
        return c

    def test_query_events_and_histogram(self, ctx, flight):
        before = aggregate.HISTOGRAMS.get("query.latency")
        before_n = before.count if before else 0
        ctx.sql_collect("SELECT region, SUM(v) FROM t GROUP BY region")
        kinds = [e["kind"] for e in recorder.events()]
        for expected in ("query.plan", "query.admit", "query.verify",
                         "query.done"):
            assert expected in kinds, kinds
        assert aggregate.HISTOGRAMS["query.latency"].count == before_n + 1

    def test_admission_counters(self, ctx, flight):
        base = METRICS.counts["queries_admitted"]
        ctx.sql_collect("SELECT region FROM t")
        assert METRICS.counts["queries_admitted"] == base + 1
        # the declared stubs render at zero and survive reset()
        text = ctx.metrics_text()
        assert 'name="queries_queued"' in text
        assert 'name="queries_shed"' in text
        METRICS.reset()
        assert "queries_shed" in METRICS.counts
        ctx.sql_collect("SELECT region FROM t")  # restore some state

    def test_cached_repeat_records_hit_event(self, ctx, flight):
        sql = "SELECT region, SUM(v) FROM t GROUP BY region"
        ctx.sql_collect(sql)
        recorder.clear()
        ctx.sql_collect(sql)
        kinds = [e["kind"] for e in recorder.events()]
        assert "cache.hit" in kinds
        hit = next(e for e in recorder.events()
                   if e["kind"] == "cache.hit")
        assert hit["attrs"]["level"] == "result"

    def test_slow_query_auto_capture(self, ctx, flight, tmp_path):
        recorder.configure(slow_s=0.0)  # every query is "slow"
        ctx.sql_collect("SELECT region, SUM(v) FROM t GROUP BY region")
        dumps = glob.glob(os.path.join(str(tmp_path), "flight-*.json"))
        docs = [json.loads(open(p, encoding="utf-8").read())
                for p in dumps]
        doc = next(d for d in docs if d["reason"] == "slow_query")
        assert doc["query"]["label"] == "Aggregate"
        assert doc["query"]["wall_s"] >= 0
        assert any(e["kind"] == "query.done" for e in doc["events"])
        assert METRICS.counts.get("flight.slow_queries", 0) >= 1

    def test_failed_query_auto_capture(self, ctx, flight, tmp_path):
        from datafusion_tpu.errors import IoError

        ctx.register_csv("gone", str(tmp_path / "missing.csv"), SCHEMA)
        with pytest.raises(IoError, match="missing.csv"):
            ctx.sql_collect("SELECT region FROM gone")
        kinds = [e["kind"] for e in recorder.events()]
        assert "query.error" in kinds
        dumps = glob.glob(os.path.join(str(tmp_path), "flight-*.json"))
        docs = [json.loads(open(p, encoding="utf-8").read())
                for p in dumps]
        doc = next(d for d in docs if d["reason"] == "query_failure")
        assert doc["query"]["error"]

    def test_explain_analyze_capture_includes_otlp(self, ctx, flight,
                                                   tmp_path):
        recorder.configure(slow_s=0.0)
        res = ctx.sql_collect(
            "EXPLAIN ANALYZE SELECT region, SUM(v) FROM t GROUP BY region"
        )
        assert res.spans
        dumps = glob.glob(os.path.join(str(tmp_path), "flight-*.json"))
        docs = [json.loads(open(p, encoding="utf-8").read())
                for p in dumps]
        doc = next(d for d in docs if d["reason"] == "slow_query")
        # instrumented run: the artifact embeds the stitched OTLP trace
        # and the operator report beside the flight events
        assert doc["query"]["trace_id"]
        assert doc["otlp"]["resourceSpans"]
        got = otlp.otlp_to_spans(doc["otlp"])
        # captured mid-session: finished operator spans are in (the
        # root "query" span is still open at the materialization
        # boundary, so it is not — the full set goes to the env-gated
        # OTLP export at session end)
        assert any(s["name"].startswith("op.") for s in got)
        assert all(
            s["trace_id"].endswith(doc["query"]["trace_id"]) for s in got
        )
        assert any("rows=" in line for line in doc["explain"])

    def test_explain_analyze_exports_otlp_once(self, ctx, flight,
                                               tmp_path, monkeypatch):
        # the funnel's in-flight export yields to explain_analyze's
        # complete-set export: ONE document per analyzed query (a
        # consumer that trusts span ids would double-count otherwise),
        # and it carries the root span the mid-session set lacks
        out = tmp_path / "q.otlp.jsonl"
        monkeypatch.setenv("DATAFUSION_TPU_OTLP_FILE", str(out))
        ctx.sql_collect(
            "EXPLAIN ANALYZE SELECT region, SUM(v) FROM t GROUP BY region"
        )
        lines = out.read_text(encoding="utf-8").strip().splitlines()
        assert len(lines) == 1, f"expected one OTLP document, got {len(lines)}"
        spans = otlp.otlp_to_spans(json.loads(lines[0]))
        assert any(s["name"] == "query" for s in spans)  # root included

    def test_plain_query_exports_otlp_once(self, ctx, flight, tmp_path,
                                           monkeypatch):
        from datafusion_tpu.obs import trace as obs_trace

        out = tmp_path / "plain.otlp.jsonl"
        monkeypatch.setenv("DATAFUSION_TPU_OTLP_FILE", str(out))
        with obs_trace.session():
            ctx.sql_collect("SELECT region FROM t")
        lines = out.read_text(encoding="utf-8").strip().splitlines()
        assert len(lines) == 1


class TestClusterTelemetryPiggyback:
    def test_lease_refresh_carries_snapshot(self):
        from datafusion_tpu.cluster.client import LocalClusterClient
        from datafusion_tpu.cluster.service import ClusterState

        state = ClusterState()
        c = LocalClusterClient(state)
        lease = c.lease_grant(30.0)["lease"]
        c.put("workers/10.0.0.1:99", {"addr": "10.0.0.1:99"}, lease=lease)
        snap = {"ts": time.time(), "histograms": {}, "counts": {"x": 1},
                "gauges": {}}
        c.lease_refresh(lease, telemetry=snap)
        served = c.telemetry()["workers"]
        assert served == {"10.0.0.1:99": snap}
        # the snapshot dies with the membership key
        c.lease_revoke(lease)
        assert c.telemetry()["workers"] == {}

    def test_expired_lease_drops_snapshot(self):
        from datafusion_tpu.cluster.service import ClusterState

        state = ClusterState()
        lease = state.lease_grant(10.0, now=0.0)["lease"]
        state.put("workers/a:1", {"addr": "a:1"}, lease=lease, now=1.0)
        state.lease_refresh(lease, now=2.0,
                            telemetry={"histograms": {}, "counts": {}})
        assert "a:1" in state.telemetry(now=3.0)
        assert state.telemetry(now=100.0) == {}  # TTL lapsed

    def test_lease_churn_lands_in_flight_ring(self, flight):
        from datafusion_tpu.cluster.service import ClusterState

        state = ClusterState()
        lease = state.lease_grant(10.0, now=0.0)["lease"]
        state.put("workers/b:2", {"addr": "b:2"}, lease=lease, now=0.5)
        state.membership(now=100.0)  # expiry sweep
        kinds = [e["kind"] for e in recorder.events()]
        assert "cluster.join" in kinds
        assert "cluster.leave" in kinds
        assert "cluster.lease_gone" in kinds


class TestDistributedFleet:
    """Two real worker OS processes: fleet aggregation from >= 2
    workers, the worker flight_dump request, and the correlated
    artifact set for a slow distributed query."""

    @pytest.fixture(scope="class")
    def workers(self, tmp_path_factory):
        tmpdir = str(tmp_path_factory.mktemp("fleet"))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        procs, addrs = [], []
        try:
            for _ in range(2):
                proc = subprocess.Popen(
                    [sys.executable, "-m", "datafusion_tpu.worker",
                     "--bind", "127.0.0.1:0", "--device", "cpu"],
                    cwd=REPO, env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL, text=True,
                )
                procs.append(proc)
                line = proc.stdout.readline()
                assert "listening on" in line, line
                host, port = line.strip().rsplit(" ", 1)[1].rsplit(":", 1)
                addrs.append((host, int(port)))
            yield tmpdir, addrs
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                p.wait(timeout=10)

    def _ctx(self, tmpdir, addrs):
        from datafusion_tpu.exec.datasource import CsvDataSource
        from datafusion_tpu.parallel.coordinator import DistributedContext
        from datafusion_tpu.parallel.partition import PartitionedDataSource

        paths = [
            _write_csv(os.path.join(tmpdir, f"p{i}.csv"), seed=i)
            for i in range(3)
        ]
        ctx = DistributedContext(addrs)
        ctx.register_datasource("t", PartitionedDataSource(
            [CsvDataSource(p, SCHEMA, True, 131072) for p in paths]
        ))
        return ctx

    def test_fleet_aggregation_from_two_workers(self, workers, flight):
        tmpdir, addrs = workers
        ctx = self._ctx(tmpdir, addrs)
        ctx.sql_collect("SELECT region, SUM(v) FROM t GROUP BY region")
        assert ctx.fleet_refresh() == 2
        fleet = ctx.telemetry.fleet()
        assert fleet["nodes"] == 3  # 2 workers + local
        frag = fleet["histograms"].get("fragment.latency")
        assert frag is not None and frag.count >= 3  # 3 partitions served
        gauges = ctx.telemetry.gauges()
        assert "fleet.fragment.latency.p99_s" in gauges
        assert "fleet.query.latency.p99_s" in gauges
        text = ctx.metrics_text()
        assert 'name="fleet.fragment.latency.p99_s"' in text
        top = ctx.top_text()
        for host, port in addrs:
            assert f"{host}:{port}" in top
        # SLO burn gauges ride the same scrape once an objective arms
        slo.WATCHDOG.add(slo.Objective("fleet_p99", "p99", 60.0))
        try:
            ctx.metrics_text()
            assert "slo.fleet_p99.burn_rate" in METRICS.gauges
        finally:
            slo.WATCHDOG.objectives.pop()

    def test_worker_flight_dump_request(self, workers, flight):
        tmpdir, addrs = workers
        ctx = self._ctx(tmpdir, addrs)
        ctx.sql_collect("SELECT region, SUM(v) FROM t GROUP BY region")
        dumped = [w.flight_dump() for w in ctx.workers]
        assert all(d is not None for d in dumped)
        kinds = {e["kind"] for d in dumped for e in d["events"]}
        assert "fragment.serve" in kinds

    def test_slow_distributed_query_artifact_set(self, workers, flight,
                                                 tmp_path):
        tmpdir, addrs = workers
        recorder.configure(slow_s=0.0, directory=str(tmp_path))
        ctx = self._ctx(tmpdir, addrs)
        res = ctx.sql_collect(
            "EXPLAIN ANALYZE SELECT region, SUM(v) FROM t GROUP BY region"
        )
        dumps = glob.glob(os.path.join(str(tmp_path), "flight-*.json"))
        docs = [json.loads(open(p, encoding="utf-8").read())
                for p in dumps]
        doc = next(d for d in docs if d["reason"] == "slow_query")
        # one correlated artifact: local events + every worker's ring +
        # the stitched OTLP trace + the operator report
        assert set(doc["nodes"]) == {f"{h}:{p}" for h, p in addrs}
        worker_kinds = {
            e["kind"]
            for nd in doc["nodes"].values() for e in nd["events"]
        }
        assert "fragment.serve" in worker_kinds
        otlp_spans = otlp.otlp_to_spans(doc["otlp"])
        procs = {s["proc"] for s in otlp_spans}
        assert any(p.startswith("worker") for p in procs)
        assert any(p.startswith("main") for p in procs)
        assert res.spans  # the analyzed run itself succeeded
