"""End-to-end execution tests.

Models the reference's operator/integration tests
(`projection.rs:85-107`: real file fixtures, no mocks) and its
example-as-test (`examples/csv_sql.rs` — the uk_cities query is the
canonical smoke-proof of the full pipeline).
"""

import os

import numpy as np
import pytest

from datafusion_tpu import DataType, ExecutionContext, Field, Schema


@pytest.fixture
def ctx(test_data_dir):
    c = ExecutionContext(batch_size=1024)
    c.register_csv(
        "cities",
        os.path.join(test_data_dir, "uk_cities.csv"),
        Schema(
            [
                Field("city", DataType.UTF8, False),
                Field("lat", DataType.FLOAT64, False),
                Field("lng", DataType.FLOAT64, False),
            ]
        ),
        has_header=False,
    )
    c.register_csv(
        "people",
        os.path.join(test_data_dir, "people.csv"),
        Schema(
            [
                Field("id", DataType.INT32, False),
                Field("first_name", DataType.UTF8, False),
            ]
        ),
        has_header=True,
    )
    c.register_csv(
        "null_test",
        os.path.join(test_data_dir, "null_test.csv"),
        Schema(
            [
                Field("c_int", DataType.INT32, True),
                Field("c_float", DataType.FLOAT64, True),
                Field("c_string", DataType.UTF8, True),
                Field("c_bool", DataType.BOOLEAN, True),
            ]
        ),
        has_header=True,
    )
    c.register_csv(
        "numerics",
        os.path.join(test_data_dir, "numerics.csv"),
        Schema(
            [
                Field("a", DataType.INT64, False),
                Field("b", DataType.INT64, False),
                Field("a_f", DataType.FLOAT64, False),
                Field("b_f", DataType.FLOAT64, False),
            ]
        ),
        has_header=True,
    )
    return c


def test_csv_sql_example(ctx):
    # the reference's examples/csv_sql.rs workload — its only end-to-end proof
    t = ctx.sql_collect(
        "SELECT city, lat, lng, lat + lng FROM cities "
        "WHERE lat > 51.0 AND lat < 53"
    )
    assert t.schema.names() == ["city", "lat", "lng", "binary_expr"]
    rows = t.to_rows()
    assert len(rows) == 18  # uk_cities.csv rows with 51 < lat < 53
    for _city, lat, lng, s in rows:
        assert 51.0 < lat < 53.0
        assert s == pytest.approx(lat + lng)
    assert any(r[0].startswith("Solihull") for r in rows)


def test_projection_all_columns(ctx):
    # ported from reference projection.rs:85-107
    t = ctx.sql_collect("SELECT id FROM people")
    assert t.schema.names() == ["id"]
    assert t.column_values(0) == list(range(1, 11))


def test_select_star(ctx):
    t = ctx.sql_collect("SELECT * FROM people")
    rows = t.to_rows()
    assert len(rows) == 10
    assert rows[:4] == [(1, "Andy"), (2, "Brian"), (3, "Chris"), (4, "Donna")]
    assert rows[-1] == (10, "Juliet")


def test_string_filter(ctx):
    t = ctx.sql_collect("SELECT id FROM people WHERE first_name = 'Brian'")
    assert t.column_values(0) == [2]
    t = ctx.sql_collect("SELECT id FROM people WHERE first_name != 'Brian'")
    assert t.column_values(0) == [1] + list(range(3, 11))
    # ordered comparison on strings via dictionary lookup table
    t = ctx.sql_collect("SELECT first_name FROM people WHERE first_name >= 'Gary'")
    assert sorted(t.column_values(0)) == ["Gary", "Helen", "Irene", "Juliet"]


def test_arithmetic(ctx):
    t = ctx.sql_collect("SELECT a + b, a - b, a * b, a_f / b_f FROM numerics")
    rows = t.to_rows()
    assert rows[0][0] == 5 and rows[0][1] == -1 and rows[0][2] == 6
    assert rows[0][3] == pytest.approx(3.14 / -2.13)


def test_int_division_and_modulus(ctx):
    t = ctx.sql_collect("SELECT b / a, b % a FROM numerics WHERE a > 0")
    # rows where a>0: (2,3) and (5,5)
    assert t.to_rows() == [(1, 1), (1, 0)]


def test_nulls(ctx):
    t = ctx.sql_collect("SELECT c_int, c_float, c_string FROM null_test")
    vals = t.column_values(1)
    assert vals[2] is None  # row 3 has empty c_float
    assert t.column_values(2)[3] is None  # row 4 has empty c_string
    t = ctx.sql_collect("SELECT c_int FROM null_test WHERE c_float IS NULL")
    assert t.column_values(0) == [3]
    t = ctx.sql_collect("SELECT c_int FROM null_test WHERE c_float IS NOT NULL")
    assert t.column_values(0) == [1, 2, 4, 5]


def test_null_comparison_drops_rows(ctx):
    # SQL: a comparison with NULL input is NULL -> row filtered out
    t = ctx.sql_collect("SELECT c_int FROM null_test WHERE c_float > 0.0")
    assert t.column_values(0) == [1, 2, 4, 5]


def test_global_aggregates(ctx):
    t = ctx.sql_collect(
        "SELECT MIN(lat), MAX(lat), SUM(lat), AVG(lat), COUNT(1) FROM cities"
    )
    lats = _cities_lats(ctx)
    row = t.to_rows()[0]
    assert row[0] == pytest.approx(lats.min())
    assert row[1] == pytest.approx(lats.max())
    assert row[2] == pytest.approx(lats.sum())
    assert row[3] == pytest.approx(lats.mean())
    assert row[4] == len(lats)


def test_aggregate_with_filter(ctx):
    t = ctx.sql_collect("SELECT COUNT(1), SUM(lat) FROM cities WHERE lat > 52")
    lats = _cities_lats(ctx)
    sel = lats[lats > 52]
    assert t.to_rows()[0][0] == len(sel)
    assert t.to_rows()[0][1] == pytest.approx(sel.sum())


def test_group_by_string(ctx):
    t = ctx.sql_collect(
        "SELECT c_bool, COUNT(1), SUM(c_int) FROM null_test GROUP BY c_bool"
    )
    by_key = {r[0]: r for r in t.to_rows()}
    # fixture: rows 1-3 true (c_int 1,2,3), rows 4-5 false (c_int 4,5)
    assert by_key[True][1] == 3 and by_key[True][2] == 6
    assert by_key[False][1] == 2 and by_key[False][2] == 9
    t2 = ctx.sql_collect(
        "SELECT first_name, COUNT(1) FROM people GROUP BY first_name"
    )
    rows2 = sorted(t2.to_rows())
    assert len(rows2) == 10
    assert rows2[:2] == [("Andy", 1), ("Brian", 1)]


def test_avg_of_nullable_column(ctx):
    t = ctx.sql_collect("SELECT AVG(c_float), COUNT(c_float) FROM null_test")
    row = t.to_rows()[0]
    # null row excluded from both
    assert row[1] == 4
    assert row[0] == pytest.approx((1.1 + 2.2 + 4.4 + 6.6) / 4)


def test_order_by(ctx):
    t = ctx.sql_collect("SELECT city, lat FROM cities ORDER BY lat DESC LIMIT 3")
    lats = [r[1] for r in t.to_rows()]
    assert lats == sorted(lats, reverse=True)
    assert len(lats) == 3
    all_lats = sorted(_cities_lats(ctx), reverse=True)
    assert lats == pytest.approx(all_lats[:3])


def test_order_by_string(ctx):
    t = ctx.sql_collect("SELECT first_name FROM people ORDER BY first_name DESC")
    assert t.column_values(0) == [
        "Juliet", "Irene", "Helen", "Gary", "Fiona",
        "Edward", "Donna", "Chris", "Brian", "Andy",
    ]


def test_limit(ctx):
    t = ctx.sql_collect("SELECT id FROM people LIMIT 2")
    assert t.column_values(0) == [1, 2]


def test_select_literal_no_table(ctx):
    t = ctx.sql_collect("SELECT 1")
    assert t.to_rows() == [(1,)]
    t = ctx.sql_collect("SELECT sqrt(9)")
    assert t.to_rows()[0][0] == pytest.approx(3.0)


def test_udf(ctx):
    import jax.numpy as jnp

    ctx.register_udf("plus_one", [DataType.FLOAT64], DataType.FLOAT64, lambda x: x + 1)
    t = ctx.sql_collect("SELECT plus_one(lat) FROM cities LIMIT 1")
    lats = _cities_lats(ctx)
    assert t.to_rows()[0][0] == pytest.approx(lats[0] + 1)


def test_ddl_create_external_table(ctx, test_data_dir):
    path = os.path.join(test_data_dir, "uk_cities.csv")
    res = ctx.sql(
        f"CREATE EXTERNAL TABLE uk (city VARCHAR(100) NOT NULL, "
        f"lat DOUBLE NOT NULL, lng DOUBLE NOT NULL) "
        f"STORED AS CSV WITHOUT HEADER ROW LOCATION '{path}'"
    )
    assert "uk" in ctx.datasources
    t = ctx.sql_collect("SELECT COUNT(1) FROM uk")
    assert t.to_rows()[0][0] == 37


def test_explain(ctx):
    res = ctx.sql("EXPLAIN SELECT id FROM people WHERE id > 2")
    s = repr(res)
    assert "Projection" in s and "Selection" in s and "TableScan" in s


def test_cast(ctx):
    t = ctx.sql_collect("SELECT CAST(id AS DOUBLE) FROM people")
    assert t.column_values(0) == [float(i) for i in range(1, 11)]
    assert t.schema.fields[0].data_type == DataType.FLOAT64


def test_cpu_device_explicit(test_data_dir):
    c = ExecutionContext(device="cpu")
    c.register_csv(
        "cities",
        os.path.join(test_data_dir, "uk_cities.csv"),
        Schema(
            [
                Field("city", DataType.UTF8, False),
                Field("lat", DataType.FLOAT64, False),
                Field("lng", DataType.FLOAT64, False),
            ]
        ),
        has_header=False,
    )
    t = c.sql_collect("SELECT COUNT(1) FROM cities")
    assert t.to_rows()[0][0] == 37


def _cities_lats(ctx):
    import csv

    ds = ctx.datasources["cities"]
    with open(ds.path) as f:
        return np.array([float(r[1]) for r in csv.reader(f)])

def test_count_star_vs_count_column(ctx):
    # COUNT(1) counts rows even where columns are NULL; COUNT(col)
    # counts non-null values of that column
    t = ctx.sql_collect("SELECT COUNT(1) FROM null_test")
    assert t.to_rows()[0][0] == 5
    t = ctx.sql_collect("SELECT COUNT(c_float) FROM null_test")
    assert t.to_rows()[0][0] == 4
    # COUNT(1) where column 0 itself has the NULL (c_int is col 0 and
    # fully populated here, so force the edge through c_float as arg 0
    # of the rewritten plan): the flag, not the arg, drives row counting
    t = ctx.sql_collect("SELECT COUNT(1), COUNT(c_float) FROM null_test WHERE c_int > 0")
    assert t.to_rows()[0] == (5, 4)


def test_group_by_null_keys(ctx):
    # SQL: NULL forms its own group, distinct from every real value
    t = ctx.sql_collect(
        "SELECT c_string, COUNT(1) FROM null_test GROUP BY c_string"
    )
    rows = t.to_rows()
    null_groups = [r for r in rows if r[0] is None]
    assert len(null_groups) == 1
    assert null_groups[0][1] == 2  # rows 4 and 5 have null c_string
    real = {r[0]: r[1] for r in rows if r[0] is not None}
    assert real == {"1.11": 1, "2.22": 1, "3.33": 1}


def test_or_with_null_operand(ctx):
    # TRUE OR NULL = TRUE: row 3 (c_float null, c_int 3) must survive
    t = ctx.sql_collect(
        "SELECT c_int FROM null_test WHERE c_int = 3 OR c_float > 100.0"
    )
    assert t.column_values(0) == [3]
    # FALSE AND NULL = FALSE is just dropped either way; but
    # NULL AND TRUE = NULL drops the row
    t = ctx.sql_collect(
        "SELECT c_int FROM null_test WHERE c_float > 0.0 AND c_int > 0"
    )
    assert t.column_values(0) == [1, 2, 4, 5]


class TestHighCardinalityGroupBy:
    def _mem_ctx(self, n, n_groups, seed=0, batch=4096):
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        rng = np.random.default_rng(seed)
        schema = Schema(
            [Field("k", DataType.INT64, False), Field("v", DataType.FLOAT64, False)]
        )
        keys = rng.integers(0, n_groups, n)
        vals = rng.uniform(0, 100, n)
        batches = [
            make_host_batch(
                schema,
                [keys[i : i + batch], vals[i : i + batch]],
                [None, None],
                [None, None],
            )
            for i in range(0, n, batch)
        ]
        ctx = ExecutionContext(batch_size=batch)
        ctx.register_datasource("t", MemoryDataSource(schema, batches))
        return ctx, keys, vals

    def test_many_groups_across_batches(self):
        # far above DENSE_GROUP_MAX: exercises the vectorized encoder
        # and the large-capacity update path over multiple batches
        n, n_groups = 40_000, 5_000
        ctx, keys, vals = self._mem_ctx(n, n_groups)
        t = ctx.sql_collect(
            "SELECT k, SUM(v), COUNT(1), MIN(v), AVG(v) FROM t GROUP BY k"
        )
        assert t.num_rows == len(np.unique(keys))
        got = {r[0]: r[1:] for r in t.to_rows()}
        for g in np.unique(keys)[:50]:
            sel = vals[keys == g]
            s, c, mn, av = got[int(g)]
            np.testing.assert_allclose(s, sel.sum(), rtol=1e-12)
            assert c == len(sel)
            np.testing.assert_allclose(mn, sel.min(), rtol=1e-12)
            np.testing.assert_allclose(av, sel.mean(), rtol=1e-12)

    def test_slot_sharing_sum_avg_count(self):
        # SUM(v)/AVG(v)/COUNT(v) share accumulator slots; results must
        # still be independent and correct
        from datafusion_tpu.exec.aggregate import AggregateRelation

        n, n_groups = 10_000, 7
        ctx, keys, vals = self._mem_ctx(n, n_groups)
        rel = ctx.sql("SELECT k, SUM(v), AVG(v), COUNT(1), COUNT(k) FROM t GROUP BY k")
        agg = rel
        while not isinstance(agg, AggregateRelation):
            agg = agg.child
        # 1 shared sum slot + 1 shared cnt slot for v, 1 cnt slot for k
        assert len(agg.slots) == 3
        from datafusion_tpu.exec.materialize import collect

        t = collect(rel)
        got = {r[0]: r[1:] for r in t.to_rows()}
        for g in range(n_groups):
            sel = vals[keys == g]
            s, av, c1, ck = got[g]
            np.testing.assert_allclose(s, sel.sum(), rtol=1e-12)
            np.testing.assert_allclose(av, sel.mean(), rtol=1e-12)
            assert c1 == len(sel) and ck == len(sel)

    def test_encoder_null_keys_and_growth(self):
        from datafusion_tpu.exec.aggregate import GroupKeyEncoder

        enc = GroupKeyEncoder(1)
        a = np.asarray([5, 7, 5, 9], np.int64)
        ids1 = enc.encode([a], [np.asarray([True, True, False, True])])
        # 5, 7, NULL, 9 -> 4 distinct groups (NULL groups separately)
        assert len(set(ids1.tolist())) == 4
        # same keys in a later batch map to the same ids
        ids2 = enc.encode([a], [np.asarray([True, True, False, True])])
        np.testing.assert_array_equal(ids1, ids2)
        # new keys get fresh ids, old ids stable
        ids3 = enc.encode([np.asarray([7, 100], np.int64)], [None])
        assert ids3[0] == ids1[1]
        assert ids3[1] == enc.num_groups - 1
        vals, valid = enc.key_column(0)
        assert valid is not None and not valid[ids1[2]]

    def test_float_group_keys_bitcast(self):
        # float GROUP BY keys must not merge 1.5 and 1.7 (value cast)
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        schema = Schema(
            [Field("k", DataType.FLOAT64, False), Field("v", DataType.INT64, False)]
        )
        k = np.asarray([1.5, 1.7, 2.5, 1.5, -0.0, 0.0])
        v = np.asarray([1, 2, 4, 8, 16, 32], np.int64)
        ctx2 = ExecutionContext()
        ctx2.register_datasource(
            "ft",
            MemoryDataSource(
                schema, [make_host_batch(schema, [k, v], [None, None], [None, None])]
            ),
        )
        t = ctx2.sql_collect("SELECT k, SUM(v) FROM ft GROUP BY k")
        got = {r[0]: r[1] for r in t.to_rows()}
        assert got == {1.5: 9, 1.7: 2, 2.5: 4, 0.0: 48}

    def test_string_minmax_many_groups_dict_growth(self):
        # >DENSE_GROUP_MAX groups with MIN/MAX over Utf8, where batch 2
        # grows the dictionary (ranks shift between merges)
        from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        rng = np.random.default_rng(3)
        schema = Schema(
            [Field("k", DataType.INT64, False), Field("s", DataType.UTF8, False)]
        )
        n_groups = 200
        d = StringDictionary()
        all_k, all_s, batches = [], [], []
        # batch 1 uses words starting m..z; batch 2 adds a..l words that
        # sort BEFORE every earlier dictionary entry
        for lo, hi in ((12, 26), (0, 26)):
            k = rng.integers(0, n_groups, 3000)
            words = [
                chr(97 + rng.integers(lo, hi)) + f"{rng.integers(0, 100):02d}"
                for _ in range(3000)
            ]
            codes = d.encode(words)
            batches.append(
                make_host_batch(schema, [k, codes], [None, None], [None, d])
            )
            all_k.append(k)
            all_s.extend(words)
        keys = np.concatenate(all_k)
        words = np.asarray(all_s, dtype=object)
        ctx = ExecutionContext(batch_size=4096)
        ctx.register_datasource("st", MemoryDataSource(schema, batches))
        t = ctx.sql_collect("SELECT k, MIN(s), MAX(s), COUNT(1) FROM st GROUP BY k")
        assert t.num_rows == len(np.unique(keys))
        got = {r[0]: r[1:] for r in t.to_rows()}
        for g in np.unique(keys):
            sel = sorted(words[keys == g])
            mn, mx, c = got[int(g)]
            assert mn == sel[0] and mx == sel[-1] and c == len(sel)

    def test_nullable_values_many_groups(self):
        # null handling (cnt slots diverge from row counts) on the
        # sort-merge path, plus integer sums
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        rng = np.random.default_rng(5)
        schema = Schema(
            [Field("k", DataType.INT64, False), Field("v", DataType.INT64, True)]
        )
        n, n_groups = 20_000, 300
        keys = rng.integers(0, n_groups, n)
        vals = rng.integers(-50, 50, n)
        valid = rng.random(n) < 0.7
        batches = [
            make_host_batch(
                schema,
                [keys[i : i + 4096], vals[i : i + 4096]],
                [None, valid[i : i + 4096]],
                [None, None],
            )
            for i in range(0, n, 4096)
        ]
        ctx = ExecutionContext(batch_size=4096)
        ctx.register_datasource("nt", MemoryDataSource(schema, batches))
        t = ctx.sql_collect(
            "SELECT k, SUM(v), COUNT(v), COUNT(1), MAX(v) FROM nt GROUP BY k"
        )
        got = {r[0]: r[1:] for r in t.to_rows()}
        for g in range(0, n_groups, 17):
            m = (keys == g) & valid
            s, cv, c1, mx = got[g]
            assert s == vals[m].sum() and cv == m.sum()
            assert c1 == (keys == g).sum() and mx == vals[m].max()


class TestIdentityPassthrough:
    """Bare-column projections bypass the device kernel: exact values
    (f64 is emulated on TPU — an identity round trip perturbs ~1e-14)
    and no transfer for untouched columns."""

    def test_filtered_select_passes_input_arrays(self):
        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.context import ExecutionContext
        from datafusion_tpu.exec.datasource import MemoryDataSource

        schema = Schema(
            [Field("a", DataType.FLOAT64, False), Field("b", DataType.INT64, False)]
        )
        a = np.array([43.21, 12.34, 0.5])
        b = np.array([1, -2, 3], dtype=np.int64)
        batch = make_host_batch(schema, [a, b], [None, None], [None, None])
        ctx = ExecutionContext(device="cpu")
        ctx.register_datasource("t", MemoryDataSource(schema, [batch]))

        out = next(ctx.sql("SELECT a, b, a * 2 FROM t WHERE b > 0").batches())
        # identity outputs ARE the input arrays — no kernel round trip
        assert out.data[0] is batch.data[0]
        assert out.data[1] is batch.data[1]
        t = ctx.sql_collect("SELECT a, b FROM t WHERE b > 0")
        assert t.column_values(0) == [43.21, 0.5]

    def test_pure_selection_no_device_work(self):
        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.context import ExecutionContext
        from datafusion_tpu.exec.datasource import MemoryDataSource

        schema = Schema([Field("a", DataType.FLOAT64, False)])
        batch = make_host_batch(schema, [np.array([1.5, 2.5])], [None], [None])
        ctx = ExecutionContext(device="cpu")
        ctx.register_datasource("t", MemoryDataSource(schema, [batch]))
        out = next(ctx.sql("SELECT a FROM t").batches())
        assert out.data[0] is batch.data[0]
        assert out.mask is None  # no kernel ran at all


class TestLiteralParameterization:
    """WHERE x > <literal> must compile ONE kernel for every literal
    value (SURVEY §7 recompilation control; kernels.parameterize_exprs)."""

    def _src(self):
        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        rng = np.random.default_rng(13)
        schema = Schema(
            [Field("x", DataType.FLOAT64, False), Field("k", DataType.INT64, False)]
        )
        batch = make_host_batch(
            schema,
            [rng.uniform(0, 100, 5000), rng.integers(0, 7, 5000)],
            [None, None],
            [None, None],
        )
        return schema, MemoryDataSource(schema, [batch])

    def test_pipeline_cache_stays_one_across_literals(self):
        import numpy as np

        from datafusion_tpu.exec import kernels
        from datafusion_tpu.exec.context import ExecutionContext

        schema, src = self._src()
        ctx = ExecutionContext(device="cpu")
        ctx.register_datasource("t", src)

        def n_pipeline_cores():
            return sum(1 for k in kernels._REGISTRY if k[0] == "pipeline")

        want = None
        base = None
        for i, lit in enumerate(np.linspace(10.0, 90.0, 10)):
            out = ctx.sql_collect(f"SELECT x, x * 2.0 FROM t WHERE x > {lit:.4f}")
            if i == 0:
                base = n_pipeline_cores()
                want = out  # sanity below
            # correctness per literal
            assert all(r[0] > lit for r in out.to_rows())
        assert n_pipeline_cores() == base, "literal value leaked into cache key"

    def test_aggregate_cache_stays_one_across_literals(self):
        from datafusion_tpu.exec import kernels
        from datafusion_tpu.exec.context import ExecutionContext

        schema, src = self._src()
        ctx = ExecutionContext(device="cpu")
        ctx.register_datasource("t", src)

        def n_agg_cores():
            return sum(1 for k in kernels._REGISTRY if k[0] == "aggregate")

        base = None
        import numpy as np

        for i, lit in enumerate(np.linspace(0.1, 0.9, 10)):
            out = ctx.sql_collect(
                f"SELECT k, SUM(x * {lit:.3f}), AVG(x * {lit:.3f}) FROM t "
                f"WHERE x > {10 + i} GROUP BY k"
            )
            if i == 0:
                base = n_agg_cores()
            assert out.num_rows == 7
        assert n_agg_cores() == base

    def test_distinct_value_patterns_do_not_share_a_core(self):
        # SUM(x*a), AVG(x*b) with a != b must NOT reuse the a == b core
        # (different accumulator dedup structure)
        from datafusion_tpu.exec.context import ExecutionContext

        schema, src = self._src()
        ctx = ExecutionContext(device="cpu")
        ctx.register_datasource("t", src)
        same = ctx.sql_collect("SELECT k, SUM(x * 0.5), AVG(x * 0.5) FROM t GROUP BY k")
        diff = ctx.sql_collect("SELECT k, SUM(x * 0.5), AVG(x * 0.25) FROM t GROUP BY k")
        import numpy as np

        for rs, rd in zip(sorted(same.to_rows()), sorted(diff.to_rows())):
            assert rs[0] == rd[0]
            np.testing.assert_allclose(rd[2], rs[2] / 2, rtol=1e-9)


class TestWireCompression:
    """H2D wire codecs must be exactly lossless (exec/batch.py)."""

    def test_roundtrip_exact(self):
        import jax.numpy as jnp
        import numpy as np

        from datafusion_tpu.exec.batch import _decode_wire, _encode_wire

        rng = np.random.default_rng(0)
        cases = [
            np.array([True, False] * 512),
            np.arange(1024, dtype=np.int64),                    # narrow
            (np.arange(1024) * 10**9).astype(np.int64),         # raw
            np.linspace(0, 50, 1024).round(0),                  # f32-exact
            np.round(rng.uniform(900, 105000, 1024), 2),        # raw f64
            rng.integers(0, 11, 1024) / 100.0,                  # dict
            np.concatenate([[1.5, np.nan, -0.0, np.inf], np.zeros(1020)]),
            np.arange(1024, dtype=np.uint64) + 2**63,           # raw u64
            np.array([-129, 127] * 512, dtype=np.int64),        # int16
        ]
        for a in cases:
            spec, wires = _encode_wire(a)
            dec = np.asarray(
                _decode_wire(spec, tuple(jnp.asarray(w) for w in wires))
            )
            assert dec.dtype == a.dtype
            assert np.array_equal(dec, a, equal_nan=(a.dtype.kind == "f"))
            assert sum(w.nbytes for w in wires) <= a.nbytes

    def test_device_inputs_roundtrip(self):
        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.batch import device_inputs, make_host_batch

        schema = Schema(
            [
                Field("i", DataType.INT64, True),
                Field("f", DataType.FLOAT64, False),
                Field("d", DataType.FLOAT64, False),
            ]
        )
        rng = np.random.default_rng(1)
        cols = [
            rng.integers(-100, 100, 2048).astype(np.int64),
            np.round(rng.uniform(900, 105000, 2048), 2),
            rng.integers(0, 9, 2048) / 100.0,
        ]
        valid = rng.random(2048) > 0.1
        batch = make_host_batch(schema, cols, [valid, None, None], [None] * 3)
        data, validity, _ = device_inputs(batch)
        for got, want in zip(data, batch.data):
            assert np.array_equal(np.asarray(got), want)
        assert np.array_equal(np.asarray(validity[0]), batch.validity[0])
        # second call hits the batch cache
        data2, _, _ = device_inputs(batch)
        assert data2[0] is data[0]

    def test_decimal_wire(self):
        # fixed-point f64 (prices with 2 decimals) travels as int32 +
        # static scale, halving the bytes of the biggest TPC-H column
        import jax.numpy as jnp
        import numpy as np

        from datafusion_tpu.exec.batch import _decode_wire, _encode_wire

        rng = np.random.default_rng(3)
        a = np.round(rng.uniform(900.0, 104950.0, 4096), 2)
        spec, wires = _encode_wire(a)
        assert spec == ("decimal", 100)
        assert wires[0].dtype == np.int32
        dec = np.asarray(_decode_wire(spec, tuple(jnp.asarray(w) for w in wires)))
        assert np.array_equal(dec.view(np.int64), a.view(np.int64))
        # 3 decimals
        b = np.round(rng.uniform(-1000.0, 1000.0, 4096), 3)
        spec_b, _ = _encode_wire(b)
        assert spec_b == ("decimal", 1000)
        # not fixed-point: falls through to raw
        c = rng.standard_normal(4096)
        spec_c, _ = _encode_wire(c)
        assert spec_c == ("raw",)

    def test_decimal_wire_rejects_overflow_and_negzero(self):
        # values >= 2^31/scale in rows the strided sample skips must NOT
        # silently wrap through int32; -0.0 has no int32 image at all
        import numpy as np

        from datafusion_tpu.exec.batch import _encode_wire

        a = np.round(np.linspace(900.0, 104950.0, 8192), 2)
        a[1] = 50_000_000.00  # odd index: stride-2 sample misses it
        spec, wires = _encode_wire(a)
        if spec[0] == "decimal":
            codes, scale = wires
            got = codes.astype(np.float64) / scale[0]
            assert np.array_equal(got, a)
        else:
            assert spec == ("raw",)

        b = np.round(np.linspace(-10.0, 10.0, 4096), 2)
        b[7] = -0.0
        spec_b, wires_b = _encode_wire(b)
        if spec_b[0] == "decimal":
            codes, scale = wires_b
            got = codes.astype(np.float64) / scale[0]
            assert np.array_equal(got.view(np.int64), b.view(np.int64))
        # dict codec legitimately captures -0.0 bit-exactly; decimal
        # would have lost the sign

    def test_dict_preferred_over_decimal(self):
        # low-cardinality fixed-point (l_discount shape) must take the
        # 1-byte dict wire, not the 4-byte decimal wire
        import numpy as np

        from datafusion_tpu.exec.batch import _encode_wire

        rng = np.random.default_rng(11)
        a = rng.integers(0, 11, 8192) / 100.0
        spec, wires = _encode_wire(a)
        assert spec == ("dict",)

    def test_staged_aux_not_consumed_cross_relation(self, monkeypatch):
        # two different queries over the same long-lived batches: the
        # second must not consume the first's staged aux entries
        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
        from datafusion_tpu.exec.context import ExecutionContext
        from datafusion_tpu.exec.datasource import MemoryDataSource

        schema = Schema([Field("s", DataType.UTF8, False),
                         Field("v", DataType.FLOAT64, False)])
        d = StringDictionary()
        rng = np.random.default_rng(2)
        strs = [f"k{i:03d}" for i in rng.integers(0, 40, 4096)]
        batch = make_host_batch(
            schema,
            [d.encode(strs), rng.uniform(0, 1, 4096)],
            [None, None],
            [d, None],
        )
        src = MemoryDataSource(schema, [batch])
        monkeypatch.setenv("DATAFUSION_TPU_PREFETCH", "1")
        ctx = ExecutionContext(device="cpu")
        ctx.register_datasource("t", src)
        r1 = ctx.sql_collect("SELECT s, SUM(v) FROM t WHERE s > 'k010' GROUP BY s")
        # a different aggregate over the same batches (different core,
        # different aux specs) — must recompute, not reuse r1's aux
        r2 = ctx.sql_collect("SELECT s, COUNT(1) FROM t WHERE s < 'k030' GROUP BY s")
        want = {}
        for s in strs:
            if s < "k030":
                want[s] = want.get(s, 0) + 1
        got = dict(r2.to_rows())
        assert got == want
        assert all(row[0] > "k010" for row in r1.to_rows())

    def test_blob_vs_per_wire_parity(self, monkeypatch):
        # the single-buffer wire format must decode identically to
        # per-wire device_put (DATAFUSION_TPU_H2D_BLOB=0)
        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.batch import device_inputs, make_host_batch

        schema = Schema(
            [
                Field("i", DataType.INT64, True),
                Field("p", DataType.FLOAT64, False),
                Field("d", DataType.FLOAT64, False),
                Field("r", DataType.FLOAT64, False),
            ]
        )
        rng = np.random.default_rng(7)
        cols = [
            rng.integers(-100, 100, 2048).astype(np.int64),
            np.round(rng.uniform(900, 105000, 2048), 2),
            rng.integers(0, 9, 2048) / 100.0,
            rng.standard_normal(2048),
        ]
        valid = rng.random(2048) > 0.5

        def build():
            return make_host_batch(schema, cols, [valid, None, None, None], [None] * 4)

        monkeypatch.setenv("DATAFUSION_TPU_H2D_BLOB", "1")
        blob_data, blob_valid, _ = device_inputs(build())
        monkeypatch.setenv("DATAFUSION_TPU_H2D_BLOB", "0")
        per_data, per_valid, _ = device_inputs(build())
        for g, w in zip(blob_data, per_data):
            assert np.array_equal(
                np.asarray(g).view(np.int64), np.asarray(w).view(np.int64)
            )
        assert np.array_equal(np.asarray(blob_valid[0]), np.asarray(per_valid[0]))

    def test_packed_mask_pull(self):
        import jax.numpy as jnp
        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.batch import RecordBatch
        from datafusion_tpu.exec.materialize import _fetch_mask, _start_mask_pull

        rng = np.random.default_rng(9)
        mask = rng.random(1024) > 0.4
        schema = Schema([Field("x", DataType.INT64, False)])
        b = RecordBatch(
            schema,
            [jnp.arange(1024, dtype=jnp.int64)],
            [None],
            [None],
            num_rows=1000,
            mask=jnp.asarray(mask),
        )
        _start_mask_pull(b)
        assert "packed_mask" in b.cache
        got = _fetch_mask(b)
        assert np.array_equal(got, mask)

    def test_dict_wire_is_bit_exact(self):
        # -0.0 and NaN payloads survive the dictionary encoding
        # bit-for-bit (np.unique on float VALUES would collapse them)
        import jax.numpy as jnp
        import numpy as np

        from datafusion_tpu.exec.batch import _decode_wire, _encode_wire

        a = np.tile(np.array([0.01, 0.07, -0.0, np.nan, 104949.99, -0.03]), 256)
        spec, wires = _encode_wire(a)
        assert spec == ("dict",)
        dec = np.asarray(_decode_wire(spec, tuple(jnp.asarray(w) for w in wires)))
        assert np.array_equal(dec.view(np.int64), a.view(np.int64))
        # the values table is fixed-size: one decoder shape per capacity
        assert wires[1].shape == (256,)


class TestHostRouting:
    """Host-routed scalar projections / predicates (relation._host_routed):
    active only on accelerator devices, so the CPU suite forces the mode
    via monkeypatched `_is_accelerator` and asserts exact agreement with
    the device-kernel path on the same queries."""

    @pytest.fixture
    def host_mode(self, monkeypatch):
        import datafusion_tpu.exec.kernels as kernels
        import datafusion_tpu.exec.relation as relation

        monkeypatch.setattr(relation, "_is_accelerator", lambda device: True)
        # host-routing changes kernel cache keys; isolate so other tests
        # never see cores built in forced-host mode
        saved = dict(kernels._REGISTRY)
        kernels._REGISTRY.clear()
        yield
        kernels._REGISTRY.clear()
        kernels._REGISTRY.update(saved)

    def _both(self, make_ctx, sql):
        from datafusion_tpu.exec.materialize import collect

        return sorted(collect(make_ctx().sql(sql)).to_rows())

    def test_scalar_projection_matches_device(self, ctx, host_mode, test_data_dir):
        from datafusion_tpu.exec.materialize import collect

        sql = (
            "SELECT city, lat, lng, lat + lng, lat * 2 - lng "
            "FROM cities WHERE lat > 51.0 AND lat < 53.0"
        )
        got = sorted(collect(ctx.sql(sql)).to_rows())
        assert len(got) == 18
        for row in got:
            assert row[3] == row[1] + row[2]
            assert row[4] == row[1] * 2 - row[2]

    def test_int_division_modulus_parity(self, ctx, host_mode):
        # C-style truncation on negatives: host eval must match the
        # device kernel's lax.div/lax.rem semantics
        from datafusion_tpu.exec.materialize import collect

        rows = sorted(
            collect(
                ctx.sql("SELECT a, b, a / b, a % b FROM numerics WHERE b <> 0")
            ).to_rows()
        )
        for a, b, q, r in rows:
            # C-style truncation: round the true quotient toward zero
            want_q = -(-a // b) if (a < 0) != (b < 0) and a % b != 0 else a // b
            assert q == want_q, (a, b, q)
            assert r == a - want_q * b, (a, b, r)

    def test_string_predicate_aggregate(self, ctx, host_mode):
        # Utf8-vs-literal predicate host-routes through the dictionary
        # compare table on the aggregate path
        from datafusion_tpu.exec.materialize import collect

        got = collect(
            ctx.sql(
                "SELECT COUNT(1), MIN(city), MAX(lat) FROM cities "
                "WHERE city > 'M'"
            )
        ).to_rows()
        rows = collect(ctx.sql("SELECT city, lat FROM cities")).to_rows()
        want = [r for r in rows if r[0] > "M"]
        assert got[0][0] == len(want)
        assert got[0][1] == min(r[0] for r in want)
        assert got[0][2] == max(r[1] for r in want)

    def test_nullable_predicate_and_projection(self, ctx, host_mode):
        from datafusion_tpu.exec.materialize import collect

        got = collect(
            ctx.sql(
                "SELECT c_int, c_int + 1, c_float / 2 FROM null_test "
                "WHERE c_int IS NOT NULL"
            )
        ).to_rows()
        assert all(r[0] is not None for r in got)
        for r in got:
            assert r[1] == r[0] + 1

    def test_three_valued_logic_or_and(self, ctx, host_mode):
        # TRUE OR NULL = TRUE / FALSE AND NULL = FALSE: a null operand
        # must not poison a determined result (device bool_fn parity)
        from datafusion_tpu.exec.materialize import collect

        raw = collect(ctx.sql("SELECT c_int, c_float FROM null_test")).to_rows()

        got = collect(
            ctx.sql("SELECT COUNT(1) FROM null_test WHERE c_int > 0 OR c_float > 0")
        ).to_rows()[0][0]
        want = sum(
            1 for ci, cf in raw
            if (ci is not None and ci > 0) or (cf is not None and cf > 0)
        )
        assert got == want

        got = collect(
            ctx.sql(
                "SELECT COUNT(1) FROM null_test WHERE c_int > 0 AND c_float > 0"
            )
        ).to_rows()[0][0]
        want = sum(
            1 for ci, cf in raw
            if ci is not None and ci > 0 and cf is not None and cf > 0
        )
        assert got == want

    def test_literal_variants_share_compiled_core(self, ctx, host_mode):
        # host-routed predicates/projections must not fork the device
        # kernel per literal value (SURVEY §7 recompilation control)
        r1 = ctx.sql("SELECT lat + 1.0 FROM cities WHERE lat > 51.0")
        r2 = ctx.sql("SELECT lat + 2.0 FROM cities WHERE lat > 52.0")
        assert r1.core is r2.core
        a1 = ctx.sql("SELECT COUNT(1), SUM(lat) FROM cities WHERE city > 'A'")
        a2 = ctx.sql("SELECT COUNT(1), SUM(lat) FROM cities WHERE city > 'Q'")
        assert a1.core is a2.core
        from datafusion_tpu.exec.materialize import collect

        # and each relation still applies ITS OWN literals
        c1 = collect(a1).to_rows()[0][0]
        c2 = collect(a2).to_rows()[0][0]
        assert c1 > c2 > 0

    def test_bare_string_literal_matches_device_error(self, ctx, host_mode):
        from datafusion_tpu.errors import NotSupportedError

        with pytest.raises(NotSupportedError):
            ctx.sql_collect("SELECT city, 'x' FROM cities")


class TestHostRoutedPredicate:
    """On accelerators, numpy-evaluable predicates run on the host
    (relation.PipelineRelation._host_pred_expr): the predicate's input
    columns never cross H2D, and together with host-routed projections
    the batch usually never touches the device at all."""

    def test_filter_never_builds_device_kernel(self, ctx, test_data_dir, monkeypatch):
        import datafusion_tpu.exec.kernels as kernels
        import datafusion_tpu.exec.relation as relation
        from datafusion_tpu.exec.materialize import collect
        from datafusion_tpu.exec.relation import PipelineRelation

        monkeypatch.setattr(relation, "_is_accelerator", lambda device: True)
        saved = dict(kernels._REGISTRY)
        kernels._REGISTRY.clear()
        try:
            rel = ctx.sql(
                "SELECT city, lat, lng, lat + lng FROM cities "
                "WHERE lat > 51.0 AND lat < 53.0"
            )
            node = rel
            pipe = None
            while node is not None:
                if isinstance(node, PipelineRelation):
                    pipe = node
                    break
                node = getattr(node, "child", None)
            assert pipe is not None
            assert pipe._host_pred_expr is not None
            assert not pipe.core.needs_kernel  # scalar projections host-route too
            got = sorted(collect(rel).to_rows())
        finally:
            kernels._REGISTRY.clear()
            kernels._REGISTRY.update(saved)
        want = sorted(
            collect(
                ctx.sql(
                    "SELECT city, lat, lng, lat + lng FROM cities "
                    "WHERE lat > 51.0 AND lat < 53.0"
                )
            ).to_rows()
        )
        assert got == want
        assert len(got) == 18

    def test_distinct_literals_share_core_not_results(self, ctx, monkeypatch):
        # the host predicate carries per-query literals; the shared
        # compiled core must not leak one query's mask into another's
        import datafusion_tpu.exec.kernels as kernels
        import datafusion_tpu.exec.relation as relation
        from datafusion_tpu.exec.materialize import collect

        monkeypatch.setattr(relation, "_is_accelerator", lambda device: True)
        saved = dict(kernels._REGISTRY)
        kernels._REGISTRY.clear()
        try:
            a = collect(ctx.sql("SELECT city FROM cities WHERE lat > 52.0"))
            b = collect(ctx.sql("SELECT city FROM cities WHERE lat > 54.0"))
        finally:
            kernels._REGISTRY.clear()
            kernels._REGISTRY.update(saved)
        assert a.num_rows > b.num_rows > 0


class TestWirePolicy:
    """put_compressed skips the codec entirely when the transfer target
    is the host platform (no link to compress for); DATAFUSION_TPU_WIRE
    forces either mode."""

    def _batch(self):
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.datatypes import DataType, Field, Schema

        rng = np.random.default_rng(11)
        schema = Schema(
            [
                Field("p", DataType.FLOAT64, False),
                Field("q", DataType.FLOAT64, False),
                Field("i", DataType.INT64, True),
            ]
        )
        cols = [
            np.round(rng.uniform(900, 105000, 2048), 2),
            rng.integers(0, 11, 2048) / 100.0,
            rng.integers(-100, 100, 2048).astype(np.int64),
        ]
        valid = rng.random(2048) > 0.2
        return make_host_batch(schema, cols, [None, None, valid], [None] * 3)

    def test_host_target_skips_wire(self, monkeypatch):
        from datafusion_tpu.exec import batch as B

        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "auto")
        calls = []
        orig = B._encode_wire
        monkeypatch.setattr(
            B, "_encode_wire", lambda a, d=None: calls.append(1) or orig(a, d)
        )
        b = self._batch()
        data, validity, _ = B.device_inputs(b, None)
        assert not calls  # CPU target: no codec probing at all
        for got, want in zip(data, b.data):
            assert np.array_equal(np.asarray(got), want)
        assert np.array_equal(np.asarray(validity[2]), b.validity[2])

    def test_forced_wire_matches_raw(self, monkeypatch):
        from datafusion_tpu.exec import batch as B

        b1 = self._batch()
        b2 = self._batch()
        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        d_wire, v_wire, _ = B.device_inputs(b1, None)
        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "never")
        d_raw, v_raw, _ = B.device_inputs(b2, None)
        for a, c in zip(d_wire, d_raw):
            ha, hc = np.asarray(a), np.asarray(c)
            assert ha.dtype == hc.dtype
            assert np.array_equal(ha, hc)
        assert np.array_equal(np.asarray(v_wire[2]), np.asarray(v_raw[2]))

    def test_wire_hints_skip_probe_and_stay_exact(self, monkeypatch):
        from datafusion_tpu.exec import batch as B

        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        rng = np.random.default_rng(5)
        col1 = np.round(rng.uniform(900, 105000, 2048), 2)   # decimal 100
        col2 = rng.integers(0, 11, 2048) / 100.0             # dict
        hints: dict = {}
        out1 = B.put_compressed([col1, col2], None, hints)
        assert set(hints) == {0, 1}
        assert hints[0][0] == "decimal" and hints[1][0] == "dict"
        # second batch of the same columns: the hint path must produce
        # bit-identical decodes
        col1b = np.round(rng.uniform(900, 105000, 2048), 2)
        col2b = rng.integers(0, 11, 2048) / 100.0
        full = []
        orig = B._encode_wire
        monkeypatch.setattr(
            B, "_encode_wire", lambda a, d=None: full.append(1) or orig(a, d)
        )
        out2 = B.put_compressed([col1b, col2b], None, hints)
        assert not full  # both columns rode their hints
        assert np.array_equal(np.asarray(out2[0]).view(np.int64), col1b.view(np.int64))
        assert np.array_equal(np.asarray(out2[1]).view(np.int64), col2b.view(np.int64))
        assert np.array_equal(np.asarray(out1[0]).view(np.int64), col1.view(np.int64))

    def test_wire_hint_miss_falls_back(self, monkeypatch):
        from datafusion_tpu.exec import batch as B

        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        rng = np.random.default_rng(6)
        col = np.round(rng.uniform(0, 100, 2048), 2)  # decimal 100
        hints: dict = {}
        B.put_compressed([col], None, hints)
        assert hints[0][0] == "decimal"
        # next batch breaks the fixed-point assumption: full probe rules
        wild = rng.standard_normal(2048)
        out = B.put_compressed([wild], None, hints)
        assert np.array_equal(
            np.asarray(out[0]).view(np.int64), wild.view(np.int64)
        )

    def test_blob_pull_roundtrip_forced(self, monkeypatch):
        # DATAFUSION_TPU_WIRE=always keeps the blob-packed D2H path live
        # on CPU (device_pull_start's host-platform skip is bypassed)
        import jax.numpy as jnp

        from datafusion_tpu.exec import batch as B

        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        rng = np.random.default_rng(9)
        tree = (
            jnp.asarray(rng.integers(-(2**62), 2**62, 1024)),
            (
                jnp.asarray(rng.standard_normal(1024)),
                jnp.asarray(rng.random(1024) > 0.5),
            ),
            jnp.asarray(rng.integers(0, 255, 1024).astype(np.uint8)),
        )
        pull = B.device_pull_start(tree)
        assert pull._blob is not None  # the packed path, not direct pulls
        out = pull.finish()
        leaves_in = [tree[0], tree[1][0], tree[1][1], tree[2]]
        leaves_out = [out[0], out[1][0], out[1][1], out[2]]
        for want, got in zip(leaves_in, leaves_out):
            w = np.asarray(want)
            assert got.dtype == w.dtype
            assert np.array_equal(got, w, equal_nan=(w.dtype.kind == "f"))


class TestAdaptivePlacement:
    """Link-aware aggregate slot placement (aggregate._decide_placement):
    on a slow measured link, float SUM/AVG/COUNT partials compute on the
    host via bincount instead of shipping their columns.  Forced on CPU
    via DATAFUSION_TPU_WIRE=always + a pinned DATAFUSION_TPU_LINK_MBPS."""

    def _rows(self, ctx, sql):
        from datafusion_tpu.exec.materialize import collect

        return sorted(collect(ctx.sql(sql)).to_rows())

    def _assert_same(self, a, b):
        assert len(a) == len(b)
        for ra, rb in zip(a, b):
            for va, vb in zip(ra, rb):
                if isinstance(va, float):
                    np.testing.assert_allclose(va, vb, rtol=1e-12)
                else:
                    assert va == vb

    @pytest.fixture
    def slow_link(self, monkeypatch):
        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        monkeypatch.setenv("DATAFUSION_TPU_LINK_MBPS", "0.001")

    @pytest.fixture
    def fast_link(self, monkeypatch):
        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        monkeypatch.setenv("DATAFUSION_TPU_LINK_MBPS", "1e9")

    def test_full_host_split_matches_device(self, ctx, slow_link):
        from datafusion_tpu.exec.aggregate import AggregateRelation
        from datafusion_tpu.utils.metrics import METRICS

        sql = (
            "SELECT city, SUM(lat), AVG(lng), COUNT(1) FROM cities "
            "WHERE lat > 51.0 GROUP BY city"
        )
        rel = ctx.sql(sql)
        node = rel
        while node is not None and not isinstance(node, AggregateRelation):
            node = getattr(node, "child", None)
        assert node is not None
        from datafusion_tpu.exec.materialize import collect

        METRICS.reset()
        got = sorted(collect(rel).to_rows())
        # every slot went host: the reduced device core is gone entirely
        assert node._placement and node._placement.core is None
        assert METRICS.snapshot()["counts"].get("aggregate.host_routed_slots")
        ctx2_rows = self._rows(self._fresh_ctx(ctx), sql)
        self._assert_same(got, ctx2_rows)

    def _fresh_ctx(self, ctx):
        # same tables, default (no-split) placement: the comparison run
        from datafusion_tpu import ExecutionContext
        import os as _os

        _os.environ["DATAFUSION_TPU_LINK_MBPS"] = "1e9"
        c = ExecutionContext(batch_size=1024)
        c.datasources = dict(ctx.datasources)
        return c

    def test_mixed_split_keeps_minmax_on_device(self, ctx, slow_link):
        from datafusion_tpu.exec.aggregate import AggregateRelation

        sql = (
            "SELECT SUM(lng), AVG(lng), COUNT(1), MIN(lat), MAX(city) "
            "FROM cities WHERE lat > 51.0"
        )
        rel = ctx.sql(sql)
        node = rel
        while node is not None and not isinstance(node, AggregateRelation):
            node = getattr(node, "child", None)
        from datafusion_tpu.exec.materialize import collect

        got = sorted(collect(rel).to_rows())
        assert node._placement
        assert node._placement.core is not None  # MIN/MAX stayed device
        assert len(node._placement.core.specs) == 3  # count(*), min, max
        self._assert_same(got, self._rows(self._fresh_ctx(ctx), sql))

    def test_fast_link_never_splits(self, ctx, fast_link):
        from datafusion_tpu.exec.aggregate import AggregateRelation

        sql = "SELECT city, SUM(lat) FROM cities GROUP BY city"
        rel = ctx.sql(sql)
        node = rel
        while node is not None and not isinstance(node, AggregateRelation):
            node = getattr(node, "child", None)
        from datafusion_tpu.exec.materialize import collect

        sorted(collect(rel).to_rows())
        assert node._placement is False  # decided: no split

    def test_nulls_through_host_partials(self, ctx, slow_link):
        sql = (
            "SELECT COUNT(1), COUNT(c_float), SUM(c_float), AVG(c_float) "
            "FROM null_test"
        )
        got = self._rows(ctx, sql)
        want = self._rows(self._fresh_ctx(ctx), sql)
        self._assert_same(got, want)

    def test_memory_source_always_ships(self, monkeypatch, slow_link):
        from datafusion_tpu import DataType, ExecutionContext, Field, Schema
        from datafusion_tpu.exec.aggregate import AggregateRelation
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        schema = Schema([Field("k", DataType.INT64, False),
                         Field("v", DataType.FLOAT64, False)])
        rng = np.random.default_rng(2)
        b = make_host_batch(
            schema,
            [rng.integers(0, 4, 2048), np.round(rng.uniform(0, 9, 2048), 2)],
            [None, None], [None, None],
        )
        c = ExecutionContext(batch_size=2048)
        c.register_datasource("t", MemoryDataSource(schema, [b]))
        rel = c.sql("SELECT k, SUM(v) FROM t GROUP BY k")
        node = rel
        while node is not None and not isinstance(node, AggregateRelation):
            node = getattr(node, "child", None)
        from datafusion_tpu.exec.materialize import collect

        sorted(collect(rel).to_rows())
        assert node._placement is False  # reusable source: always device

    def test_count_utf8_column_host(self, ctx, slow_link):
        from datafusion_tpu.exec.aggregate import AggregateRelation
        from datafusion_tpu.exec.materialize import collect

        sql = "SELECT COUNT(c_string), SUM(c_float) FROM null_test"
        rel = ctx.sql(sql)
        node = rel
        while not isinstance(node, AggregateRelation):
            node = node.child
        got = sorted(collect(rel).to_rows())
        assert node._placement and node._placement.core is None
        want = self._rows(self._fresh_ctx(ctx), sql)
        self._assert_same(got, want)


def test_package_version_in_sync():
    """pyproject.toml's version must match datafusion_tpu.__version__
    (two declarations where the reference's Cargo.toml has one)."""
    tomllib = pytest.importorskip("tomllib")  # stdlib only on Python 3.11+

    import datafusion_tpu

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "pyproject.toml"), "rb") as fh:
        meta = tomllib.load(fh)
    assert meta["project"]["version"] == datafusion_tpu.__version__
    scripts = meta["project"]["scripts"]
    assert scripts["datafusion-tpu"] == "datafusion_tpu.cli:main"
    assert scripts["datafusion-tpu-worker"] == "datafusion_tpu.parallel.worker:main"


class TestHostPartialsGrowth:
    """Host accumulators must grow as later batches introduce new
    groups (aggregate._HostPartials._grown)."""

    def test_group_growth_across_batches_host_partials(self, monkeypatch):
        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        monkeypatch.setenv("DATAFUSION_TPU_LINK_MBPS", "0.001")
        # groups appearing only in later batches: host accumulators grow
        from datafusion_tpu import DataType, ExecutionContext, Field, Schema
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource
        from datafusion_tpu.exec.materialize import collect

        schema = Schema([Field("k", DataType.INT64, False),
                         Field("v", DataType.FLOAT64, False)])
        rng = np.random.default_rng(8)

        class StreamSource(MemoryDataSource):
            reusable_batches = False  # force the placement decision

        batches = []
        for lo in (0, 40, 90):  # later batches introduce new keys
            k = rng.integers(lo, lo + 50, 4096)
            v = np.round(rng.uniform(-10, 10, 4096), 2)
            batches.append(make_host_batch(schema, [k, v], [None, None], [None, None]))
        from datafusion_tpu.exec.aggregate import AggregateRelation

        src = StreamSource(schema, batches)
        c = ExecutionContext(batch_size=4096)
        c.register_datasource("t", src)
        sql = "SELECT k, SUM(v), AVG(v), COUNT(1) FROM t GROUP BY k"
        rel = c.sql(sql)
        node = rel
        while not isinstance(node, AggregateRelation):
            node = node.child
        got = sorted(collect(rel).to_rows())
        # the point of this test is the HOST path's accumulator growth:
        # fail loudly if placement ever stops routing this shape there
        assert node._placement and node._placement.core is None
        c2 = ExecutionContext(batch_size=4096)
        c2.register_datasource("t", StreamSource(schema, batches))
        monkeypatch.setenv("DATAFUSION_TPU_LINK_MBPS", "1e9")
        want = sorted(collect(c2.sql(sql)).to_rows())
        assert len(got) == len(want)
        for ra, rb in zip(got, want):
            for va, vb in zip(ra, rb):
                if isinstance(va, float):
                    np.testing.assert_allclose(va, vb, rtol=1e-12)
                else:
                    assert va == vb

    def test_having_order_limit_over_placed_aggregate(self, monkeypatch):
        # the aggregate's output batch feeds HAVING/ORDER BY/LIMIT
        # downstream; the host-split result must be indistinguishable
        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        from datafusion_tpu import DataType, ExecutionContext, Field, Schema
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource
        from datafusion_tpu.exec.materialize import collect

        schema = Schema([Field("k", DataType.INT64, False),
                         Field("v", DataType.FLOAT64, True)])
        rng = np.random.default_rng(12)

        class StreamSource(MemoryDataSource):
            reusable_batches = False

        k = rng.integers(0, 30, 8192)
        v = np.round(rng.uniform(-5, 5, 8192), 2)
        valid = rng.random(8192) > 0.15
        batches = [make_host_batch(schema, [k[i:i+2048], v[i:i+2048]],
                                   [None, valid[i:i+2048]], [None, None])
                   for i in range(0, 8192, 2048)]
        # predicate on the GROUP KEY: v stays exclusive to the host slots
        # (a predicate on v would force v to ship and disable the split)
        sql = ("SELECT k, SUM(v), COUNT(v) FROM t WHERE k < 25 GROUP BY k "
               "HAVING COUNT(v) > 100 ORDER BY k LIMIT 10")
        from datafusion_tpu.utils.metrics import METRICS

        outs = {}
        for mode, mbps in (("host", "0.001"), ("device", "1e9")):
            monkeypatch.setenv("DATAFUSION_TPU_LINK_MBPS", mbps)
            METRICS.reset()
            c = ExecutionContext(batch_size=2048)
            c.register_datasource("t", StreamSource(schema, batches))
            outs[mode] = collect(c.sql(sql)).to_rows()
            routed = METRICS.snapshot()["counts"].get("aggregate.host_routed_slots")
            assert bool(routed) == (mode == "host")
        assert len(outs["host"]) == len(outs["device"]) > 0
        for ra, rb in zip(outs["host"], outs["device"]):
            assert ra[0] == rb[0] and ra[2] == rb[2]
            np.testing.assert_allclose(ra[1], rb[1], rtol=1e-12)

    def test_null_group_keys_host_partials(self, monkeypatch):
        # NULL keys form their own group; host bincount must agree
        monkeypatch.setenv("DATAFUSION_TPU_WIRE", "always")
        from datafusion_tpu import DataType, ExecutionContext, Field, Schema
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource
        from datafusion_tpu.exec.materialize import collect

        schema = Schema([Field("k", DataType.INT64, True),
                         Field("v", DataType.FLOAT64, False)])
        rng = np.random.default_rng(13)

        class StreamSource(MemoryDataSource):
            reusable_batches = False

        k = rng.integers(0, 5, 4096)
        kvalid = rng.random(4096) > 0.2
        v = np.round(rng.uniform(0, 10, 4096), 2)
        batches = [make_host_batch(schema, [k[i:i+1024], v[i:i+1024]],
                                   [kvalid[i:i+1024], None], [None, None])
                   for i in range(0, 4096, 1024)]
        sql = "SELECT k, SUM(v), AVG(v), COUNT(1) FROM t GROUP BY k"
        from datafusion_tpu.utils.metrics import METRICS

        outs = {}
        for mode, mbps in (("host", "0.001"), ("device", "1e9")):
            monkeypatch.setenv("DATAFUSION_TPU_LINK_MBPS", mbps)
            METRICS.reset()
            c = ExecutionContext(batch_size=1024)
            c.register_datasource("t", StreamSource(schema, batches))
            key = lambda r: tuple((x is None, 0 if x is None else x) for x in r)
            outs[mode] = sorted(collect(c.sql(sql)).to_rows(), key=key)
            routed = METRICS.snapshot()["counts"].get("aggregate.host_routed_slots")
            assert bool(routed) == (mode == "host")
        assert len(outs["host"]) == 6  # 5 keys + the NULL group
        for ra, rb in zip(outs["host"], outs["device"]):
            assert ra[0] == rb[0] and ra[3] == rb[3]
            np.testing.assert_allclose(ra[1], rb[1], rtol=1e-12)
            np.testing.assert_allclose(ra[2], rb[2], rtol=1e-12)
