"""Partitioned execution over an 8-device CPU-simulated mesh.

The hermetic analog of the reference's planned docker-compose
multi-worker smoketest (`scripts/smoketest.sh:30-66`): conftest forces
8 virtual CPU devices, so partial-aggregate + psum/pmin/pmax combine
runs over a real (simulated) mesh without TPUs.
"""

import numpy as np
import pytest

from datafusion_tpu import DataType, Field, Schema
from datafusion_tpu.parallel import (
    PartitionedContext,
    PartitionedDataSource,
    PhysicalPlan,
    PlanFragment,
    make_mesh,
)
from datafusion_tpu.exec.context import ExecutionContext


SCHEMA = Schema(
    [
        Field("region", DataType.UTF8, False),
        Field("qty", DataType.INT64, True),
        Field("price", DataType.FLOAT64, False),
    ]
)

REGIONS = ["north", "south", "east", "west", "centre"]


def _write_partitions(tmp_path, n_parts=5, rows_per_part=200, seed=7):
    rng = np.random.default_rng(seed)
    paths, all_rows = [], []
    for p in range(n_parts):
        path = tmp_path / f"part{p}.csv"
        lines = ["region,qty,price"]
        for _i in range(rows_per_part):
            region = REGIONS[rng.integers(len(REGIONS))]
            qty = "" if rng.random() < 0.05 else str(int(rng.integers(-50, 500)))
            price = f"{rng.random() * 100:.4f}"
            lines.append(f"{region},{qty},{price}")
            all_rows.append((region, None if qty == "" else int(qty), float(price)))
        path.write_text("\n".join(lines) + "\n")
        paths.append(str(path))
    return paths, all_rows


@pytest.fixture(scope="module")
def parts(tmp_path_factory):
    return _write_partitions(tmp_path_factory.mktemp("parts"))


def _partitioned_ctx(paths, n_devices=8):
    ctx = PartitionedContext(mesh=make_mesh(n_devices), batch_size=64)
    ctx.register_partitioned_csv("sales", paths, SCHEMA)
    return ctx


def _single_ctx(paths):
    # reference single-device answer: same files via union scan
    ctx = ExecutionContext(batch_size=64)
    from datafusion_tpu.exec.datasource import CsvDataSource

    ctx.register_datasource(
        "sales", PartitionedDataSource([CsvDataSource(p, SCHEMA, True, 64) for p in paths])
    )
    return ctx


SQL_GROUPED = (
    "SELECT region, SUM(qty), COUNT(qty), MIN(price), MAX(price), AVG(price) "
    "FROM sales GROUP BY region"
)


def _as_dict(table, key_cols=1):
    rows = table.to_rows()
    return {r[:key_cols]: r[key_cols:] for r in rows}


class TestPartitionedAggregate:
    def test_grouped_matches_single_device(self, parts):
        paths, _ = parts
        got = _as_dict(_partitioned_ctx(paths).sql_collect(SQL_GROUPED))
        want = _as_dict(_single_ctx(paths).sql_collect(SQL_GROUPED))
        assert set(got) == set(want)
        for k in want:
            np.testing.assert_allclose(
                np.asarray(got[k], dtype=float), np.asarray(want[k], dtype=float),
                rtol=1e-9,
            )

    def test_global_aggregate(self, parts):
        paths, rows = parts
        table = _partitioned_ctx(paths).sql_collect(
            "SELECT SUM(price), COUNT(price), MIN(qty), MAX(qty) FROM sales"
        )
        (s, c, mn, mx), = table.to_rows()
        prices = [r[2] for r in rows]
        qtys = [r[1] for r in rows if r[1] is not None]
        assert c == len(prices)
        np.testing.assert_allclose(s, sum(prices), rtol=1e-9)
        assert mn == min(qtys) and mx == max(qtys)

    def test_where_fused_into_partials(self, parts):
        paths, _ = parts
        sql = "SELECT region, COUNT(price), SUM(price) FROM sales WHERE qty > 100 GROUP BY region"
        got = _as_dict(_partitioned_ctx(paths).sql_collect(sql))
        want = _as_dict(_single_ctx(paths).sql_collect(sql))
        assert set(got) == set(want)
        for k in want:
            np.testing.assert_allclose(
                np.asarray(got[k], dtype=float), np.asarray(want[k], dtype=float),
                rtol=1e-9,
            )

    def test_string_predicate_shared_dictionaries(self, parts):
        paths, rows = parts
        table = _partitioned_ctx(paths).sql_collect(
            "SELECT COUNT(price) FROM sales WHERE region = 'north'"
        )
        ((n,),) = (table.to_rows(),)
        assert n[0] == sum(1 for r in rows if r[0] == "north")

    def test_fewer_devices_than_partitions(self, parts):
        paths, _ = parts
        got = _as_dict(_partitioned_ctx(paths, n_devices=2).sql_collect(SQL_GROUPED))
        want = _as_dict(_single_ctx(paths).sql_collect(SQL_GROUPED))
        for k in want:
            np.testing.assert_allclose(
                np.asarray(got[k], dtype=float), np.asarray(want[k], dtype=float),
                rtol=1e-9,
            )

    def test_more_devices_than_partitions(self, parts):
        paths, _ = parts
        ctx = PartitionedContext(mesh=make_mesh(8), batch_size=64)
        ctx.register_partitioned_csv("sales", paths[:3], SCHEMA)
        want_ctx = _single_ctx(paths[:3])
        got = _as_dict(ctx.sql_collect(SQL_GROUPED))
        want = _as_dict(want_ctx.sql_collect(SQL_GROUPED))
        for k in want:
            np.testing.assert_allclose(
                np.asarray(got[k], dtype=float), np.asarray(want[k], dtype=float),
                rtol=1e-9,
            )

    def test_fragments_round_trip_wire_format(self, parts):
        paths, _ = parts
        ctx = _partitioned_ctx(paths)
        ctx.sql_collect(SQL_GROUPED)
        frags = ctx.last_fragments
        assert len(frags) == len(paths)
        for i, f in enumerate(frags):
            assert f.shard == i and f.num_shards == len(paths)
            rt = PlanFragment.from_json_str(f.to_json_str())
            assert rt.plan == f.plan
            # the shipped plan parses back into a real LogicalPlan
            assert rt.logical_plan().schema.names() == f.logical_plan().schema.names()


class TestPartitionedPipeline:
    """Non-aggregate plans (filter / project) run the stacked shard_map
    kernel across the mesh instead of the round-2 serial union scan."""

    def test_filter_project_matches_single_device(self, parts):
        from datafusion_tpu.utils.metrics import METRICS

        paths, rows = parts
        sql = (
            "SELECT region, price * 2.0, qty FROM sales "
            "WHERE price > 30.0 AND qty > 100"
        )
        METRICS.reset()
        table = _partitioned_ctx(paths).sql_collect(sql)
        snap = METRICS.snapshot()
        assert snap["timings_s"].get("execute.partitioned_pipeline", 0) > 0, (
            "partitioned filter/project did not take the mesh path"
        )
        single = _single_ctx(paths).sql_collect(sql)
        assert sorted(table.to_rows()) == sorted(single.to_rows())
        want = [
            (r[0], r[2] * 2.0, r[1])
            for r in rows
            if r[2] > 30.0 and r[1] is not None and r[1] > 100
        ]
        assert len(table.to_rows()) == len(want)

    def test_filter_only_parity(self, parts):
        paths, rows = parts
        sql = "SELECT region, qty, price FROM sales WHERE qty > 250"
        table = _partitioned_ctx(paths).sql_collect(sql)
        want = [r for r in rows if r[1] is not None and r[1] > 250]
        assert sorted(table.to_rows()) == sorted(want)

    def test_string_predicate_over_mesh(self, parts):
        paths, rows = parts
        sql = "SELECT region, price FROM sales WHERE region = 'north'"
        table = _partitioned_ctx(paths).sql_collect(sql)
        want = [(r[0], r[2]) for r in rows if r[0] == "north"]
        assert sorted(table.to_rows()) == sorted(want)

    def test_four_partitions_on_eight_devices(self, tmp_path):
        paths, rows = _write_partitions(tmp_path, n_parts=4, rows_per_part=333)
        sql = "SELECT price, qty FROM sales WHERE price < 20.0"
        table = _partitioned_ctx(paths).sql_collect(sql)
        want = [(r[2], r[1]) for r in rows if r[2] < 20.0]
        assert sorted(table.to_rows(), key=repr) == sorted(want, key=repr)

    def test_host_fn_projection_falls_back_to_serial(self, parts):
        from datafusion_tpu.utils.metrics import METRICS

        paths, rows = parts
        ctx = _partitioned_ctx(paths)
        ctx.register_udf(
            "tagit", [DataType.FLOAT64], DataType.UTF8,
            host_fn=lambda x: np.asarray([f"p{v:.0f}" for v in x], dtype=object),
        )
        METRICS.reset()
        table = ctx.sql_collect("SELECT region, tagit(price) FROM sales WHERE qty > 400")
        snap = METRICS.snapshot()
        assert snap["timings_s"].get("execute.partitioned_pipeline", 0) == 0
        want = [
            (r[0], f"p{r[2]:.0f}") for r in rows
            if r[1] is not None and r[1] > 400
        ]
        assert sorted(table.to_rows()) == sorted(want)


class TestPartitionedFallback:
    def test_non_aggregate_matches_union_semantics(self, parts):
        paths, rows = parts
        table = _partitioned_ctx(paths).sql_collect(
            "SELECT region, price FROM sales WHERE price > 50.0"
        )
        want = [(r[0], r[2]) for r in rows if r[2] > 50.0]
        got = table.to_rows()
        assert len(got) == len(want)
        assert sorted(got) == sorted(want)

    def test_sort_limit_over_partitions(self, parts):
        paths, rows = parts
        table = _partitioned_ctx(paths).sql_collect(
            "SELECT price FROM sales ORDER BY price DESC LIMIT 5"
        )
        want = sorted((r[2] for r in rows), reverse=True)[:5]
        np.testing.assert_allclose([r[0] for r in table.to_rows()], want, rtol=1e-12)


class TestMemoryPartitions:
    def test_memory_partitions_remap_string_codes(self):
        """Partitions whose dictionaries assigned codes in different
        orders must still group correctly (codes remap into a shared
        dictionary at registration)."""
        from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        schema = Schema(
            [Field("region", DataType.UTF8, False), Field("qty", DataType.INT64, False)]
        )

        def mem_part(regions, qtys):
            d = StringDictionary()
            codes = d.encode(regions)
            batch = make_host_batch(
                schema,
                [codes, np.asarray(qtys, np.int64)],
                [None, None],
                [d, None],
            )
            return MemoryDataSource(schema, [batch])

        # p0 assigns north=0, south=1; p1 assigns south=0, north=1
        p0 = mem_part(["north", "north", "south"], [1, 2, 300])
        p1 = mem_part(["south", "north"], [4, 1000])
        ctx = PartitionedContext(mesh=make_mesh(2))
        ctx.register_datasource("t", PartitionedDataSource([p0, p1]))
        got = _as_dict(ctx.sql_collect("SELECT region, SUM(qty) FROM t GROUP BY region"))
        assert got == {("north",): (1003,), ("south",): (304,)}


class TestFusedMeshRounds:
    def test_warm_rounds_fold_into_one_launch(self):
        """Multi-round mesh aggregates fold like the single-device
        batch-group fold: consecutive WARM rounds (round cache hits)
        of one shape class dispatch as ONE multi-round launch, with
        parity against the per-round path."""
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource
        from datafusion_tpu.exec.materialize import collect
        from datafusion_tpu.utils.metrics import METRICS

        schema = Schema([
            Field("k", DataType.INT64, False),
            Field("v", DataType.FLOAT64, False),
        ])
        rng = np.random.default_rng(11)
        parts = []
        for _p in range(4):
            batches = [
                make_host_batch(schema, [
                    rng.integers(0, 6, 512).astype(np.int64),
                    rng.uniform(0, 10, 512),
                ])
                for _ in range(3)  # 3 rounds per scan
            ]
            parts.append(MemoryDataSource(schema, batches))
        ctx = PartitionedContext(mesh=make_mesh(4), result_cache=False)
        ctx.register_datasource("t", PartitionedDataSource(parts))
        rel = ctx.sql("SELECT k, SUM(v), COUNT(1) FROM t GROUP BY k")
        want = sorted(collect(rel).to_rows())
        assert sorted(collect(rel).to_rows()) == want  # admit rounds
        before = dict(METRICS.counts)
        got = sorted(collect(rel).to_rows())  # warm: multi-round fold
        delta = {
            k: v - before.get(k, 0) for k, v in METRICS.counts.items()
        }
        assert got == want
        assert delta.get("mesh.round_cache_hits", 0) >= 3
        assert delta.get("mesh.fused_rounds", 0) >= 3
        assert delta.get("mesh.fused_round_launches", 0) == 1
        assert delta.get("device.launches.mesh.stacked", 0) == 0

    def test_fuse_off_restores_per_round_dispatch(self):
        """DATAFUSION_TPU_FUSE=0: warm rounds dispatch one launch each
        (byte-identical escape hatch), same answers."""
        import os

        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource
        from datafusion_tpu.exec.materialize import collect
        from datafusion_tpu.utils.metrics import METRICS

        schema = Schema([
            Field("k", DataType.INT64, False),
            Field("v", DataType.FLOAT64, False),
        ])
        rng = np.random.default_rng(12)
        parts = [
            MemoryDataSource(schema, [
                make_host_batch(schema, [
                    rng.integers(0, 6, 256).astype(np.int64),
                    rng.uniform(0, 10, 256),
                ])
                for _ in range(2)
            ])
            for _p in range(2)
        ]
        ctx = PartitionedContext(mesh=make_mesh(2), result_cache=False)
        ctx.register_datasource("t", PartitionedDataSource(parts))
        rel = ctx.sql("SELECT k, SUM(v) FROM t GROUP BY k")
        want = sorted(collect(rel).to_rows())
        assert sorted(collect(rel).to_rows()) == want
        os.environ["DATAFUSION_TPU_FUSE"] = "0"
        try:
            before = dict(METRICS.counts)
            assert sorted(collect(rel).to_rows()) == want
            delta = {
                k: v - before.get(k, 0)
                for k, v in METRICS.counts.items()
            }
            assert delta.get("mesh.fused_round_launches", 0) == 0
            assert delta.get("device.launches.mesh.stacked", 0) == 2
        finally:
            os.environ.pop("DATAFUSION_TPU_FUSE", None)


class TestPhysicalPlanParity:
    def test_physical_plan_json_round_trip(self):
        """Mirrors the reference's PhysicalPlan variants
        (physicalplan.rs:18-34) in the JSON wire format."""
        from datafusion_tpu.plan.logical import EmptyRelation

        plan = EmptyRelation(Schema([]))
        for pp in (
            PhysicalPlan("interactive", plan),
            PhysicalPlan("write", plan, filename="/tmp/out.csv", file_format="csv"),
            PhysicalPlan("show", plan, count=10),
        ):
            rt = PhysicalPlan.from_json(pp.to_json())
            assert rt.kind == pp.kind
            assert rt.filename == pp.filename
            assert rt.count == pp.count


class TestCacheConsistency:
    def test_pack_overflow_keeps_groups_distinct(self):
        """Mixed-radix pack must bail (not wrap) when an int64 key spans
        more than 63 bits."""
        from datafusion_tpu.exec.aggregate import GroupKeyEncoder

        enc = GroupKeyEncoder(2)
        k0 = np.asarray([-(2**62), 2**62, -(2**62), 2**62], dtype=np.int64)
        k1 = np.asarray([0, 0, 1, 1], dtype=np.int64)
        ids = enc.encode([k0, k1], [None, None])
        assert len(set(ids.tolist())) == 4

    def test_merge_codes_invalidates_device_cache(self):
        """A query before partitioned registration must not leave stale
        device copies of pre-merge dict codes."""
        from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        schema = Schema(
            [Field("s", DataType.UTF8, False), Field("v", DataType.INT64, False)]
        )

        def mem(strings, vals):
            d = StringDictionary()
            codes = d.encode(strings)
            return MemoryDataSource(
                schema,
                [make_host_batch(schema, [codes, np.asarray(vals, np.int64)],
                                 [None, None], [d, None])],
            )

        p0 = mem(["a", "b"], [1, 1])
        p1 = mem(["b", "a"], [1, 1])  # opposite code order
        ctx = ExecutionContext()
        ctx.register_datasource("t0", p1)
        # populate p1's device cache with pre-merge codes
        before = ctx.sql_collect("SELECT SUM(v) FROM t0 WHERE s = 'b'")
        assert before.to_rows() == [(1,)]
        pctx = PartitionedContext(mesh=make_mesh(2))
        pctx.register_datasource("t", PartitionedDataSource([p0, p1]))
        after = pctx.sql_collect("SELECT SUM(v) FROM t WHERE s = 'b'")
        assert after.to_rows() == [(2,)]


class TestMeshStringMinMax:
    def test_utf8_minmax_over_mesh(self):
        """MIN/MAX(Utf8) rides the collective combine in rank space
        (partitions share dictionaries, so codes are globally valid)."""
        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
        from datafusion_tpu.exec.context import ExecutionContext
        from datafusion_tpu.exec.datasource import MemoryDataSource
        from datafusion_tpu.parallel.partition import (
            PartitionedContext,
            PartitionedDataSource,
        )

        schema = Schema(
            [
                Field("k", DataType.INT64, False),
                Field("name", DataType.UTF8, True),
            ]
        )
        rng = np.random.default_rng(23)
        parts = []
        for _p in range(4):
            d = StringDictionary()
            names = [f"name_{int(i):03d}" for i in rng.integers(0, 200, 300)]
            codes = d.encode(names)
            valid = rng.random(300) > 0.1
            cols = [rng.integers(0, 5, 300).astype(np.int64), codes]
            parts.append(
                MemoryDataSource(
                    schema, [make_host_batch(schema, cols, [None, valid], [None, d])]
                )
            )
        pds = PartitionedDataSource(parts)

        sql = "SELECT k, MIN(name), MAX(name), COUNT(name) FROM t GROUP BY k"
        mctx = PartitionedContext(n_devices=4)
        mctx.register_datasource("t", pds)
        got = sorted(mctx.sql_collect(sql).to_rows())

        lctx = ExecutionContext(device="cpu")
        lctx.register_datasource("t", pds)
        want = sorted(lctx.sql_collect(sql).to_rows())
        assert got == want
        # prove the mesh path actually ran (not the serial fallback)
        from datafusion_tpu.parallel.partition import _match_partitioned_aggregate

        plan = mctx._plan(
            __import__("datafusion_tpu.sql.parser", fromlist=["parse_sql"]).parse_sql(sql)
        )
        agg, _, _ = _match_partitioned_aggregate(plan, mctx.datasources)
        assert agg is not None
