"""Tail-latency attribution (obs/attribution.py): per-query critical
paths, the tail explainer, and per-client metering.

The contracts under test:
- the meter's charge/split semantics (solo vs shared scopes, weighted
  apportionment, conservation of split sums);
- the serving-chain segment decomposition and the span-tree critical
  path, including **hedge-loser exclusion**: a merged trace with a
  lost hedge attempt must not inflate the winner's critical path, and
  the loser's wall meters as duplicate cost — never as the winner's
  device-seconds (double charge);
- pin byte-second accrual against the device ledger's pin table;
- the tail explainer's windowed per-segment p50/p95/p99 ranking;
- the surfacing paths: tenant.* gauges in scrapes, /debug/tenants,
  /debug/tail, the tar-format debug bundle, and the CLI modes;
- serve.py integration: client_id rides submit/flight events, costs
  apportion per client, conservation (summed device-seconds tracks
  the measured launch wall), and the shed-after-enqueue audit
  (``_shed_ticket`` is idempotent — ``_pending`` can never go
  negative).
"""

from __future__ import annotations

import io
import json
import tarfile
import threading
import time
import types

import numpy as np
import pytest

from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.datasource import MemoryDataSource
from datafusion_tpu.obs import attribution
from datafusion_tpu.obs.attribution import (
    EXPLAINER,
    METER,
    TailExplainer,
    charge_h2d,
    charge_hedge_loss,
    client_scope,
    critical_path_from_spans,
    hedge_loser_span_ids,
    note_launch,
    shared_scope,
)
from datafusion_tpu.obs.device import LEDGER
from datafusion_tpu.utils.metrics import METRICS


@pytest.fixture(autouse=True)
def _clean_attribution():
    attribution.reset_for_tests()
    yield
    attribution.reset_for_tests()


# -- span helpers ------------------------------------------------------
def _span(name, start_ms, end_ms, span_id, parent_id=None,
          trace_id="t1", **attrs):
    return {
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent_id,
        "start_ns": int(start_ms * 1e6),
        "end_ns": int(end_ms * 1e6),
        "attrs": attrs,
    }


class TestMeter:
    def test_solo_scope_charges_one_client(self):
        with client_scope("alice"):
            note_launch(0.25)
            charge_h2d(1000)
        snap = METER.snapshot()
        assert snap["alice"]["device_seconds"] == pytest.approx(0.25)
        assert snap["alice"]["h2d_bytes"] == 1000.0

    def test_shared_scope_splits_by_weight_and_conserves(self):
        members = (("a", 0.5), ("b", 0.25), ("c", 0.25))
        with shared_scope(members):
            note_launch(1.0)
            charge_h2d(4000)
        snap = METER.snapshot()
        assert snap["a"]["device_seconds"] == pytest.approx(0.5)
        assert snap["b"]["device_seconds"] == pytest.approx(0.25)
        assert snap["c"]["device_seconds"] == pytest.approx(0.25)
        # conservation: the split sums to the measured whole
        assert sum(
            s["device_seconds"] for s in snap.values()
        ) == pytest.approx(1.0)
        assert sum(s["h2d_bytes"] for s in snap.values()) \
            == pytest.approx(4000.0)

    def test_no_scope_charges_nobody(self):
        note_launch(0.5)
        charge_h2d(1 << 20)
        assert METER.snapshot() == {}

    def test_scope_accumulator_reads_back_launch_wall(self):
        with client_scope("a") as acc:
            note_launch(0.1)
            note_launch(0.2)
        assert acc[0] == pytest.approx(0.3)

    def test_scopes_nest_and_restore(self):
        with client_scope("outer"):
            assert attribution.current_client() == "outer"
            with client_scope("inner"):
                assert attribution.current_client() == "inner"
            assert attribution.current_client() == "outer"
        assert attribution.current_client() is None
        assert attribution.current_scope() is None

    def test_scope_is_per_thread(self):
        seen = {}

        def other():
            seen["client"] = attribution.current_client()

        with client_scope("main-only"):
            th = threading.Thread(target=other)
            th.start()
            th.join()
        assert seen["client"] is None

    def test_hedge_loss_charges_duplicate_not_device_seconds(self):
        scope = ("solo", "alice", [0.0])
        charge_hedge_loss(scope, 0.7)
        snap = METER.snapshot()
        assert snap["alice"]["hedge_duplicate_seconds"] \
            == pytest.approx(0.7)
        assert snap["alice"]["device_seconds"] == 0.0
        charge_hedge_loss(None, 1.0)  # untenanted loser: nobody pays
        assert "default" not in METER.snapshot()

    def test_totals(self):
        METER.charge("a", "queries", 1)
        METER.charge("b", "queries", 2)
        METER.charge("b", "device_seconds", 0.5)
        t = METER.totals()
        assert t["queries"] == 3
        assert t["device_seconds"] == pytest.approx(0.5)

    def test_client_cardinality_is_bounded(self, monkeypatch):
        """'Millions of users' must not grow the meter (and the
        tenant.* gauges riding every scrape) without bound: past the
        cap, new clients fold into one overflow bucket — totals and
        conservation stay exact."""
        monkeypatch.setattr(attribution, "_MAX_CLIENTS", 4)
        for i in range(10):
            METER.charge(f"user-{i}", "device_seconds", 1.0)
        snap = METER.snapshot()
        assert len(snap) == 5  # 4 named + the overflow bucket
        assert snap[attribution._OVERFLOW]["device_seconds"] \
            == pytest.approx(6.0)
        assert METER.totals()["device_seconds"] == pytest.approx(10.0)


class TestPinAccrual:
    def test_byte_seconds_accrue_to_pinning_client(self):
        fp = "table:attr_test_pin"
        LEDGER.pin(fp, nbytes=1000, owner="pin.attr_test")
        try:
            t0 = time.monotonic()
            attribution.register_pin_client(fp, "carol")
            attribution._PIN_ACCRUED_AT[fp] = t0  # pin the anchor
            attribution.accrue_pins(now=t0 + 10.0)
            snap = METER.snapshot()
            assert snap["carol"]["pin_byte_seconds"] \
                == pytest.approx(10_000.0)
            # accrual is incremental, not from-birth
            attribution.accrue_pins(now=t0 + 12.0)
            assert METER.snapshot()["carol"]["pin_byte_seconds"] \
                == pytest.approx(12_000.0)
        finally:
            LEDGER.unpin(fp)

    def test_evicted_pin_stops_accruing(self):
        fp = "table:attr_test_evict"
        LEDGER.pin(fp, nbytes=500, owner="pin.attr_test")
        t0 = time.monotonic()
        attribution.register_pin_client(fp, "dave")
        attribution._PIN_ACCRUED_AT[fp] = t0
        LEDGER.unpin(fp)
        attribution.accrue_pins(now=t0 + 100.0)
        assert "dave" not in METER.snapshot()
        assert fp not in attribution._PIN_CLIENTS  # pruned


class TestTailExplainer:
    def test_ranking_names_dominant_segment(self):
        ex = TailExplainer()
        for i in range(50):
            ex.observe(1.0, {"queue_wait": 0.8, "merge": 0.1,
                             "shared_launch_share": 0.1})
        rep = ex.explain()
        assert rep["top"] == "queue_wait"
        assert rep["queries"] == 50
        by_name = {r["segment"]: r for r in rep["segments"]}
        assert by_name["queue_wait"]["p99_s"] == pytest.approx(0.8)
        assert by_name["queue_wait"]["share_of_wall"] \
            == pytest.approx(0.8)

    def test_tail_ranks_above_median_heavy_segment(self):
        """A segment that is big at p99 but small at p50 must outrank
        a segment that is moderate everywhere: the explainer ranks by
        TAIL contribution, which is the question a breach asks."""
        ex = TailExplainer()
        for i in range(100):
            spiky = 2.0 if i >= 98 else 0.01  # p99 ~2.0
            ex.observe(spiky + 0.3, {"demux_pull": spiky,
                                     "merge": 0.3})
        rep = ex.explain()
        assert rep["top"] == "demux_pull"

    def test_window_prunes_old_paths(self):
        ex = TailExplainer(window_s=600.0)
        ex._paths.append((time.monotonic() - 10_000, "served", 1.0,
                          {"queue_wait": 1.0}))
        ex.observe(1.0, {"merge": 1.0})
        rep = ex.explain()
        assert rep["queries"] == 1
        assert rep["top"] == "merge"

    def test_observe_phases_fallback_and_scope_skip(self):
        attribution.observe_phases(2.0, {"decode": 1.5, "h2d": 0.5})
        assert len(EXPLAINER) == 1
        # a served query (client scope ambient) observes its own path
        with client_scope("a"):
            attribution.observe_phases(2.0, {"decode": 1.5})
        assert len(EXPLAINER) == 1
        # no phases at all: the wall still counts, as "other"
        attribution.observe_phases(3.0, None)
        rep = EXPLAINER.explain()
        assert rep["queries"] == 2
        assert {r["segment"] for r in rep["segments"]} \
            == {"decode", "h2d", "other"}

    def test_observe_path_counts_client_query(self):
        attribution.observe_path("erin", 1.0, {"queue_wait": 1.0})
        assert METER.snapshot()["erin"]["queries"] == 1.0
        assert EXPLAINER.explain()["kinds"] == {"served": 1}


class TestCriticalPathFromSpans:
    def test_segments_union_and_other(self):
        spans = [
            _span("query", 0, 100, "root"),
            # two parallel dispatches overlap: union, not sum
            _span("coord.dispatch", 10, 50, "d1", "root", shard=0),
            _span("coord.dispatch", 30, 70, "d2", "root", shard=1),
            _span("merge", 70, 90, "m1", "root"),
        ]
        cp = critical_path_from_spans(spans)
        assert cp["wall_s"] == pytest.approx(0.100)
        assert cp["segments"]["coord.dispatch"] == pytest.approx(0.060)
        assert cp["segments"]["merge"] == pytest.approx(0.020)
        # other = 100ms - (60ms dispatch-union + 20ms merge) = 20ms
        assert cp["segments"]["other"] == pytest.approx(0.020)
        assert cp["excluded_spans"] == 0

    def test_lost_hedge_attempt_excluded_from_critical_path(self):
        """Satellite: a merged trace with a LOST hedge attempt — the
        primary outran it (no hedge_won on the request record), so the
        attempt's long-running span and its worker child must not
        inflate the winner's critical path; the attempt's wall reports
        as duplicate cost instead.  This is the shape the coordinator
        actually emits: the primary request-record span ends at the
        first valid response; the attempt span (``hedge_attempt``)
        outlives it."""
        spans = [
            _span("query", 0, 100, "root"),
            # the request record: ends when the primary answered
            _span("coord.dispatch", 10, 40, "rec", "root",
                  shard=0, hedged=True),
            # the abandoned hedge attempt, finishing long after
            _span("coord.dispatch", 15, 95, "lose", "root",
                  shard=0, hedged=True, hedge_attempt=True),
            _span("worker.fragment", 16, 94, "wf", "lose", shard=0),
            _span("merge", 40, 50, "m", "root"),
        ]
        cp = critical_path_from_spans(spans)
        # the request record's 30ms, NOT extended by the loser's tail
        assert cp["segments"]["coord.dispatch"] == pytest.approx(0.030)
        assert cp["excluded_spans"] == 2  # attempt + its worker child
        assert cp["hedge_loser_s"] == pytest.approx(0.080)
        # and hedge_loser_s is NOT part of the path segments
        assert sum(
            v for k, v in cp["segments"].items()
        ) == pytest.approx(cp["wall_s"])

    def test_won_hedge_attempt_is_kept_as_provenance(self):
        """When the hedge WINS, the coordinator marks ``hedge_won`` on
        the request record and the winner's worker spans parent under
        the ATTEMPT span — excluding it would drop the very subtree
        that produced the answer.  Nothing is excluded (the abandoned
        primary request has no span of its own)."""
        spans = [
            _span("query", 0, 100, "root"),
            _span("coord.dispatch", 10, 40, "rec", "root",
                  shard=0, hedged=True, hedge_won=True,
                  winner="w2:1"),
            _span("coord.dispatch", 20, 40, "att", "root",
                  shard=0, hedged=True, hedge_attempt=True),
            _span("worker.fragment", 21, 39, "wf", "att", shard=0),
        ]
        assert hedge_loser_span_ids(spans) == set()
        cp = critical_path_from_spans(spans)
        assert cp["excluded_spans"] == 0
        assert cp["segments"]["coord.dispatch"] == pytest.approx(0.030)

    def test_failover_retries_are_not_hedge_pairs(self):
        """Two dispatch spans for one shard WITHOUT hedge attrs are a
        failover retry (connection error -> replay elsewhere), not a
        hedge: the successful retry is real critical-path time and
        must never be excluded as a 'loser'."""
        spans = [
            _span("query", 0, 3500, "root"),
            # failed first attempt (ends EARLIEST — the old
            # earliest-end heuristic would have kept this one)
            _span("coord.dispatch", 1000, 1500, "a0", "root",
                  shard=0, attempt=0, failed_over=True),
            # the successful retry
            _span("coord.dispatch", 1500, 3000, "a1", "root",
                  shard=0, attempt=1),
            _span("worker.fragment", 1600, 2900, "wf", "a1", shard=0),
        ]
        assert hedge_loser_span_ids(spans) == set()
        cp = critical_path_from_spans(spans)
        # both attempts count: [1000,1500) + [1500,3000) = 2s
        assert cp["segments"]["coord.dispatch"] == pytest.approx(2.0)
        assert cp["hedge_loser_s"] == 0.0

    def test_distinct_shards_are_not_hedge_groups(self):
        spans = [
            _span("query", 0, 50, "root"),
            _span("coord.dispatch", 0, 30, "d1", "root", shard=0),
            _span("coord.dispatch", 0, 40, "d2", "root", shard=1),
        ]
        assert hedge_loser_span_ids(spans) == set()

    def test_loser_wall_not_double_charged_to_meter(self):
        """The metering half of the satellite: the winner's wall
        charges device_seconds once; the loser's wall charges ONLY
        hedge_duplicate_seconds — never a second device_seconds
        charge (the coordinator's loser attempt reports through
        `charge_hedge_loss`, not `note_launch`)."""
        scope = ("solo", "frank", [0.0])
        with client_scope("frank"):
            note_launch(0.030)          # the winner's launch wall
        charge_hedge_loss(scope, 0.085)  # the loser, self-reporting
        snap = METER.snapshot()["frank"]
        assert snap["device_seconds"] == pytest.approx(0.030)
        assert snap["hedge_duplicate_seconds"] == pytest.approx(0.085)

    def test_empty_and_unended_spans(self):
        assert critical_path_from_spans([])["wall_s"] == 0.0
        cp = critical_path_from_spans(
            [{"name": "x", "span_id": "a", "start_ns": 5, "end_ns": 0}]
        )
        assert cp["segments"] == {}


class TestSurfacing:
    def test_tenant_gauges_in_metrics_text(self):
        METER.charge("gina", "device_seconds", 1.25)
        ctx = ExecutionContext(result_cache=False)
        text = ctx.metrics_text()
        assert "tenant.gina.device_seconds" in text

    def test_node_snapshot_carries_tenant_gauges(self):
        from datafusion_tpu.obs.aggregate import node_snapshot

        METER.charge("henry", "h2d_bytes", 4096)
        snap = node_snapshot()
        assert snap["gauges"]["tenant.henry.h2d_bytes"] == 4096.0

    def test_fleet_sums_tenant_gauges_across_nodes(self):
        from datafusion_tpu.obs.aggregate import FleetAggregator

        agg = FleetAggregator(include_local=False)
        for node, secs in (("w1", 1.0), ("w2", 2.0)):
            agg.ingest(node, {
                "ts": time.time(), "histograms": {}, "counts": {},
                "gauges": {"tenant.ida.device_seconds": secs},
            })
        g = agg.gauges()
        assert g["fleet.tenant.ida.device_seconds"] == pytest.approx(3.0)

    def test_debug_tenants_route(self):
        from datafusion_tpu.obs.httpd import _route_request

        METER.charge("judy", "device_seconds", 0.5)
        METER.charge("judy", "queries", 3)
        srv = types.SimpleNamespace(label="test-node")
        code, ctype, body = _route_request(srv, "/debug/tenants", {})
        assert code == 200
        doc = json.loads(body)
        assert doc["node"] == "test-node"
        assert doc["clients"]["judy"]["queries"] == 3
        assert "conservation" in doc
        assert set(doc["conservation"]) \
            == {"device_seconds_sum", "launch_wall_s", "coverage"}

    def test_debug_tail_route(self):
        from datafusion_tpu.obs.httpd import _route_request

        EXPLAINER.observe(1.0, {"queue_wait": 0.9, "merge": 0.1})
        srv = types.SimpleNamespace(label="test-node")
        code, _, body = _route_request(srv, "/debug/tail", {})
        doc = json.loads(body)
        assert code == 200 and doc["top"] == "queue_wait"
        # window filter forwards: age the entry past the window first
        # (a warm route round-trip can finish inside 0.1 ms)
        time.sleep(0.001)
        code, _, body = _route_request(
            srv, "/debug/tail", {"window_s": "0.0001"}
        )
        assert json.loads(body)["queries"] == 0

    def test_tenants_text_renders_conservation(self):
        METER.charge("kate", "device_seconds", 0.25)
        METER.charge("kate", "queries", 1)
        text = attribution.tenants_text()
        assert "kate" in text and "conservation:" in text

    def test_slo_breach_artifact_attaches_tail(self, tmp_path):
        from datafusion_tpu.obs import recorder
        from datafusion_tpu.obs.slo import Objective, SloWatchdog

        EXPLAINER.observe(1.0, {"queue_wait": 0.95, "merge": 0.05})
        recorder.configure(directory=str(tmp_path), dump_interval_s=0)
        try:
            wd = SloWatchdog(min_samples=1)
            wd.add(Objective("tail_test", "p99", 0.001))
            wd.observe(5.0)
            rows = wd.evaluate()
            assert rows[0]["breached"]
            dumps = list(tmp_path.glob("flight-*.json"))
            assert dumps, "breach produced no artifact"
            doc = json.loads(dumps[-1].read_text())
            assert doc["reason"] == "slo_breach"
            assert doc["tail"]["top"] == "queue_wait"
        finally:
            recorder.configure(dump_interval_s=30.0)

    def test_slow_query_artifact_attaches_tail_and_critical_path(
            self, tmp_path):
        from datafusion_tpu.obs import recorder

        EXPLAINER.observe(1.0, {"decode": 0.8, "h2d": 0.2})
        recorder.configure(directory=str(tmp_path), dump_interval_s=0)
        try:
            path = recorder.capture_query_artifacts(
                "slow_query", wall_s=12.0, trace_id=None, label="q",
            )
            doc = json.loads(open(path).read())
            assert doc["tail"]["top"] == "decode"
        finally:
            recorder.configure(dump_interval_s=30.0)


class TestTarBundle:
    def test_members_and_core_doc(self):
        from datafusion_tpu.obs.httpd import build_bundle_tar

        METER.charge("liam", "device_seconds", 0.125)
        EXPLAINER.observe(0.5, {"merge": 0.5})
        blob = build_bundle_tar(profile_seconds=0.0)
        with tarfile.open(fileobj=io.BytesIO(blob)) as tf:
            names = set(tf.getnames())
            assert {"bundle.json", "flights.jsonl", "spans.jsonl",
                    "metrics.prom", "tenants.json",
                    "tail.json"} <= names
            core = json.loads(
                tf.extractfile("bundle.json").read()
            )
            # heavy attachments moved OUT of the core document
            assert core["flights"]["member"] == "flights.jsonl"
            assert "metrics" not in core
            assert sorted(core["attachments"]) == sorted(
                names - {"bundle.json"}
            )
            tenants = json.loads(
                tf.extractfile("tenants.json").read()
            )
            assert tenants["clients"]["liam"]["device_seconds"] \
                == pytest.approx(0.125)
            # flight members parse line-wise
            flights = tf.extractfile("flights.jsonl").read().decode()
            for line in filter(None, flights.split("\n")):
                json.loads(line)

    def test_tar_route(self):
        from datafusion_tpu.obs.httpd import _route_request

        srv = types.SimpleNamespace(
            label="n", gauges=lambda: {}, status_fn=None,
        )
        code, ctype, body = _route_request(
            srv, "/debug/bundle", {"format": "tar", "seconds": "0"}
        )
        assert code == 200 and ctype == "application/x-tar"
        with tarfile.open(fileobj=io.BytesIO(body)) as tf:
            assert "bundle.json" in tf.getnames()

    def test_cli_local_tar_bundle(self, tmp_path):
        from datafusion_tpu.cli import run_debug_bundle

        out = io.StringIO()
        rc = run_debug_bundle(None, None, str(tmp_path), 0.0,
                              out=out, fmt="tar")
        assert rc == 0
        tars = list(tmp_path.glob("bundle-local.tar"))
        assert len(tars) == 1
        with tarfile.open(tars[0]) as tf:
            assert "bundle.json" in tf.getnames()
        assert "members" in out.getvalue()

    def test_cli_top_tenants(self):
        from datafusion_tpu.cli import run_top

        METER.charge("mona", "queries", 2)
        out = io.StringIO()
        rc = run_top(None, None, 0.0, out=out, tenants=True)
        assert rc == 0
        assert "mona" in out.getvalue()
        assert "conservation" in out.getvalue()

    def test_fleet_tenants_render_from_gauges(self):
        """A coordinator's --tenants view renders a REMOTE fleet's
        metering from the node-summed gauges — a fresh CLI process's
        own (empty) meter must not hide the fleet's clients."""
        clients = attribution.clients_from_gauges({
            "fleet.tenant.ana.device_seconds": 1.5,
            "fleet.tenant.ana.queries": 3.0,
            "tenant.dotted.id.h2d_bytes": 2e6,  # dotted client id
            "fleet.nodes": 2,  # non-tenant gauges ignored
        })
        assert clients["ana"]["device_seconds"] == 1.5
        assert clients["dotted.id"]["h2d_bytes"] == 2e6
        text = attribution.tenants_text_from_gauges({
            "fleet.tenant.ana.device_seconds": 1.5,
        })
        assert "ana" in text and "fleet sums" in text

    def test_served_query_observes_slo_watchdog_once(self):
        """The funnel's watchdog feed is suppressed for served
        queries (client scope ambient): only the front door's
        client-visible wall lands in the SLO window — 2N samples
        would dilute exactly the queueing tail the SLO watches."""
        from datafusion_tpu.obs import slo as slo_mod
        from datafusion_tpu.obs.aggregate import query_completed

        wd = slo_mod.SloWatchdog(min_samples=1, capture_on_breach=False)
        wd.add(slo_mod.Objective("x", "p99", 10.0))
        prev, slo_mod.WATCHDOG = slo_mod.WATCHDOG, wd
        try:
            with client_scope("serv"):
                query_completed(0.01)   # served: suppressed
            query_completed(0.02)       # plain query: observed
            assert len(wd._window) == 1
        finally:
            slo_mod.WATCHDOG = prev


# -- serve.py integration ----------------------------------------------
def _table(seed: int, rows: int = 2048, batches: int = 2):
    rng = np.random.default_rng(seed)
    schema = Schema([
        Field("k", DataType.UTF8, False),
        Field("v", DataType.FLOAT64, False),
        Field("p", DataType.FLOAT64, False),
    ])
    d = StringDictionary()
    out = []
    for _ in range(batches):
        codes = d.encode([f"g{j}" for j in rng.integers(0, 8, rows)])
        out.append(make_host_batch(
            schema,
            [codes, np.round(rng.uniform(0, 100, rows), 2),
             np.round(rng.uniform(0, 1, rows), 3)],
            dicts=[d, None, None],
        ))
    return MemoryDataSource(schema, out)


def _q(lit: float) -> str:
    return (f"SELECT k, SUM(v), COUNT(1) FROM t "
            f"WHERE p < {lit} GROUP BY k")


class TestServeIntegration:
    def test_per_client_metering_and_conservation(self):
        ctx = ExecutionContext(result_cache=False)
        ctx.register_datasource("t", _table(21))
        disp0 = METRICS.timings.get("device.dispatch", 0.0)
        srv = ctx.serve(workers=2, window_s=0.01, megabatch_max=8)
        try:
            tickets = []
            for i in range(8):
                cid = f"client{i % 2}"
                tickets.append(srv.submit(_q(0.3 + 0.02 * i),
                                          client_id=cid))
            for t in tickets:
                t.result(timeout=60)
        finally:
            srv.stop()
        snap = METER.snapshot()
        assert snap["client0"]["queries"] == 4
        assert snap["client1"]["queries"] == 4
        dev_sum = sum(c["device_seconds"] for c in snap.values())
        launch_wall = METRICS.timings.get("device.dispatch", 0.0) - disp0
        assert launch_wall > 0
        # conservation: apportioned device-seconds == measured launch
        # wall (both derive from the same per-launch measurement; the
        # only work outside a scope here would be a bug)
        assert dev_sum == pytest.approx(launch_wall, rel=0.10)
        # pin attribution: the first client to touch the table owns
        # the pin's byte-seconds
        assert "table:t" in attribution._PIN_CLIENTS
        t0 = time.monotonic()
        attribution.accrue_pins(now=t0 + 5)
        pin_client = attribution._PIN_CLIENTS.get("table:t")
        if pin_client is not None:  # may have been evicted by pressure
            assert METER.snapshot()[pin_client]["pin_byte_seconds"] > 0

    def test_served_paths_feed_explainer_with_segments(self):
        ctx = ExecutionContext(result_cache=False)
        ctx.register_datasource("t", _table(22))
        srv = ctx.serve(workers=1, window_s=0.01)
        try:
            for i in range(3):
                srv.submit(_q(0.4 + 0.01 * i),
                           client_id="nina").result(timeout=60)
        finally:
            srv.stop()
        rep = EXPLAINER.explain()
        assert rep["kinds"].get("served", 0) >= 3
        seen = {r["segment"] for r in rep["segments"]}
        assert "queue_wait" in seen
        assert "shared_launch_share" in seen or "merge" in seen

    def test_flight_events_carry_client_id(self):
        from datafusion_tpu.errors import QueryShedError
        from datafusion_tpu.obs import recorder

        ctx = ExecutionContext(result_cache=False)
        ctx.register_datasource("t", _table(23))
        srv = ctx.serve(workers=1, window_s=0.005, queue_depth=1)
        shed = 0
        tickets = []
        try:
            for i in range(8):
                try:
                    tickets.append(srv.submit(_q(0.3 + 0.01 * i),
                                              client_id="oscar"))
                except QueryShedError:
                    shed += 1
            for t in tickets:
                t.result(timeout=60)
        finally:
            srv.stop()
        kinds = {}
        for ev in recorder.events():
            if ev["kind"].startswith("serve."):
                kinds.setdefault(ev["kind"], []).append(
                    (ev.get("attrs") or {}).get("client")
                )
        assert "oscar" in kinds.get("serve.queued", [])
        assert "oscar" in kinds.get("serve.admit", [])
        if shed:
            assert "oscar" in kinds.get("serve.shed", [])
            assert METER.snapshot()["oscar"]["shed"] == shed

    def test_shed_ticket_idempotent_pending_never_negative(self):
        """The shed-after-enqueue audit: a double shed (stop() drain
        racing an executor-side deadline shed) must count once —
        ``_pending`` never goes negative and conservation holds."""
        ctx = ExecutionContext(result_cache=False)
        ctx.register_datasource("t", _table(24))
        srv = ctx.serve(workers=1, window_s=30.0, megabatch_max=64)
        try:
            t = srv.submit(_q(0.4), client_id="pete")
            time.sleep(0.05)
            assert srv._pending == 1
            srv._shed_ticket(t, "deadline")
            srv._shed_ticket(t, "shutdown")  # duplicate: no effect
            assert srv._pending == 0
            assert srv.shed == 1
            assert srv.admitted + srv.shed == srv.submitted
        finally:
            srv.stop()
        # the stop() drain saw an already-shed ticket: still 0
        assert srv._pending == 0
        assert srv.shed == 1

    def test_stop_drain_still_sheds_queued_tickets(self):
        from datafusion_tpu.errors import QueryShedError

        ctx = ExecutionContext(result_cache=False)
        ctx.register_datasource("t", _table(25))
        srv = ctx.serve(workers=1, window_s=30.0, megabatch_max=64)
        t = srv.submit(_q(0.4), client_id="quinn")
        time.sleep(0.05)
        srv.stop()
        with pytest.raises(QueryShedError) as ei:
            t.result(timeout=5.0)
        assert ei.value.reason == "shutdown"
        assert srv._pending == 0
        assert srv.admitted + srv.shed == srv.submitted


class TestLintCoverage:
    def test_df005_catches_lock_in_attribution_path(self):
        from datafusion_tpu.analysis.lint import lint_source

        src = (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "def note_launch(seconds):\n"
            "    with _lock:\n"
            "        pass\n"
        )
        findings = lint_source(src, "datafusion_tpu/obs/attribution.py")
        assert any(f.rule == "DF005" for f in findings)

    def test_repo_attribution_module_is_clean(self):
        import os

        from datafusion_tpu.analysis.lint import lint_paths

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(repo, "datafusion_tpu", "obs",
                            "attribution.py")
        assert lint_paths([path]) == []
