"""Parity tests for the C++ native SQL front-end and plan IR
(`native/sql_frontend.cpp`).

The reference's front-end is native (its parser `dfparser.rs:74`, its
serde plan IR `logicalplan.rs:133-345`); here the C++ implementation is
the default and the Python one the fallback, so these tests pin the two
to identical behavior: AST equality over a statement corpus, identical
ParserError classification, byte-identical plan JSON round trips, and
identical pretty-prints (the planner golden-test format).
"""

from __future__ import annotations

import pytest

from datafusion_tpu.datatypes import DataType, Field, Schema, StructType
from datafusion_tpu.errors import ParserError, PlanError
from datafusion_tpu.native.sqlfront import (
    frontend_available,
    native_parse_sql,
    native_plan_repr,
    native_plan_roundtrip,
)
from datafusion_tpu.plan.expr import Column, Literal, ScalarValue, SortExpr
from datafusion_tpu.plan.logical import Limit, Projection, Sort, TableScan
from datafusion_tpu.sql.parser import Parser, parse_sql
from datafusion_tpu.sql.planner import SqlToRel

pytestmark = pytest.mark.skipif(
    not frontend_available(), reason="native front-end not built"
)

STATEMENTS = [
    "SELECT 1",
    "SELECT a FROM t",
    "SELECT * FROM t",
    "SELECT a, b + 1 AS s FROM t WHERE a > 2.5 AND b != 'x''y'",
    "SELECT a FROM t ORDER BY a DESC, b ASC LIMIT 10",
    "SELECT COUNT(*), COUNT(1), MIN(x), MAX(x), SUM(x), AVG(x) FROM t",
    "SELECT c, COUNT(*) FROM t GROUP BY c HAVING COUNT(*) > 1",
    "SELECT sqrt(x), atan2(y, x) FROM t",
    "SELECT CAST(a AS BIGINT), CAST(b AS VARCHAR(10)) FROM t",
    "SELECT -b, +b, a IS NULL, a IS NOT NULL FROM t",
    "SELECT (a + b) * 2, a % 3, a / 4 FROM t",
    "SELECT 1e5, 1E5, .5, 0.25, 3e-2, 'lit', TRUE, FALSE, NULL",
    "SELECT a FROM t WHERE a < 1 OR b <= 2 AND c >= 3 OR d <> 4",
    "SELECT 99999999999999999999999999 FROM t",  # > int64: arbitrary precision
    "select lower_case, mixed_Case_99 FROM t",
    "SELECT a -- trailing comment\nFROM t",
    "SELECT /* block */ a FROM t;",
    "EXPLAIN SELECT a FROM t WHERE b > 0",
    "CREATE EXTERNAL TABLE uk (city VARCHAR NOT NULL, lat DOUBLE, ok BOOLEAN NULL) "
    "STORED AS CSV WITHOUT HEADER ROW LOCATION '/x/y.csv'",
    "CREATE EXTERNAL TABLE p STORED AS PARQUET LOCATION 'f.parquet'",
    "CREATE EXTERNAL TABLE j (x INT) STORED AS NDJSON LOCATION 'f.ndjson';",
    "CREATE EXTERNAL TABLE c2 (x TINYINT, y SMALLINT, z REAL, w FLOAT(8), "
    "v CHAR(3)) STORED AS CSV WITH HEADER ROW LOCATION 'c.csv'",
]

BAD_STATEMENTS = [
    "",
    "SELEC a FROM t",
    "SELECT a FROM t WHERE",
    "SELECT a FROM t LIMIT 5 extra",
    "SELECT 'unterminated",
    "SELECT a FROM t ORDER",
    "SELECT /* unterminated FROM t",
    "CREATE EXTERNAL TABLE t (a NOTATYPE) STORED AS CSV LOCATION 'x'",
    "CREATE EXTERNAL TABLE t (a INT) LOCATION 'x'",
    "CREATE EXTERNAL TABLE t (a INT) STORED AS CSV",
    "SELECT a FROM t WHERE a IS 5",
    "SELECT CAST(a, BIGINT) FROM t",
]


class TestAstParity:
    @pytest.mark.parametrize("sql", STATEMENTS)
    def test_same_ast(self, sql):
        assert native_parse_sql(sql) == Parser(sql).parse_statement()

    @pytest.mark.parametrize("sql", BAD_STATEMENTS)
    def test_same_rejection(self, sql):
        with pytest.raises(ParserError):
            native_parse_sql(sql)
        with pytest.raises(ParserError):
            Parser(sql).parse_statement()

    def test_non_ascii_routes_to_python(self):
        # the byte-oriented C++ tokenizer defers unicode statements to
        # the Python parser (NBSP/unicode-digit classification differs)
        assert native_parse_sql("SELECT ünicøde FROM t") is None
        sel = parse_sql("SELECT ünicøde FROM t")
        assert sel.projection[0].name == "ünicøde"
        # NBSP is whitespace to Python, a word byte to C++
        sel = parse_sql("SELECT a\xa0FROM t")
        assert sel.relation.name == "t"

    def test_default_path_is_native(self, monkeypatch):
        # parse_sql must consult the native front-end when it is built
        import datafusion_tpu.native.sqlfront as sqlfront

        calls = []
        orig = sqlfront.native_parse_sql

        def spy(sql):
            calls.append(sql)
            return orig(sql)

        monkeypatch.setattr(sqlfront, "native_parse_sql", spy)
        parse_sql("SELECT 1")
        assert calls == ["SELECT 1"]


class _Catalog:
    def get_table_meta(self, name):
        return Schema(
            [
                Field("a", DataType.INT64, False),
                Field("b", DataType.FLOAT64, True),
                Field("c", DataType.UTF8, True),
                Field("d", DataType.UINT16, True),
            ]
        )

    def get_function_meta(self, name):
        return None


PLAN_QUERIES = [
    "SELECT a, b FROM t WHERE b > 1.5 ORDER BY a LIMIT 3",
    "SELECT c, MIN(b), COUNT(1) FROM t GROUP BY c",
    "SELECT CAST(a AS DOUBLE) FROM t WHERE c = 'CO' AND a IS NOT NULL",
    "SELECT b IS NULL, a % 2 FROM t WHERE c = 'x' OR a < -5",
    "SELECT c, SUM(b) FROM t GROUP BY c HAVING SUM(b) > 2 "
    "ORDER BY SUM(b) DESC LIMIT 4",
    "SELECT * FROM t",
    "SELECT b + d FROM t",  # implicit supertype casts on both sides
]


class TestPlanIrParity:
    @pytest.mark.parametrize("sql", PLAN_QUERIES)
    def test_roundtrip_and_repr(self, sql):
        plan = SqlToRel(_Catalog()).sql_to_rel(Parser(sql).parse_statement())
        js = plan.to_json_str()
        assert native_plan_roundtrip(js) == js
        assert native_plan_repr(js) == repr(plan)

    def test_struct_schema_roundtrip(self):
        # the reference's own wire-format contract test shape
        # (logicalplan.rs:609-648): nested struct schema
        schema = Schema(
            [
                Field("first_name", DataType.UTF8, False),
                Field(
                    "address",
                    StructType(
                        [
                            Field("street", DataType.UTF8, False),
                            Field("zip", DataType.UINT16, False),
                        ]
                    ),
                    False,
                ),
            ]
        )
        plan = Limit(
            5,
            Sort(
                [SortExpr(Column(0), False)],
                Projection(
                    [Column(0), Literal(ScalarValue.utf8('qu"ote\\s'))],
                    TableScan("default", "people", schema, [0, 1]),
                    schema,
                ),
                schema,
            ),
            schema,
        )
        js = plan.to_json_str()
        assert native_plan_roundtrip(js) == js
        assert native_plan_repr(js) == repr(plan)

    def test_malformed_plan_rejected(self):
        with pytest.raises(PlanError):
            native_plan_roundtrip('{"NotAPlan":{}}')
        with pytest.raises(PlanError):
            native_plan_roundtrip('{"Selection":{"expr":{"Column":0}}}')
