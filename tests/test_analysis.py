"""Static verification layer tests (analysis/): plan-IR verifier
accept/reject table over every LogicalPlan/expr variant, invariant
linter rules on synthetic ASTs + a self-lint gate over the package,
lock-order detection with a deliberately inverted two-lock fixture,
and the DATAFUSION_TPU_VERIFY=0 no-regression parity run."""

import os
import threading

import pytest

from datafusion_tpu.analysis import lint, lockcheck, verify
from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.errors import (
    NotSupportedError,
    PlanError,
    PlanVerificationError,
    TransientError,
)
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.plan.expr import (
    AggregateFunction,
    BinaryExpr,
    Cast,
    Column,
    IsNotNull,
    IsNull,
    Literal,
    Operator,
    ScalarFunction,
    ScalarValue,
    SortExpr,
)
from datafusion_tpu.plan.logical import (
    Aggregate,
    EmptyRelation,
    Limit,
    Projection,
    Selection,
    Sort,
    TableScan,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = Schema([
    Field("city", DataType.UTF8),
    Field("lat", DataType.FLOAT64),
    Field("pop", DataType.INT64),
    Field("flag", DataType.BOOLEAN),
])


def scan(schema=SCHEMA, projection=None):
    return TableScan("default", "t", schema, projection)


def lit_i(v):
    return Literal(ScalarValue.int64(v))


def lit_s(v):
    return Literal(ScalarValue.utf8(v))


@pytest.fixture
def ctx(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text(
        "city,lat,pop,flag\n"
        "SF,37.7,800000,true\nLA,34.0,4000000,false\nNY,40.7,8000000,true\n"
    )
    c = ExecutionContext(result_cache=False)
    c.register_csv("t", str(p), SCHEMA)
    return c


# ---------------------------------------------------------------- verifier


class TestVerifierAccepts:
    """Every plan variant the engine executes must verify clean."""

    @pytest.mark.parametrize("sql", [
        "SELECT city, pop FROM t",
        "SELECT * FROM t WHERE lat > 35.0",
        "SELECT pop + 1, CAST(pop AS DOUBLE) FROM t",
        "SELECT city FROM t WHERE city = 'SF'",
        "SELECT city FROM t WHERE 'SF' = city",
        "SELECT city FROM t WHERE city >= 'LA' AND pop > 100",
        "SELECT city, MIN(lat), MAX(city), COUNT(pop) FROM t GROUP BY city",
        "SELECT SUM(pop), AVG(lat) FROM t",
        "SELECT COUNT(*) FROM t",
        "SELECT 1 + 2",
        "SELECT city FROM t WHERE lat IS NOT NULL ORDER BY pop DESC LIMIT 2",
        "SELECT sqrt(lat) FROM t",
        "SELECT city FROM t WHERE pop IS NULL",
    ])
    def test_planner_output_verifies(self, ctx, sql):
        plan = ctx._plan(__import__(
            "datafusion_tpu.sql.parser", fromlist=["parse_sql"]
        ).parse_sql(sql))
        report = verify.verify_plan(plan, functions=ctx.functions)
        assert report.ok, report.render()

    def test_count_star_over_empty_relation(self):
        # COUNT(1) with no FROM: the COUNT(#0) rewrite is plan-shape
        # parity only — #0 must NOT need to resolve in a 0-col schema
        agg = AggregateFunction("COUNT", [Column(0)], DataType.UINT64, True)
        plan = Aggregate(EmptyRelation(Schema([])), [], [agg],
                         Schema([Field("COUNT", DataType.UINT64, True)]))
        assert verify.verify_plan(plan).ok

    def test_every_plan_variant_in_one_tree(self):
        base = Selection(BinaryExpr(Column(1), Operator.Gt,
                                    Literal(ScalarValue.float64(0.0))),
                         scan())
        proj = Projection(
            [Column(0), Column(2), IsNull(Column(1)), IsNotNull(Column(3))],
            base,
            Schema([Field("city", DataType.UTF8),
                    Field("pop", DataType.INT64),
                    Field("is_null", DataType.BOOLEAN, False),
                    Field("is_not_null", DataType.BOOLEAN, False)]),
        )
        sort = Sort([SortExpr(Column(1), False)], proj, proj.schema)
        plan = Limit(2, sort, sort.schema)
        report = verify.verify_plan(plan)
        assert report.ok, report.render()
        # the report carries one inferred schema per operator, root first
        labels = [label for _, label, _ in report.operators]
        assert labels[0].startswith("Limit")
        assert labels[-1].startswith("TableScan")


class TestVerifierRejects:
    def _one(self, plan, fragment, functions=None):
        report = verify.verify_plan(plan, functions=functions)
        assert not report.ok
        text = "\n".join(repr(d) for d in report.diagnostics)
        assert fragment in text, text
        with pytest.raises(PlanVerificationError):
            report.raise_if_failed()
        return report

    def test_unknown_column(self):
        plan = Projection([Column(9)], scan(),
                          Schema([Field("x", DataType.INT64)]))
        r = self._one(plan, "unknown column #9")
        # source-anchored: names the plan path and the expression
        assert r.diagnostics[0].path == "Projection.expr[0]"
        assert r.diagnostics[0].expr == "#9"

    def test_scan_projection_out_of_range(self):
        self._one(scan(projection=[0, 12]), "out of range")

    def test_non_boolean_predicate(self):
        self._one(Selection(Column(2), scan()), "expected Boolean")

    def test_utf8_vs_number_comparison(self):
        plan = Selection(
            BinaryExpr(Column(0), Operator.Eq, lit_i(3)), scan()
        )
        self._one(plan, "Utf8 column compares only against a string")

    def test_utf8_column_vs_column_comparison(self):
        plan = Selection(
            BinaryExpr(Column(0), Operator.Lt, Column(0)), scan()
        )
        self._one(plan, "column-vs-literal only")

    def test_bare_utf8_literal_projection(self):
        plan = Projection([lit_s("x")], scan(),
                          Schema([Field("lit", DataType.UTF8)]))
        self._one(plan, "bare string literals")

    def test_utf8_arithmetic(self):
        plan = Projection(
            [BinaryExpr(Column(0), Operator.Plus, lit_s("x"))], scan(),
            Schema([Field("y", DataType.UTF8)]),
        )
        self._one(plan, "not defined on Utf8")

    def test_no_common_supertype(self):
        plan = Projection(
            [BinaryExpr(Column(3), Operator.Plus, lit_i(1))], scan(),
            Schema([Field("y", DataType.INT64)]),
        )
        self._one(plan, "no common supertype")

    def test_boolean_operand_not_boolean(self):
        plan = Selection(
            BinaryExpr(Column(2), Operator.And, Column(3)), scan()
        )
        self._one(plan, "expected Boolean")

    def test_utf8_cast(self):
        plan = Projection([Cast(Column(0), DataType.INT64)], scan(),
                          Schema([Field("cast", DataType.INT64)]))
        self._one(plan, "CAST Utf8")

    def test_unknown_aggregate(self):
        agg = AggregateFunction("median", [Column(1)], DataType.FLOAT64)
        plan = Aggregate(scan(), [], [agg],
                         Schema([Field("median", DataType.FLOAT64)]))
        self._one(plan, "unknown aggregate")

    def test_aggregate_arity(self):
        agg = AggregateFunction("min", [Column(1), Column(2)],
                                DataType.FLOAT64)
        plan = Aggregate(scan(), [], [agg],
                         Schema([Field("min", DataType.FLOAT64)]))
        self._one(plan, "exactly one argument")

    def test_sum_over_utf8(self):
        agg = AggregateFunction("sum", [Column(0)], DataType.UTF8)
        plan = Aggregate(scan(), [], [agg],
                         Schema([Field("sum", DataType.UTF8)]))
        self._one(plan, "over Utf8")

    def test_min_over_computed_utf8(self):
        # fusibility + executor precondition: Utf8 MIN/MAX needs a column
        agg = AggregateFunction(
            "min", [Cast(Column(0), DataType.UTF8)], DataType.UTF8
        )
        plan = Aggregate(scan(), [], [agg],
                         Schema([Field("min", DataType.UTF8)]))
        self._one(plan, "bare column")

    def test_computed_group_key(self):
        agg = AggregateFunction("count", [Column(2)], DataType.UINT64)
        key = BinaryExpr(Column(2), Operator.Plus, lit_i(1))
        plan = Aggregate(scan(), [key], [agg],
                         Schema([Field("k", DataType.INT64),
                                 Field("count", DataType.UINT64)]))
        self._one(plan, "bare column references")

    def test_count_return_type(self):
        agg = AggregateFunction("count", [Column(2)], DataType.INT64)
        plan = Aggregate(scan(), [], [agg],
                         Schema([Field("count", DataType.INT64)]))
        self._one(plan, "COUNT returns UInt64")

    def test_aggregate_return_type_mismatch(self):
        agg = AggregateFunction("min", [Column(1)], DataType.INT64)
        plan = Aggregate(scan(), [], [agg],
                         Schema([Field("min", DataType.INT64)]))
        self._one(plan, "argument computes Float64")

    def test_declared_schema_arity_mismatch(self):
        plan = Projection([Column(1)], scan(),
                          Schema([Field("a", DataType.FLOAT64),
                                  Field("b", DataType.INT64)]))
        self._one(plan, "declared schema has 2 field(s)")

    def test_declared_dtype_mismatch(self):
        # the malformed-dtype query: schema says Int64, expr computes f64
        plan = Projection([Column(1)], scan(),
                          Schema([Field("lat", DataType.INT64)]))
        self._one(plan, "declared field 0")

    def test_non_column_sort_key(self):
        key = SortExpr(BinaryExpr(Column(2), Operator.Plus, lit_i(1)), True)
        plan = Sort([key], scan(), SCHEMA)
        self._one(plan, "ORDER BY keys must be bare column")

    def test_negative_limit(self):
        self._one(Limit(-1, scan(), SCHEMA), "non-negative")

    def test_aggregate_in_scalar_context(self):
        agg = AggregateFunction("min", [Column(1)], DataType.FLOAT64)
        plan = Selection(
            BinaryExpr(agg, Operator.Gt, Literal(ScalarValue.float64(0.0))),
            scan(),
        )
        self._one(plan, "outside an Aggregate operator")

    def test_udf_signature_checks(self, ctx):
        import jax.numpy as jnp

        ctx.register_udf("twice", [DataType.FLOAT64], DataType.FLOAT64,
                         jax_fn=lambda x: x * jnp.float64(2))
        # unknown function
        plan = Projection(
            [ScalarFunction("nosuch", [Column(1)], DataType.FLOAT64)],
            scan(), Schema([Field("nosuch", DataType.FLOAT64)]),
        )
        self._one(plan, "unknown function", functions=ctx.functions)
        # arity
        plan = Projection(
            [ScalarFunction("twice", [Column(1), Column(1)],
                            DataType.FLOAT64)],
            scan(), Schema([Field("twice", DataType.FLOAT64)]),
        )
        self._one(plan, "expects 1 argument", functions=ctx.functions)
        # argument dtype: Utf8 cannot coerce to Float64
        plan = Projection(
            [ScalarFunction("twice", [Column(0)], DataType.FLOAT64)],
            scan(), Schema([Field("twice", DataType.FLOAT64)]),
        )
        self._one(plan, "no implicit coercion", functions=ctx.functions)
        # declared return type disagrees with the registry
        plan = Projection(
            [ScalarFunction("twice", [Column(1)], DataType.INT64)],
            scan(), Schema([Field("twice", DataType.INT64)]),
        )
        self._one(plan, "registry says", functions=ctx.functions)


class TestEngineWiring:
    def test_execute_rejects_at_plan_time(self, ctx):
        bad = Projection([Column(9)], scan(),
                         Schema([Field("x", DataType.INT64)]))
        with pytest.raises(PlanVerificationError) as ei:
            ctx.execute(bad)
        # typed AND non-transient: failover must not retry an invalid plan
        assert not isinstance(ei.value, TransientError)
        assert isinstance(ei.value, PlanError)
        assert isinstance(ei.value, NotSupportedError)
        assert ei.value.diagnostics

    def test_verify_off_is_passthrough(self, ctx, monkeypatch):
        from datafusion_tpu.errors import DataFusionError

        monkeypatch.setenv("DATAFUSION_TPU_VERIFY", "0")
        bad = Projection([Column(9)], scan(),
                         Schema([Field("x", DataType.INT64)]))
        with pytest.raises(DataFusionError) as ei:
            from datafusion_tpu.exec.materialize import collect

            collect(ctx.execute(bad))
        assert not isinstance(ei.value, PlanVerificationError)

    def test_verify_off_matches_verified_results(self, ctx, monkeypatch):
        sql = ("SELECT city, MIN(lat), COUNT(pop) FROM t "
               "WHERE pop > 100 GROUP BY city")
        rows_on = ctx.sql_collect(sql).to_rows()
        monkeypatch.setenv("DATAFUSION_TPU_VERIFY", "0")
        rows_off = ctx.sql_collect(sql).to_rows()
        assert rows_on == rows_off

    def test_explain_verify_renders_schema_per_operator(self, ctx):
        out = ctx.sql("EXPLAIN VERIFY SELECT city, MIN(lat) FROM t "
                      "GROUP BY city ORDER BY city LIMIT 1")
        text = repr(out)
        assert out.ok
        assert "plan verified: OK" in text
        assert "city: Utf8" in text
        assert "MIN: Float64" in text
        # one inferred-schema line per operator in the tree
        assert text.count("::") == len(out.report.operators)

    def test_explain_verify_reports_failure_without_executing(self, ctx):
        # the planner accepts Utf8-vs-Utf8 (supertype exists); the
        # verifier catches the unsupported col-vs-col comparison shape
        out = ctx.sql("EXPLAIN VERIFY SELECT city FROM t WHERE city < city")
        assert not out.ok
        assert "FAILED" in repr(out)

    def test_sql_query_rejected_at_plan_time(self, ctx):
        with pytest.raises(PlanVerificationError):
            ctx.sql_collect("SELECT city FROM t WHERE city < city")

    def test_coordinator_rejects_fragment_plan(self, ctx):
        from datafusion_tpu.parallel.coordinator import _check_fragment_plan
        from datafusion_tpu.utils.metrics import METRICS

        bad = Projection([Column(9)], scan(),
                         Schema([Field("x", DataType.INT64)]))
        before = METRICS.counts.get("coord.plan_rejected", 0)
        with pytest.raises(PlanVerificationError):
            _check_fragment_plan(bad)
        assert METRICS.counts.get("coord.plan_rejected", 0) == before + 1
        # a good plan passes without counting
        _check_fragment_plan(scan())
        assert METRICS.counts.get("coord.plan_rejected", 0) == before + 1


# ------------------------------------------------------------------ linter


def _lint(src, relpath="datafusion_tpu/exec/fused.py"):
    return lint.lint_source(src, relpath)


class TestLintRules:
    def test_df001_host_sync(self):
        src = "import jax\ndef f(x):\n    return jax.block_until_ready(x)\n"
        found = _lint(src, "datafusion_tpu/exec/aggregate.py")
        assert [f.rule for f in found] == ["DF001"]
        # outside exec/: not a dispatch path
        assert _lint(src, "datafusion_tpu/cli.py") == []

    def test_df001_asarray_only_in_fused(self):
        src = "import numpy as np\ndef f(x):\n    return np.asarray(x)\n"
        assert [f.rule for f in _lint(src)] == ["DF001"]
        assert _lint(src, "datafusion_tpu/exec/sort.py") == []

    def test_df002_wall_clock_in_replayable(self):
        src = (
            "import time, random\n"
            "from datafusion_tpu.testing import faults\n"
            "def replay():\n"
            "    faults.check('site')\n"
            "    t = time.time()\n"
            "    r = random.random()\n"
            "    time.monotonic(); time.sleep(0)\n"
            "    return t, r\n"
            "def free():\n"
            "    return time.time()\n"
        )
        found = _lint(src, "datafusion_tpu/x.py")
        assert [f.rule for f in found] == ["DF002", "DF002"]
        assert found[0].line == 5 and found[1].line == 6

    def test_df003_raw_socket_io(self):
        src = (
            "def bad(sock):\n"
            "    sock.sendall(b'x')\n"
            "def good(sock):\n"
            "    from datafusion_tpu.testing import faults\n"
            "    faults.check('my.site')\n"
            "    sock.sendall(b'x')\n"
        )
        found = _lint(src, "datafusion_tpu/x.py")
        assert [(f.rule, f.line) for f in found] == [("DF003", 2)]

    def test_df004_broad_except(self):
        src = (
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except:\n"
            "        pass\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        pass\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        raise\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:  # noqa: BLE001 — justified\n"
            "        pass\n"
            "    try:\n"
            "        g()\n"
            "    except ValueError:\n"
            "        pass\n"
        )
        found = _lint(src, "datafusion_tpu/x.py")
        assert [(f.rule, f.line) for f in found] == [("DF004", 4),
                                                    ("DF004", 8)]

    def test_df005_lock_in_metrics(self):
        src = (
            "import threading\n"
            "class Metrics:\n"
            "    def add(self, n):\n"
            "        with self._lock:\n"
            "            pass\n"
        )
        found = _lint(src, "datafusion_tpu/utils/metrics.py")
        assert {f.rule for f in found} == {"DF005"}
        # same code outside the metrics/stats scope is fine
        assert _lint(src, "datafusion_tpu/cache/store.py") == []

    def test_df008_disk_io_under_lock_in_control_plane(self):
        src = (
            "import os\n"
            "class Node:\n"
            "    def bad(self):\n"
            "        with self._lock:\n"
            "            os.fsync(3)\n"
            "            open('/tmp/x', 'wb')\n"
            "            self._wal_sync()\n"
            "    def good(self):\n"
            "        with self._lock:\n"
            "            tail = list(self._events)\n"
            "        self._wal_sync()\n"
        )
        found = _lint(src, "datafusion_tpu/cluster/service.py")
        assert [(f.rule, f.line) for f in found] == [
            ("DF008", 5), ("DF008", 6), ("DF008", 7)]
        # the WAL module is the reviewed disk-IO boundary: exempt
        assert _lint(src, "datafusion_tpu/utils/wal.py") == []
        # outside the durability surfaces the rule does not apply
        assert _lint(src, "datafusion_tpu/cache/store.py") == []

    def test_df008_disk_io_in_lockfree_metrics(self):
        src = (
            "class Metrics:\n"
            "    def add(self, name):\n"
            "        open('/tmp/x', 'wb')\n"
        )
        found = _lint(src, "datafusion_tpu/utils/metrics.py")
        assert [f.rule for f in found] == ["DF008"]

    def test_suppression_marker(self):
        src = ("import jax\ndef f(x):\n"
               "    return jax.block_until_ready(x)  "
               "# df-lint: ok(DF001) — probe\n")
        assert _lint(src, "datafusion_tpu/exec/batch.py") == []
        # a marker for a DIFFERENT rule does not suppress
        src2 = ("import jax\ndef f(x):\n"
                "    return jax.block_until_ready(x)  "
                "# df-lint: ok(DF004)\n")
        assert [f.rule for f in
                _lint(src2, "datafusion_tpu/exec/batch.py")] == ["DF001"]

    def test_syntax_error_is_a_finding(self):
        found = _lint("def f(:\n", "datafusion_tpu/x.py")
        assert [f.rule for f in found] == ["DF000"]

    def test_self_lint_is_clean(self):
        pkg = os.path.join(REPO, "datafusion_tpu")
        findings = lint.lint_paths([pkg])
        assert findings == [], "\n".join(f.text() for f in findings)

    def test_github_format(self):
        f = lint.Finding("DF001", "a.py", 3, 1, "msg")
        assert f.github() == "::error file=a.py,line=3,col=1::DF001 msg"


# --------------------------------------------------------------- lockcheck


class TestLockcheck:
    def test_inverted_two_lock_fixture_cycles(self):
        reg = lockcheck.Registry()
        a = lockcheck.TrackedLock("store", reg)
        b = lockcheck.TrackedLock("publisher", reg)
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        cycles = reg.cycles()
        assert len(cycles) == 1
        assert set(cycles[0]) == {"store", "publisher"}
        rep = reg.report()
        assert len(rep["cycles"]) == 1
        assert all(e["site"] for e in rep["cycles"][0]["edges"])

    def test_consistent_order_is_clean(self):
        reg = lockcheck.Registry()
        a = lockcheck.TrackedLock("a", reg)
        b = lockcheck.TrackedLock("b", reg)
        for _ in range(3):
            with a, b:
                pass
        assert reg.cycles() == []
        assert reg.ok

    def test_three_lock_cycle(self):
        reg = lockcheck.Registry()
        locks = {n: lockcheck.TrackedLock(n, reg) for n in "abc"}
        for pair in (("a", "b"), ("b", "c"), ("c", "a")):
            with locks[pair[0]]:
                with locks[pair[1]]:
                    pass
        cycles = reg.cycles()
        assert len(cycles) == 1
        assert set(cycles[0]) == {"a", "b", "c"}

    def test_blocking_call_while_holding_lock(self):
        reg = lockcheck.Registry()
        a = lockcheck.TrackedLock("store", reg)
        reg.note_blocking("wire.recv")  # holding nothing: clean
        assert reg.report()["blocking"] == []
        with a:
            reg.note_blocking("wire.recv")
        rep = reg.report()
        assert [(b["op"], b["held"]) for b in rep["blocking"]] == [
            ("wire.recv", "store")
        ]
        assert not reg.ok

    def test_try_acquire_records_no_edges(self):
        reg = lockcheck.Registry()
        a = lockcheck.TrackedLock("a", reg)
        b = lockcheck.TrackedLock("b", reg)
        with a:
            assert b.acquire(blocking=False)
            b.release()
        assert reg.edges == {}

    def test_condition_compatible(self):
        reg = lockcheck.Registry()
        lk = lockcheck.TrackedLock("cond", reg)
        cond = threading.Condition(lk)
        with cond:
            assert reg.held() == ["cond"]
            cond.wait(timeout=0.01)
            assert reg.held() == ["cond"]
        assert reg.held() == []

    def test_non_lifo_release(self):
        reg = lockcheck.Registry()
        a = lockcheck.TrackedLock("a", reg)
        b = lockcheck.TrackedLock("b", reg)
        a.acquire()
        b.acquire()
        a.release()  # out of order
        assert reg.held() == ["b"]
        b.release()
        assert reg.held() == []

    def test_make_lock_plain_when_disabled(self, monkeypatch):
        monkeypatch.setattr(lockcheck, "_ENABLED", False)
        lk = lockcheck.make_lock("x")
        assert isinstance(lk, type(threading.Lock()))
        monkeypatch.setattr(lockcheck, "_ENABLED", True)
        lk = lockcheck.make_lock("x")
        assert isinstance(lk, lockcheck.TrackedLock)

    def test_cross_thread_inversion_detected(self):
        # the two orders happen on DIFFERENT threads (no deadlock this
        # run — the graph still records the hazard)
        reg = lockcheck.Registry()
        a = lockcheck.TrackedLock("a", reg)
        b = lockcheck.TrackedLock("b", reg)

        def order_ab():
            with a:
                with b:
                    pass

        def order_ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=order_ab)
        t1.start()
        t1.join()
        order_ba()
        assert len(reg.cycles()) == 1

    def test_dns_resolve_is_a_noted_blocking_site(self, monkeypatch):
        # the regression fixed this PR: DNS under coord.workers (the
        # pre-warm in _fold_view_workers keeps resolution outside the
        # lock; this pins the detector that caught it)
        monkeypatch.setattr(lockcheck, "_ENABLED", True)
        from datafusion_tpu.parallel import coordinator as co

        reg = lockcheck.Registry()
        monkeypatch.setattr(lockcheck, "GLOBAL", reg)
        co._resolve_addr.cache_clear()
        lk = lockcheck.TrackedLock("coord.workers", reg)
        with lk:
            co._resolve_addr("127.0.0.1:1234")
        assert [(b["op"], b["held"]) for b in reg.report()["blocking"]] == [
            ("dns.resolve", "coord.workers")
        ]
        co._resolve_addr.cache_clear()


# ------------------------------------------------------- CLI / report glue


class TestAnalysisCli:
    def test_lint_cli_clean_package(self, capsys):
        from datafusion_tpu.analysis.__main__ import main

        rc = main([os.path.join(REPO, "datafusion_tpu")])
        assert rc == 0
        assert "0 finding(s)" in capsys.readouterr().out

    def test_lint_cli_github_format(self, tmp_path, capsys):
        from datafusion_tpu.analysis.__main__ import main

        bad = tmp_path / "datafusion_tpu" / "exec"
        bad.mkdir(parents=True)
        f = bad / "fused.py"
        f.write_text("import numpy as np\ndef g(x):\n"
                     "    return np.asarray(x)\n")
        rc = main([str(f), "--format=github"])
        out = capsys.readouterr().out
        assert rc == 1 and "::error file=" in out

    def test_lockcheck_report_evaluation(self, tmp_path, capsys):
        import json

        from datafusion_tpu.analysis.__main__ import main

        reg = lockcheck.Registry()
        a = lockcheck.TrackedLock("a", reg)
        b = lockcheck.TrackedLock("b", reg)
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        path = tmp_path / "lockcheck.json"
        path.write_text(json.dumps(reg.report()))
        assert main(["--lockcheck-report", str(path)]) == 1
        assert "lock-order cycle" in capsys.readouterr().out
        clean = tmp_path / "clean.json"
        clean.write_text(json.dumps(lockcheck.Registry().report()))
        assert main(["--lockcheck-report", str(clean)]) == 0
