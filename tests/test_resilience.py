"""Gray-failure resilience suite: hedged dispatch, retry budgets,
per-target circuit breakers, and degraded-mode serving.

Crash failures are covered by the chaos suite (test_faults.py); this
suite covers the *alive-but-slow* class — seeded latency faults
(`delay` rules with ranges), hedge-dedup correctness (hedged winner +
late loser merge exactly once), retry-budget exhaustion under a fault
storm, breaker open/half-open/close transitions including concurrent
probes, the cluster client's blackholed-endpoint sweep classification,
the heartbeat keep-alive channel, and every degraded-mode flag's
appearance in ``metrics_text()``.
"""

from __future__ import annotations

import socket
import threading
import time

import numpy as np
import pytest

from datafusion_tpu.cluster.client import _ClientApi
from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.errors import DeviceTransientError, ExecutionError
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.parallel.coordinator import DistributedContext
from datafusion_tpu.parallel.worker import serve
from datafusion_tpu.testing import faults
from datafusion_tpu.utils import breaker as breaker_mod
from datafusion_tpu.utils import hedge as hedge_mod
from datafusion_tpu.utils import retry
from datafusion_tpu.utils.metrics import METRICS

SCHEMA = Schema(
    [
        Field("region", DataType.UTF8, False),
        Field("v", DataType.INT64, False),
        Field("x", DataType.FLOAT64, True),
    ]
)

SQL = ("SELECT region, COUNT(1), SUM(v), MIN(v), MAX(v), MIN(x), MAX(x) "
       "FROM t GROUP BY region")


def _write_partitions(tmp_path, n_parts=3, rows_per=200):
    rng = np.random.default_rng(31)
    regions = ["north", "south", "east", "west"]
    paths = []
    for p in range(n_parts):
        path = tmp_path / f"part{p}.csv"
        with open(path, "w", encoding="utf-8") as f:
            f.write("region,v,x\n")
            for _ in range(rows_per):
                f.write(f"{regions[rng.integers(0, 4)]},"
                        f"{int(rng.integers(-1000, 1000))},"
                        f"{rng.uniform(-5, 5):.6f}\n")
        paths.append(str(path))
    return paths


def _register(ctx, paths):
    from datafusion_tpu.exec.datasource import CsvDataSource
    from datafusion_tpu.parallel.partition import PartitionedDataSource

    ctx.register_datasource("t", PartitionedDataSource(
        [CsvDataSource(p, SCHEMA, True, 131072) for p in paths]))
    return ctx


def _rows(ctx):
    return sorted(collect(ctx.sql(SQL)).to_rows())


def _count(name):
    return METRICS.counts.get(name, 0)


@pytest.fixture()
def inproc_workers():
    """Two in-process workers over real TCP sockets (the chaos-smoke
    deployment shape: hermetic, but the wire/dispatch paths are real)."""
    servers, addrs = [], []
    for _ in range(2):
        server = serve("127.0.0.1:0", device="cpu")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        servers.append(server)
        addrs.append(server.server_address[:2])
    yield addrs
    for s in servers:
        s.shutdown()
        s.server_close()


@pytest.fixture()
def breakers_on():
    """Arm breakers for one test; fresh registry both sides."""
    breaker_mod.configure(True)
    breaker_mod.reset()
    yield
    breaker_mod.configure(None)
    breaker_mod.reset()


# -- circuit breaker state machine ------------------------------------

class TestCircuitBreaker:
    def test_consecutive_failures_open(self):
        b = breaker_mod.CircuitBreaker("t", failures=3, open_s=60.0)
        for _ in range(2):
            b.record(False)
        assert b.state == "closed" and b.allow()
        b.record(False)
        assert b.state == "open"
        assert not b.allow() and b.denies()

    def test_success_resets_the_streak(self):
        b = breaker_mod.CircuitBreaker("t", failures=3, window=100,
                                       ratio=1.1, open_s=60.0)
        for _ in range(10):
            b.record(False)
            b.record(False)
            b.record(True)
        assert b.state == "closed"

    def test_ratio_over_full_window_opens(self):
        b = breaker_mod.CircuitBreaker("t", failures=100, window=10,
                                       ratio=0.5, open_s=60.0)
        # alternate: never 100 consecutive, but 50% of a full window
        for i in range(10):
            b.record(i % 2 == 0)
        assert b.state == "open"

    def test_half_open_probe_then_close(self):
        now = [0.0]
        b = breaker_mod.CircuitBreaker("t", failures=1, open_s=5.0,
                                       half_open_probes=1,
                                       now=lambda: now[0])
        b.record(False)
        assert b.state == "open" and not b.allow()
        now[0] = 6.0
        assert b.allow()  # cool-down lapsed: half-open, probe admitted
        assert b.state == "half_open"
        assert not b.allow()  # concurrent probe capped
        b.record(True)
        assert b.state == "closed" and b.allow()

    def test_half_open_probe_failure_reopens(self):
        now = [0.0]
        b = breaker_mod.CircuitBreaker("t", failures=1, open_s=5.0,
                                       now=lambda: now[0])
        b.record(False)
        now[0] = 6.0
        assert b.allow()
        b.record(False)
        assert b.state == "open"
        assert not b.allow()  # cool-down re-armed at t=6
        now[0] = 12.0
        assert b.allow() and b.state == "half_open"

    def test_concurrent_probes_bounded(self):
        now = [10.0]
        b = breaker_mod.CircuitBreaker("t", failures=1, open_s=1.0,
                                       half_open_probes=2,
                                       now=lambda: now[0])
        b.record(False)
        now[0] = 20.0
        results = []
        barrier = threading.Barrier(4)

        def probe():
            barrier.wait(timeout=5)
            results.append(b.allow())

        threads = [threading.Thread(target=probe) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5)
        assert sum(results) == 2  # exactly half_open_probes admitted

    def test_late_loser_report_after_open_is_dropped(self):
        b = breaker_mod.CircuitBreaker("t", failures=1, open_s=60.0)
        b.record(False)
        assert b.state == "open"
        b.record(True)  # a request that started before the open
        assert b.state == "open"  # not corrupted into closed

    def test_cooled_open_closes_via_record_without_allow(self):
        """Peek-style consumers (the cluster sweep) never reserve via
        allow(); their post-cool-down outcome must still count as the
        probe, or an open endpoint breaker could never close."""
        now = [0.0]
        b = breaker_mod.CircuitBreaker("t", failures=1, open_s=5.0,
                                       now=lambda: now[0])
        b.record(False)
        assert b.state == "open"
        now[0] = 6.0
        assert not b.denies()  # cooled: the sweep may attempt it
        b.record(True)
        assert b.state == "closed"

    def test_registry_bounded_against_worker_churn(self, breakers_on,
                                                   monkeypatch):
        """Ephemeral-port worker restarts mint fresh breaker names;
        the registry evicts closed (evidence-free) entries at the cap
        and keeps mid-incident ones."""
        import datafusion_tpu.utils.breaker as bm

        monkeypatch.setattr(bm, "_REGISTRY_MAX", 4)
        incident = breaker_mod.breaker_for("worker:h:0")
        for _ in range(incident.failures):
            incident.record(False)
        assert incident.state == "open"
        for i in range(1, 12):
            breaker_mod.breaker_for(f"worker:h:{i}")
        assert len(bm._REGISTRY) <= 4
        assert "worker:h:0" in bm._REGISTRY  # live evidence survives

    def test_registry_disabled_and_gauges(self):
        breaker_mod.configure(False)
        try:
            assert breaker_mod.breaker_for("x") is None
        finally:
            breaker_mod.configure(None)
        breaker_mod.configure(True)
        try:
            breaker_mod.reset()
            b = breaker_mod.breaker_for("worker:h:1")
            assert b is breaker_mod.breaker_for("worker:h:1")
            b.record(False)
            for _ in range(10):
                b.record(False)
            assert breaker_mod.gauges()["breaker.worker:h:1.state"] == 2
        finally:
            breaker_mod.configure(None)
            breaker_mod.reset()


# -- retry budget -----------------------------------------------------

class TestRetryBudget:
    def test_bucket_semantics(self):
        rb = retry.RetryBudget(0.5, burst=2.0)
        rb.earn()  # 1.0 + 0.5 = 1.5
        assert rb.spend()  # 0.5 left
        assert not rb.spend()
        rb.earn()  # 1.0
        assert rb.spend()

    def test_token_bucket_never_over_grants_concurrently(self):
        """N threads racing one bucket must get exactly `tokens`
        grants — an unlocked bucket over-grants during the correlated
        storm the budget exists to bound."""
        from datafusion_tpu.utils.retry import TokenBucket

        bucket = TokenBucket(0.0, burst=8.0, initial=8.0)
        granted = []
        barrier = threading.Barrier(16)

        def spender():
            barrier.wait(timeout=10)
            granted.append(sum(bucket.spend() for _ in range(4)))

        threads = [threading.Thread(target=spender) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert sum(granted) == 8

    def test_device_call_denied_fails_fast(self):
        retry.set_retry_budget(retry.RetryBudget(0.0, burst=0.0))
        base = _count("device.retry_budget_exhausted")
        try:
            with faults.scoped({"rules": [
                {"site": "device.call", "op": "raise",
                 "exc": "DeviceTransientError", "count": 0},
            ]}):
                with pytest.raises(DeviceTransientError):
                    retry.device_call(lambda: 1)
        finally:
            retry.set_retry_budget(None)
        assert _count("device.retry_budget_exhausted") == base + 1
        assert _count("retry.budget_denied") >= 1

    def test_device_call_within_budget_retries(self, monkeypatch):
        monkeypatch.setattr(retry, "_BASE_S", 0.001)
        monkeypatch.setattr(retry, "_CAP_S", 0.002)
        retry.set_retry_budget(retry.RetryBudget(1.0, burst=4.0))
        base = _count("retry.budget_spent")
        try:
            with faults.scoped({"rules": [
                {"site": "device.call", "op": "raise",
                 "exc": "DeviceTransientError", "count": 2},
            ]}):
                assert retry.device_call(lambda: 41) == 41
        finally:
            retry.set_retry_budget(None)
        assert _count("retry.budget_spent") == base + 2

    def test_retry_volume_bounded_under_fault_storm(self, monkeypatch):
        """30% injected transient faults: total retries stay within the
        configured budget ratio (the smooth-degradation acceptance
        gate, asserted from the metrics)."""
        monkeypatch.setattr(retry, "_BASE_S", 0.0001)
        monkeypatch.setattr(retry, "_CAP_S", 0.0002)
        ratio = 0.2
        retry.set_retry_budget(retry.RetryBudget(ratio, burst=1.0))
        first0 = _count("retry.first_attempts")
        spent0 = _count("retry.budget_spent")
        failures = 0
        try:
            with faults.scoped({"seed": 11, "rules": [
                {"site": "device.call", "op": "raise",
                 "exc": "DeviceTransientError", "p": 0.3, "count": 0},
            ]}):
                for _ in range(200):
                    try:
                        retry.device_call(lambda: 1)
                    except DeviceTransientError:
                        failures += 1
        finally:
            retry.set_retry_budget(None)
        first = _count("retry.first_attempts") - first0
        spent = _count("retry.budget_spent") - spent0
        assert first == 200
        # retries never exceed ratio * first attempts + the burst
        assert spent <= ratio * first + 1.0
        assert failures > 0  # denied retries failed fast, not retried

    def test_dispatch_reassignment_consumes_the_budget(
            self, tmp_path, inproc_workers):
        """An empty budget converts fragment-reassignment storms into
        prompt failures; the same scenario recovers with no budget."""
        paths = _write_partitions(tmp_path)
        want = _rows(_register(ExecutionContext(device="cpu"), paths))
        plan = {"rules": [
            {"site": "worker.fragment", "op": "raise",
             "exc": "InjectedConnectionAbort", "count": 1},
        ]}
        retry.set_retry_budget(retry.RetryBudget(0.0, burst=0.0))
        base = _count("coord.reassign_budget_denied")
        try:
            ctx = _register(DistributedContext(inproc_workers,
                                               result_cache=False), paths)
            with faults.scoped(plan):
                with pytest.raises(ExecutionError):
                    _rows(ctx)
            assert _count("coord.reassign_budget_denied") == base + 1
        finally:
            retry.set_retry_budget(None)
        # unbudgeted (the default): the reassignment replays and heals
        ctx = _register(DistributedContext(inproc_workers,
                                           result_cache=False), paths)
        with faults.scoped(plan):
            assert _rows(ctx) == want


# -- hedge tracker ----------------------------------------------------

class TestHedgeTracker:
    def test_threshold_floor_then_history(self):
        h = hedge_mod.HedgeTracker(factor=2.0, floor_s=0.1, min_samples=2)
        assert h.threshold_s("w") == 0.1  # no history: floor
        h.observe("w", 1.0)
        h.observe("w", 1.0)
        # log2 histogram quantile is a bucket upper bound (>= 1.0)
        assert h.threshold_s("w") >= 2.0
        assert h.ewma["w"] == 1.0

    def test_fleet_history_backfills_new_workers(self):
        h = hedge_mod.HedgeTracker(factor=1.0, floor_s=0.001, min_samples=2)
        h.observe("a", 0.5)
        h.observe("b", 0.5)
        assert h.threshold_s("never-seen") >= 0.5  # fleet histogram

    def test_hedge_token_budget(self):
        h = hedge_mod.HedgeTracker(ratio=0.5, burst=2.0)
        assert h.try_hedge()  # the initial token
        assert not h.try_hedge()
        for _ in range(2):
            h.observe_dispatch()
        assert h.try_hedge()
        assert not h.try_hedge()

    def test_refund_returns_a_spent_token(self):
        h = hedge_mod.HedgeTracker(ratio=0.0, burst=2.0)
        assert h.try_hedge()
        assert not h.try_hedge()
        h.refund()  # approved hedge never launched (no target)
        assert h.try_hedge()

    def test_from_env_default_off(self, monkeypatch):
        monkeypatch.delenv("DATAFUSION_TPU_HEDGE", raising=False)
        assert hedge_mod.from_env() is None
        monkeypatch.setenv("DATAFUSION_TPU_HEDGE", "1")
        monkeypatch.setenv("DATAFUSION_TPU_HEDGE_FLOOR_S", "0.125")
        t = hedge_mod.from_env()
        assert t is not None and t.floor_s == 0.125


# -- hedged dispatch (the chaos leg) ----------------------------------

class TestHedgedDispatch:
    def test_hedged_winner_and_late_loser_merge_exactly_once(
            self, tmp_path, inproc_workers):
        """A seeded `worker.fragment` delay makes the primary crawl;
        the hedge fires, wins, and the loser's late (identical)
        response is discarded — the merged result equals the fault-free
        run with zero duplicate merges."""
        paths = _write_partitions(tmp_path)
        want = _rows(_register(ExecutionContext(device="cpu"), paths))
        tracker = hedge_mod.HedgeTracker(floor_s=0.05, min_samples=10**6)
        ctx = _register(DistributedContext(inproc_workers, hedge=tracker,
                                           result_cache=False), paths)
        won0 = _count("coord.hedges_won")
        dup0 = _count("coord.duplicate_responses_dropped")
        with faults.scoped({"rules": [
            {"site": "worker.fragment", "op": "delay", "seconds": 0.6,
             "where": {"shard": 0}, "count": 1},
        ]}):
            assert _rows(ctx) == want
        assert _count("coord.hedges_won") == won0 + 1
        assert _count("coord.duplicate_responses_dropped") == dup0
        # let the abandoned loser finish its 0.6s sleep, then prove the
        # healed path still agrees (no leaked state, no double-merge)
        time.sleep(0.7)
        assert _rows(ctx) == want

    def test_hedge_suppressed_without_alternative(self, tmp_path):
        server = serve("127.0.0.1:0", device="cpu")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            paths = _write_partitions(tmp_path, n_parts=2)
            tracker = hedge_mod.HedgeTracker(floor_s=0.01,
                                             min_samples=10**6)
            ctx = _register(DistributedContext(
                [server.server_address[:2]], hedge=tracker,
                result_cache=False), paths)
            d0 = _count("coord.hedges_dispatched")
            want = _rows(_register(ExecutionContext(device="cpu"), paths))
            assert _rows(ctx) == want
            assert _count("coord.hedges_dispatched") == d0  # nobody to hedge to
        finally:
            server.shutdown()
            server.server_close()

    def test_seeded_delay_range_drives_hedges(self, tmp_path,
                                              inproc_workers):
        """Latency faults with a [lo, hi] range: the gray-failure soak
        shape — every delayed fragment still merges exactly once."""
        paths = _write_partitions(tmp_path)
        want = _rows(_register(ExecutionContext(device="cpu"), paths))
        tracker = hedge_mod.HedgeTracker(floor_s=0.05, min_samples=10**6,
                                         ratio=1.0, burst=8.0)
        ctx = _register(DistributedContext(inproc_workers, hedge=tracker,
                                           result_cache=False), paths)
        with faults.scoped({"seed": 7, "rules": [
            {"site": "worker.fragment", "op": "delay",
             "seconds": [0.3, 0.5], "count": 2},
        ]}):
            assert _rows(ctx) == want

    def test_breaker_open_worker_skipped(self, tmp_path, inproc_workers,
                                         breakers_on):
        """An open breaker takes a worker out of the pick rotation
        while an alternative exists — the query routes around the sick
        target without paying its timeout."""
        paths = _write_partitions(tmp_path)
        want = _rows(_register(ExecutionContext(device="cpu"), paths))
        (h0, p0), _ = inproc_workers
        b = breaker_mod.breaker_for(f"worker:{h0}:{p0}")
        for _ in range(b.failures):
            b.record(False)
        assert b.state == "open"
        skips0 = _count("coord.breaker_skips")
        ctx = _register(DistributedContext(inproc_workers,
                                           result_cache=False), paths)
        assert _rows(ctx) == want
        assert _count("coord.breaker_skips") > skips0


# -- degraded-mode serving -------------------------------------------

class TestDegradedModes:
    def test_stale_view_flag_in_metrics_text(self, tmp_path, monkeypatch,
                                             inproc_workers):
        from datafusion_tpu.cluster import ClusterNode, LocalClusterClient

        monkeypatch.setenv("DATAFUSION_TPU_STALE_VIEW_GRACE_S", "0.05")
        node = ClusterNode()
        client = LocalClusterClient([node])
        ctx = DistributedContext(inproc_workers, cluster=client,
                                 result_cache=False)
        assert 'name="cluster.view_stale"} 0' in ctx.metrics_text()
        node.partitioned = True  # the whole control plane goes dark
        time.sleep(0.08)
        stale0 = _count("coord.membership_went_stale")
        text = ctx.metrics_text()
        assert 'name="cluster.view_stale"} 1' in text
        assert _count("coord.membership_went_stale") == stale0 + 1
        # serving continues off the last-good view the whole time
        node.partitioned = False
        ctx.membership.poll()
        assert 'name="cluster.view_stale"} 0' in ctx.metrics_text()

    def test_shared_tier_open_circuit_serves_local_only(self, breakers_on):
        from datafusion_tpu.cluster.shared_cache import SharedResultTier

        class DeadClient:
            def result_fetch(self, key):
                raise ConnectionRefusedError("service down")

        tier = SharedResultTier(DeadClient())
        b = tier._breaker
        assert b is not None
        for _ in range(b.failures):
            assert tier.load("fp") is None  # errors feed the breaker
        assert b.state == "open"
        ff0 = _count("coord.shared_cache_fast_fails")
        assert tier.load("fp") is None  # fast-fail, no round trip
        assert _count("coord.shared_cache_fast_fails") == ff0 + 1
        # the degraded flag renders in the scrape
        text = ExecutionContext(device="cpu").metrics_text()
        assert 'name="breaker.shared_cache.state"} 2' in text

    def test_shared_tier_decode_error_releases_the_probe(self,
                                                         breakers_on):
        """A malformed reply during the half-open probe must release
        the reserved probe slot (and count as transport-healthy) — a
        leak would wedge the tier in local-only mode forever."""
        from datafusion_tpu.cluster.shared_cache import SharedResultTier

        class WeirdClient:
            mode = "dead"

            def result_fetch(self, key):
                if self.mode == "dead":
                    raise ConnectionRefusedError("service down")
                raise KeyError("malformed entry")

        wc = WeirdClient()
        tier = SharedResultTier(wc)
        b = tier._breaker
        for _ in range(b.failures):
            tier.load("fp")
        assert b.state == "open"
        b._opened_at = b._now() - b.open_s - 1  # cool-down lapsed
        wc.mode = "malformed"
        assert tier.load("fp") is None  # the probe: answered, undecodable
        assert b.state == "closed"  # slot released, circuit closed
        assert tier.load("fp") is None  # loads keep flowing

    def test_local_fallback_serves_when_fleet_is_gone(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.setenv("DATAFUSION_TPU_LOCAL_FALLBACK", "1")
        server = serve("127.0.0.1:0", device="cpu")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        addr = server.server_address[:2]
        paths = _write_partitions(tmp_path, n_parts=2)
        want = _rows(_register(ExecutionContext(device="cpu"), paths))
        ctx = _register(DistributedContext([addr], result_cache=False),
                        paths)
        assert _rows(ctx) == want  # healthy: served remotely
        server.shutdown()
        server.server_close()
        lf0 = _count("coord.local_fallbacks")
        assert _rows(ctx) == want  # fleet dead: served HERE, degraded
        assert _count("coord.local_fallbacks") > lf0

    def test_local_fallback_off_by_default(self, tmp_path, monkeypatch):
        monkeypatch.delenv("DATAFUSION_TPU_LOCAL_FALLBACK", raising=False)
        ctx = DistributedContext([("127.0.0.1", 1)])
        assert ctx._local_worker is None and ctx._local_exec_fn is None


# -- cluster client: sweep classification + heartbeat channel ---------

class _ScriptedClient(_ClientApi):
    """A `_ClientApi` over scripted per-endpoint behaviors: each
    endpoint holds a list of callables consumed one per attempt (the
    last repeats) — sweep-policy tests without sockets."""

    def __init__(self, scripts):
        self.scripts = scripts
        self.calls = [0] * len(scripts)
        self._active = 0

    def _endpoint_count(self):
        return len(self.scripts)

    def _endpoint_index_for(self, addr):
        return int(addr) if addr is not None else None

    def _request_endpoint(self, idx, msg, timeout, bw=None, sent_box=None):
        self.calls[idx] += 1
        step = self.scripts[idx]
        fn = step.pop(0) if len(step) > 1 else step[0]
        return fn()


class TestClientSweep:
    def test_redirect_hint_overrides_timeout_memory(self):
        """One transient timeout on the true primary must not make the
        sweep skip/redirect-ping-pong off the standby until exhaustion:
        a standby naming that endpoint as primary is fresher evidence,
        so the redirect clears its timed-out mark and retries it."""
        from datafusion_tpu.errors import ClusterNotPrimaryError

        def stalled_once_then_ok():
            return {"type": "pong"}

        def stall():
            raise TimeoutError("GC pause")

        def redirect():
            raise ClusterNotPrimaryError("standby", primary="0")

        client = _ScriptedClient([
            [stall, stalled_once_then_ok],  # primary: one stall, then fine
            [redirect],                     # standby: always points at 0
        ])
        out = client.request({"type": "ping"})
        assert out == {"type": "pong"}
        assert client.calls == [2, 1]  # retried the primary, succeeded

    def test_redirect_overrides_an_open_breaker(self, breakers_on):
        """A standby naming an endpoint as primary is fresher evidence
        than that endpoint's open breaker: the redirect must be
        followed, not skip/ping-ponged until the sweep exhausts."""
        from datafusion_tpu.errors import ClusterNotPrimaryError

        def ok():
            return {"type": "pong"}

        def redirect():
            raise ClusterNotPrimaryError("standby", primary="0")

        client = _ScriptedClient([[ok], [redirect]])
        b = breaker_mod.breaker_for("cluster:0")
        for _ in range(b.failures):
            b.record(False)
        assert b.state == "open"
        client._active = 1  # start at the standby
        assert client.request({"type": "ping"}) == {"type": "pong"}
        assert client.calls == [1, 1]

    def test_open_breaker_skips_the_first_attempt_too(self, breakers_on):
        """The cross-request breaker memory must apply from a sweep's
        FIRST lap — an open starting endpoint is routed around, not
        probed at full timeout cost on every fresh request."""
        def must_not_run():
            raise AssertionError("open-circuited endpoint was dialed")

        def ok():
            return {"type": "pong"}

        client = _ScriptedClient([[must_not_run], [ok]])
        b = breaker_mod.breaker_for("cluster:0")
        for _ in range(b.failures):
            b.record(False)
        assert b.state == "open"
        assert client.request({"type": "ping"}) == {"type": "pong"}
        assert client.calls == [0, 1]
    def test_blackholed_endpoint_skipped_within_sweep(self):
        from datafusion_tpu.cluster.client import ClusterClient

        blackhole = socket.socket()
        blackhole.bind(("127.0.0.1", 0))
        blackhole.listen(1)  # accepts, never answers: pure blackhole
        bh_port = blackhole.getsockname()[1]
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]  # released: instant refusal
        try:
            client = ClusterClient(
                f"127.0.0.1:{bh_port},127.0.0.1:{dead_port}",
                request_timeout=0.3)
            skips0 = _count("cluster.client_timeout_skips")
            t0 = time.monotonic()
            with pytest.raises((ConnectionError, OSError)):
                client.request({"type": "ping"})
            elapsed = time.monotonic() - t0
            # the blackhole's timeout was paid ONCE; later sweep laps
            # skipped it instead of re-paying 0.3s each
            assert _count("cluster.client_timeout_skips") == skips0 + 2
            assert elapsed < 3.0
        finally:
            blackhole.close()

    def test_heartbeat_rides_a_persistent_channel(self):
        from datafusion_tpu.cluster import connect
        from datafusion_tpu.cluster.service import serve as serve_cluster

        server = serve_cluster("127.0.0.1:0")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            host, port = server.server_address[:2]
            client = connect(f"{host}:{port}")
            g = client.lease_grant(30.0)
            c0 = _count("cluster.heartbeat_channel_connects")
            d0 = _count("cluster.heartbeat_channel_drops")
            for _ in range(3):
                assert client.lease_refresh(g["lease"])["found"]
            # ONE channel pin, then every refresh reuses the socket
            assert _count("cluster.heartbeat_channel_connects") == c0 + 1
            assert _count("cluster.heartbeat_channel_drops") == d0
        finally:
            client.close()
            server.shutdown()
            server.server_close()

    def test_heartbeat_channel_drop_falls_back_to_sweep(self):
        from datafusion_tpu.cluster import connect
        from datafusion_tpu.cluster.service import serve as serve_cluster

        server = serve_cluster("127.0.0.1:0")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        host, port = server.server_address[:2]
        client = connect(f"{host}:{port}")
        g = client.lease_grant(30.0)
        assert client.lease_refresh(g["lease"])["found"]  # pins channel
        server.shutdown()
        server.server_close()
        d0 = _count("cluster.heartbeat_channel_drops")
        with pytest.raises((ConnectionError, OSError)):
            client.lease_refresh(g["lease"])
        assert _count("cluster.heartbeat_channel_drops") == d0 + 1
        client.close()
