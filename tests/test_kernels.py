"""Kernel parity + fused-pass coverage (ISSUE 6).

Three layers, all on the CPU tier-1 backend:

- Pallas kernels against their numpy oracles through the Pallas
  interpreter (`interpret=True` — same kernel code path the TPU runs,
  minus Mosaic lowering).
- The engine's fused-pass mode (`DATAFUSION_TPU_FUSE`, default on)
  against the unfused per-operator path: identical results, fewer
  launches, plan-chain collapse in effect.
- Sort semantics that must survive any backend/kernel swap: stability,
  NaN / signed-zero ordering, multi-key and mixed-dtype keys, and
  high-cardinality group-by exact-key/count parity vs numpy.
"""

from __future__ import annotations

import numpy as np
import pytest

from datafusion_tpu import DataType, ExecutionContext, Field, Schema
from datafusion_tpu.exec.batch import make_host_batch
from datafusion_tpu.exec.datasource import MemoryDataSource
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.utils.metrics import METRICS


def _ctx(schema, columns, validity=None, batch_size=4096, name="t"):
    from datafusion_tpu.exec.batch import StringDictionary

    n = len(columns[0])
    # Utf8 columns travel as dictionary codes (one shared dictionary
    # per column, as a real scan produces)
    dicts = [None] * len(columns)
    cols = []
    for j, c in enumerate(columns):
        c = np.asarray(c)
        if schema.field(j).data_type == DataType.UTF8:
            dicts[j] = StringDictionary()
            c = dicts[j].encode([str(x) for x in c])
        cols.append(c)
    batches = []
    for i in range(0, n, batch_size):
        sl = slice(i, i + batch_size)
        batches.append(make_host_batch(
            schema,
            [c[sl] for c in cols],
            [None if v is None else np.asarray(v)[sl]
             for v in (validity or [None] * len(columns))],
            dicts,
        ))
    ctx = ExecutionContext(device="cpu", result_cache=False)
    ctx.register_datasource(name, MemoryDataSource(schema, batches))
    return ctx


def _rows(ctx, sql):
    return collect(ctx.sql(sql)).to_rows()


# ---------------------------------------------------------------- pallas


class TestPallasKernelParity:
    def test_hash_agg_sum_min_max_parity(self):
        from datafusion_tpu.exec.pallas import hash_agg

        import jax

        rng = np.random.default_rng(7)
        n, g = 6000, 900
        ids = rng.integers(0, g, n).astype(np.int32)
        live = rng.random(n) > 0.15
        for vals in (
            rng.normal(size=n),                                # f64
            rng.integers(-10**6, 10**6, n).astype(np.int64),   # i64
        ):
            for kind in ("sum", "min", "max"):
                got = np.asarray(jax.jit(
                    lambda i, v, l, k=kind: hash_agg.grouped_reduce(
                        i, v, l, g, k, interpret=True
                    )
                )(ids, vals, live))
                want = hash_agg.grouped_reduce_numpy(ids, vals, live, g, kind)
                if vals.dtype.kind == "f":
                    np.testing.assert_allclose(
                        got, want, rtol=1e-12, err_msg=f"{kind}/{vals.dtype}"
                    )
                else:
                    np.testing.assert_array_equal(
                        got, want, err_msg=f"{kind}/{vals.dtype}"
                    )

    def test_hash_agg_empty_groups_keep_identity(self):
        from datafusion_tpu.exec.pallas import hash_agg

        ids = np.zeros(16, np.int32)  # every row hits group 0
        vals = np.arange(16).astype(np.int64)
        live = np.ones(16, bool)
        out = hash_agg.grouped_reduce_numpy(ids, vals, live, 8, "min")
        assert out[0] == 0
        assert (out[1:] == np.iinfo(np.int64).max).all()

    def test_bitonic_argsort_stability_and_sizes(self):
        from datafusion_tpu.exec.pallas import sort_kernel

        rng = np.random.default_rng(11)
        for n in (1, 2, 3, 17, 128, 1000, 2048):
            keys = rng.integers(0, 40, n).astype(np.int64)  # heavy ties
            got = np.asarray(sort_kernel.argsort_i64(keys, interpret=True))
            want = np.argsort(keys, kind="stable")
            np.testing.assert_array_equal(got, want, err_msg=f"n={n}")

    def test_bitonic_multi_key_vs_lexsort(self):
        from datafusion_tpu.exec.pallas import sort_kernel

        rng = np.random.default_rng(13)
        a = rng.integers(0, 6, 700).astype(np.int64)
        b = rng.integers(-50, 50, 700).astype(np.int64)
        c = rng.integers(0, 3, 700).astype(np.int64)
        got = np.asarray(sort_kernel.argsort_multi([a, b, c], interpret=True))
        want = sort_kernel.argsort_numpy([a, b, c])
        np.testing.assert_array_equal(got, want)

    def test_engine_aggregate_under_interpret_kernels(self, monkeypatch):
        # end to end: DATAFUSION_TPU_PALLAS=interpret routes the
        # high-cardinality aggregate through the Pallas hash-agg kernel
        monkeypatch.setenv("DATAFUSION_TPU_PALLAS", "interpret")
        rng = np.random.default_rng(17)
        n, g = 4000, 300
        schema = Schema([
            Field("k", DataType.INT64, False),
            Field("v", DataType.FLOAT64, False),
            Field("w", DataType.INT64, True),
        ])
        k = rng.integers(0, g, n)
        v = rng.normal(size=n)
        w = rng.integers(-9, 9, n)
        wv = rng.random(n) > 0.2
        sql = ("SELECT k, SUM(v), MIN(w), MAX(w), COUNT(w), COUNT(1) "
               "FROM t GROUP BY k")
        got = sorted(_rows(_ctx(schema, [k, v, w], [None, None, wv]), sql))
        monkeypatch.setenv("DATAFUSION_TPU_PALLAS", "0")
        want = sorted(_rows(_ctx(schema, [k, v, w], [None, None, wv]), sql))
        assert len(got) == len(want) == g
        for a, b in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(a, float), np.asarray(b, float), rtol=1e-9
            )

    def test_engine_full_sort_under_interpret_kernels(self, monkeypatch):
        rng = np.random.default_rng(19)
        n = 3000
        schema = Schema([
            Field("a", DataType.INT64, False),
            Field("tag", DataType.INT64, False),
        ])
        a = rng.integers(0, 50, n)
        tag = np.arange(n, dtype=np.int64)
        sql = "SELECT a, tag FROM t ORDER BY a"
        monkeypatch.setenv("DATAFUSION_TPU_PALLAS", "interpret")
        METRICS.reset()
        got = _rows(_ctx(schema, [a, tag], batch_size=n), sql)
        assert METRICS.snapshot()["counts"].get("sort.pallas_runs")
        monkeypatch.setenv("DATAFUSION_TPU_PALLAS", "0")
        want = _rows(_ctx(schema, [a, tag], batch_size=n), sql)
        assert got == want  # incl. tag order: stability parity


# ------------------------------------------------------------ fused pass


class TestFusedPasses:
    def _agg_data(self):
        rng = np.random.default_rng(23)
        n, g = 40_000, 3000  # past DENSE_GROUP_MAX: sort-merge territory
        schema = Schema([
            Field("k", DataType.INT64, False),
            Field("v", DataType.FLOAT64, False),
            Field("w", DataType.INT64, False),
        ])
        cols = [rng.integers(0, g, n), rng.normal(size=n),
                rng.integers(-100, 100, n)]
        return schema, cols, g

    def test_fused_vs_unfused_aggregate_parity(self, monkeypatch):
        schema, cols, g = self._agg_data()
        sql = ("SELECT k, SUM(w), MIN(v), MAX(v), COUNT(1) FROM t "
               "WHERE v > -1.5 GROUP BY k")
        monkeypatch.setenv("DATAFUSION_TPU_FUSE", "1")
        got = sorted(_rows(_ctx(schema, cols), sql))
        monkeypatch.setenv("DATAFUSION_TPU_FUSE", "0")
        want = sorted(_rows(_ctx(schema, cols), sql))
        assert len(got) == len(want) == g
        for a, b in zip(got, want):
            assert a[0] == b[0] and a[1] == b[1] and a[4] == b[4]  # exact
            np.testing.assert_allclose(a[2], b[2], rtol=1e-12)
            np.testing.assert_allclose(a[3], b[3], rtol=1e-12)

    def test_fused_mode_reduces_launches(self, monkeypatch):
        schema, cols, _ = self._agg_data()
        sql = "SELECT k, SUM(w), COUNT(1) FROM t GROUP BY k"

        def launches(fuse):
            monkeypatch.setenv("DATAFUSION_TPU_FUSE", fuse)
            monkeypatch.setenv("DATAFUSION_TPU_FUSE_BATCHES", "1")
            ctx = _ctx(schema, cols, batch_size=2048)  # ~20 batches
            METRICS.reset()
            collect(ctx.sql(sql))
            snap = METRICS.snapshot()["counts"]
            return snap.get("device.launches", 0), snap.get("fused.groups", 0)

        fused_n, groups = launches("1")
        unfused_n, _ = launches("0")
        assert groups >= 1
        # ~20 per-batch launches collapse into one per batch group
        assert fused_n < unfused_n
        assert fused_n <= 4

    def test_fuse_group_bucketing_bounds_compiles(self):
        from datafusion_tpu.exec.fused import bucket_group

        assert bucket_group(1) == 1
        assert bucket_group(5) == 6
        assert bucket_group(115) == 128
        assert bucket_group(9000) == 9000  # beyond the ladder: as-is

    def test_aggregate_over_projection_chain_collapses(self, monkeypatch):
        # DataFrame-style Aggregate(Projection(Selection(scan))) lowers
        # to ONE AggregateRelation under fusion
        from datafusion_tpu.plan.expr import (
            AggregateFunction, BinaryExpr, Column, Literal, Operator,
            ScalarValue,
        )
        from datafusion_tpu.plan.logical import (
            Aggregate, Projection, Selection, TableScan,
        )

        rng = np.random.default_rng(29)
        n = 10_000
        schema = Schema([
            Field("a", DataType.FLOAT64, False),
            Field("k", DataType.INT64, False),
        ])
        cols = [rng.normal(size=n), rng.integers(0, 40, n)]
        scan = TableScan("default", "t", schema)
        sel = Selection(
            BinaryExpr(Column(0), Operator.Gt,
                       Literal(ScalarValue.float64(-0.7))), scan,
        )
        proj = Projection(
            [Column(1),
             BinaryExpr(Column(0), Operator.Multiply,
                        Literal(ScalarValue.float64(3.0)))],
            sel,
            Schema([Field("k", DataType.INT64, False),
                    Field("x", DataType.FLOAT64, False)]),
        )
        agg = Aggregate(
            proj, [Column(0)],
            [AggregateFunction("sum", [Column(1)], DataType.FLOAT64)],
            Schema([Field("k", DataType.INT64, False),
                    Field("s", DataType.FLOAT64, False)]),
        )

        def run(fuse):
            monkeypatch.setenv("DATAFUSION_TPU_FUSE", fuse)
            ctx = _ctx(schema, cols)
            rel = ctx.execute(agg)
            return sorted(collect(rel).to_rows()), rel

        got, rel = run("1")
        assert getattr(rel, "_fused_chain", None) == "filter+project+aggregate"
        assert type(rel).__name__ == "AggregateRelation"
        assert rel.op_children() and type(
            rel.op_children()[0]
        ).__name__ == "DataSourceRelation"  # no interposed pipeline
        want, _ = run("0")
        for a, b in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(a, float), np.asarray(b, float), rtol=1e-12
            )

    def test_sort_chain_collapses_with_filter_and_projection(
        self, monkeypatch
    ):
        rng = np.random.default_rng(31)
        n = 20_000
        schema = Schema([
            Field("a", DataType.FLOAT64, False),
            Field("b", DataType.INT64, False),
            Field("c", DataType.INT64, False),
        ])
        cols = [rng.normal(size=n), rng.integers(0, 1000, n),
                rng.integers(0, 5, n)]
        sql = "SELECT b, a FROM t WHERE c < 3 ORDER BY b DESC, a LIMIT 25"

        def run(fuse):
            monkeypatch.setenv("DATAFUSION_TPU_FUSE", fuse)
            ctx = _ctx(schema, cols)
            rel = ctx.sql(sql)
            return collect(rel).to_rows(), rel

        got, rel = run("1")
        assert getattr(rel, "_fused_chain", None) == "filter+project+sort"
        assert "+filter" in rel.op_label() and "+project" in rel.op_label()
        want, _ = run("0")
        assert got == want
        # and the full-sort (no LIMIT) variant
        fsql = "SELECT b, a FROM t WHERE c < 3 ORDER BY b, a"
        monkeypatch.setenv("DATAFUSION_TPU_FUSE", "1")
        f_got = _rows(_ctx(schema, cols), fsql)
        monkeypatch.setenv("DATAFUSION_TPU_FUSE", "0")
        f_want = _rows(_ctx(schema, cols), fsql)
        assert f_got == f_want

    def test_explain_analyze_reports_fused_passes(self, monkeypatch):
        monkeypatch.setenv("DATAFUSION_TPU_FUSE", "1")
        rng = np.random.default_rng(37)
        n = 8000
        schema = Schema([
            Field("k", DataType.INT64, False),
            Field("v", DataType.FLOAT64, False),
        ])
        ctx = _ctx(schema, [rng.integers(0, 500, n), rng.normal(size=n)],
                   batch_size=1024)
        res = ctx.sql(
            "EXPLAIN ANALYZE SELECT k, SUM(v) FROM t WHERE v > 0 GROUP BY k"
        )
        report = res.report()
        assert "launches_per_pass=" in report
        assert "kernel_cache hit/miss=" in report
        assert res.counters["device.launches"] >= 1
        # the gauges export through the Prometheus text path
        text = ctx.metrics_text()
        assert 'name="query.launches_per_pass"' in text

    def test_repeat_query_no_kernel_cache_misses(self, monkeypatch):
        monkeypatch.setenv("DATAFUSION_TPU_FUSE", "1")
        rng = np.random.default_rng(41)
        n = 5000
        schema = Schema([
            Field("k", DataType.INT64, False),
            Field("v", DataType.FLOAT64, False),
        ])
        ctx = _ctx(schema, [rng.integers(0, 100, n), rng.normal(size=n)])
        sql = "SELECT k, SUM(v) FROM t GROUP BY k"
        first = _rows(ctx, sql)
        METRICS.reset()
        second = _rows(ctx, sql)
        snap = METRICS.snapshot()["counts"]
        assert snap.get("kernel_cache.misses", 0) == 0
        assert sorted(first) == sorted(second)


# ---------------------------------------------------- sort semantics


class TestSortSemantics:
    def test_stability_under_heavy_ties(self, monkeypatch):
        rng = np.random.default_rng(43)
        n = 30_000
        schema = Schema([
            Field("a", DataType.INT64, False),
            Field("tag", DataType.INT64, False),
        ])
        a = rng.integers(0, 8, n)  # 8 distinct keys: massive tie runs
        tag = np.arange(n, dtype=np.int64)
        for fuse in ("1", "0"):
            monkeypatch.setenv("DATAFUSION_TPU_FUSE", fuse)
            rows = _rows(_ctx(schema, [a, tag], batch_size=4096),
                         "SELECT a, tag FROM t ORDER BY a")
            # within each key run, the original row order must survive
            last = {}
            for key, tag_v in rows:
                assert last.get(key, -1) < tag_v, f"unstable at key {key}"
                last[key] = tag_v

    def test_nan_and_signed_zero_ordering(self, monkeypatch):
        vals = np.array([1.5, np.nan, -0.0, 0.0, -np.inf, np.inf,
                         -1.5, np.nan, 0.0, -0.0])
        tag = np.arange(len(vals), dtype=np.int64)
        schema = Schema([
            Field("a", DataType.FLOAT64, False),
            Field("tag", DataType.INT64, False),
        ])
        outs = {}
        for fuse in ("1", "0"):
            monkeypatch.setenv("DATAFUSION_TPU_FUSE", fuse)
            outs[fuse] = _rows(_ctx(schema, [vals, tag]),
                               "SELECT a, tag FROM t ORDER BY a")
        assert str(outs["1"]) == str(outs["0"])  # NaN-safe comparison
        order = [t for _, t in outs["1"]]
        # -inf first, then -1.5; NaNs sort last (stable between them);
        # the four zeros stay contiguous (±0.0 compare equal or split —
        # backend-dependent — but never interleave with nonzeros)
        assert order[0] == 4 and order[1] == 6
        assert order[-2:] == [1, 7]
        zeros = [t for v, t in outs["1"] if v == 0.0]
        assert sorted(zeros) == [2, 3, 8, 9]
        assert order[2:6] == zeros

    def test_multi_key_mixed_dtype(self, monkeypatch):
        rng = np.random.default_rng(47)
        n = 6000
        words = np.array(["ash", "birch", "cedar", "oak"], dtype=object)
        schema = Schema([
            Field("s", DataType.UTF8, False),
            Field("f", DataType.FLOAT64, False),
            Field("i", DataType.INT64, False),
        ])
        s = words[rng.integers(0, 4, n)]
        f = rng.normal(size=n).round(1)  # ties across keys
        i = rng.integers(-40, 40, n)
        sql = "SELECT s, f, i FROM t ORDER BY s, f DESC, i"
        got = {}
        for fuse in ("1", "0"):
            monkeypatch.setenv("DATAFUSION_TPU_FUSE", fuse)
            got[fuse] = _rows(_ctx(schema, [s, f, i]), sql)
        assert got["1"] == got["0"]
        want = sorted(
            zip(s.tolist(), f.tolist(), i.tolist()),
            key=lambda r: (r[0], -r[1], r[2]),
        )
        assert got["1"] == [tuple(w) for w in want]

    def test_high_cardinality_groupby_exact_keys_and_counts(
        self, monkeypatch
    ):
        rng = np.random.default_rng(53)
        n, g = 60_000, 20_000  # most groups have 1-6 rows
        schema = Schema([
            Field("k", DataType.INT64, False),
            Field("v", DataType.FLOAT64, False),
        ])
        k = rng.integers(0, g, n)
        v = rng.normal(size=n)
        for fuse in ("1", "0"):
            monkeypatch.setenv("DATAFUSION_TPU_FUSE", fuse)
            rows = _rows(_ctx(schema, [k, v], batch_size=8192),
                         "SELECT k, COUNT(1), SUM(v) FROM t GROUP BY k")
            got_keys = sorted(r[0] for r in rows)
            want_keys, want_counts = np.unique(k, return_counts=True)
            assert got_keys == want_keys.tolist()
            counts = {r[0]: r[1] for r in rows}
            assert all(
                counts[kk] == cc
                for kk, cc in zip(want_keys.tolist(), want_counts.tolist())
            )
            sums = {r[0]: r[2] for r in rows}
            want_sums = np.bincount(k, weights=v, minlength=g)
            for kk in want_keys.tolist():
                np.testing.assert_allclose(sums[kk], want_sums[kk], rtol=1e-9)
