"""Hash joins: SQL front-end through device/host execution and the
shuffle exchange.

Parity oracle is `pandas.merge` over the same host rows (the reference
repo has no join to compare against — PAPER.md §L2's LogicalPlan is
single-table).  Covers the dense-int device path (fused-launch counts,
pinned-build reuse with zero build-side H2D on warm probes), the host
fallback (duplicate keys, NULL keys, Utf8 keys, multi-key), plan JSON
round-trips, verifier diagnostics, projection push-down through Join,
and the shuffle partition/dedup units distributed joins build on.
"""

from __future__ import annotations

import os

import numpy as np
import pandas as pd
import pytest

from datafusion_tpu import DataType, ExecutionContext, Field, Schema
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.utils.metrics import METRICS


def _write_csv(path, header, rows):
    with open(path, "w", encoding="utf-8") as f:
        f.write(header + "\n")
        for r in rows:
            f.write(",".join("" if v is None else str(v) for v in r) + "\n")
    return str(path)


@pytest.fixture
def jctx(tmp_path):
    """fact (600 rows, dup + dangling keys) and dim (50 rows, unique
    int key) — the canonical probe/build pair."""
    rng = np.random.default_rng(7)
    fact = [(int(rng.integers(0, 60)), i, round(float(rng.uniform(0, 10)), 3))
            for i in range(600)]  # keys 50..59 dangle (no dim row)
    dim = [(i, f"name{i}", int(i % 7)) for i in range(50)]
    ctx = ExecutionContext(batch_size=256)
    ctx.register_csv(
        "fact", _write_csv(tmp_path / "fact.csv", "k,seq,x", fact),
        Schema([Field("k", DataType.INT64, False),
                Field("seq", DataType.INT64, False),
                Field("x", DataType.FLOAT64, False)]),
        has_header=True,
    )
    ctx.register_csv(
        "dim", _write_csv(tmp_path / "dim.csv", "k,name,grp", dim),
        Schema([Field("k", DataType.INT64, False),
                Field("name", DataType.UTF8, False),
                Field("grp", DataType.INT64, False)]),
        has_header=True,
    )
    ctx._fact = pd.DataFrame(fact, columns=["k", "seq", "x"])
    ctx._dim = pd.DataFrame(dim, columns=["k", "name", "grp"])
    return ctx


def _rows(ctx, sql):
    def key(row):
        return tuple((v is None, 0 if v is None else v) for v in row)

    return sorted(collect(ctx.sql(sql)).to_rows(), key=key)


def _pd_rows(df, cols):
    out = []
    for t in df[cols].itertuples(index=False):
        out.append(tuple(None if pd.isna(v) else v for v in t))

    def key(row):
        return tuple((v is None, 0 if v is None else v) for v in row)

    return sorted(out, key=key)


def _counts():
    return dict(METRICS.snapshot()["counts"])


def _delta(a, b, k):
    return b.get(k, 0) - a.get(k, 0)


class TestJoinParity:
    def test_inner_dense_path(self, jctx):
        s0 = _counts()
        got = _rows(jctx, "SELECT seq, name FROM fact "
                          "JOIN dim ON fact.k = dim.k")
        s1 = _counts()
        exp = _pd_rows(jctx._fact.merge(jctx._dim, on="k"), ["seq", "name"])
        assert got == exp
        # unique int build key in a small range: the dense device path
        # must engage, probing every (256-row) batch in ONE fused launch
        assert _delta(s0, s1, "join.build.dense") == 1
        assert _delta(s0, s1, "device.launches.join.build") == 1
        n_batches = -(-600 // 256)
        assert _delta(s0, s1, "device.launches.join.probe") == n_batches

    def test_left_outer(self, jctx):
        got = _rows(jctx, "SELECT seq, name FROM fact "
                          "LEFT JOIN dim ON fact.k = dim.k")
        exp = _pd_rows(jctx._fact.merge(jctx._dim, on="k", how="left"),
                       ["seq", "name"])
        assert got == exp
        assert any(r[1] is None for r in got)  # dangling keys NULL-extend

    def test_join_filter_aggregate(self, jctx):
        got = _rows(jctx, "SELECT grp, COUNT(seq) FROM fact "
                          "JOIN dim ON fact.k = dim.k "
                          "WHERE x > 5 GROUP BY grp")
        df = jctx._fact.merge(jctx._dim, on="k")
        df = df[df.x > 5].groupby("grp", as_index=False).agg(n=("seq", "count"))
        exp = _pd_rows(df, ["grp", "n"])
        assert [(g, int(n)) for g, n in got] == exp

    def test_duplicate_build_keys_host_path(self, jctx, tmp_path):
        # grp repeats in dim -> non-unique build keys -> host CSR path
        s0 = _counts()
        got = _rows(jctx, "SELECT seq, name FROM fact "
                          "JOIN dim ON fact.k = dim.grp")
        s1 = _counts()
        exp = _pd_rows(
            jctx._fact.merge(jctx._dim, left_on="k", right_on="grp"),
            ["seq", "name"])
        assert got == exp
        assert _delta(s0, s1, "join.build.dense") == 0

    def test_utf8_key(self, jctx, tmp_path):
        # string-keyed join: dictionary codes differ per table, so the
        # match must go through content, never through code equality
        labels = [(f"name{i}", i * 11) for i in range(0, 60, 2)]
        jctx.register_csv(
            "labels", _write_csv(tmp_path / "lab.csv", "name,score", labels),
            Schema([Field("name", DataType.UTF8, False),
                    Field("score", DataType.INT64, False)]),
            has_header=True,
        )
        got = _rows(jctx, "SELECT grp, score FROM dim "
                          "JOIN labels ON dim.name = labels.name")
        lf = pd.DataFrame(labels, columns=["name", "score"])
        exp = _pd_rows(jctx._dim.merge(lf, on="name"), ["grp", "score"])
        assert got == exp

    def test_multi_key_join(self, jctx):
        got = _rows(jctx, "SELECT seq, name FROM fact "
                          "JOIN dim ON fact.k = dim.k AND fact.k = dim.grp")
        exp = _pd_rows(
            jctx._fact.merge(jctx._dim, on="k")
            .query("k == grp"), ["seq", "name"])
        assert got == exp


class TestJoinEdges:
    def _mini(self, tmp_path, left_rows, right_rows,
              left_null=False, right_null=False):
        ctx = ExecutionContext(batch_size=64)
        ctx.register_csv(
            "l", _write_csv(tmp_path / "l.csv", "k,v", left_rows),
            Schema([Field("k", DataType.INT64, left_null),
                    Field("v", DataType.INT64, False)]),
            has_header=True,
        )
        ctx.register_csv(
            "r", _write_csv(tmp_path / "r.csv", "k,w", right_rows),
            Schema([Field("k", DataType.INT64, right_null),
                    Field("w", DataType.INT64, False)]),
            has_header=True,
        )
        return ctx

    def test_null_keys_match_nothing(self, tmp_path):
        ctx = self._mini(
            tmp_path,
            [(1, 10), (None, 11), (2, 12), (None, 13)],
            [(1, 100), (None, 101), (2, 102)],
            left_null=True, right_null=True,
        )
        got = _rows(ctx, "SELECT v, w FROM l JOIN r ON l.k = r.k")
        assert got == [(10, 100), (12, 102)]  # NULL != NULL
        got = _rows(ctx, "SELECT v, w FROM l LEFT JOIN r ON l.k = r.k")
        assert got == [(10, 100), (11, None), (12, 102), (13, None)]

    def test_empty_build_side(self, tmp_path):
        ctx = self._mini(tmp_path, [(1, 10), (2, 20)], [])
        assert _rows(ctx, "SELECT v, w FROM l JOIN r ON l.k = r.k") == []
        assert _rows(ctx, "SELECT v, w FROM l LEFT JOIN r ON l.k = r.k") \
            == [(10, None), (20, None)]

    def test_empty_probe_side(self, tmp_path):
        ctx = self._mini(tmp_path, [], [(1, 100)])
        assert _rows(ctx, "SELECT v, w FROM l JOIN r ON l.k = r.k") == []
        assert _rows(ctx, "SELECT v, w FROM l LEFT JOIN r ON l.k = r.k") == []

    @pytest.mark.parametrize("dtype,vals", [
        (DataType.INT32, [3, 1, 4, 1, 5]),
        (DataType.INT64, [-(1 << 40), 0, 1 << 40, 0, 7]),
        (DataType.FLOAT64, [1.5, -0.0, 2.25, 0.0, 1.5]),
    ])
    def test_dtype_matrix(self, tmp_path, dtype, vals):
        left = [(v, i) for i, v in enumerate(vals)]
        right = [(v, i * 100) for i, v in enumerate(sorted(set(vals)))]
        ctx = ExecutionContext(batch_size=64)
        ctx.register_csv(
            "l", _write_csv(tmp_path / "l.csv", "k,v", left),
            Schema([Field("k", dtype, False),
                    Field("v", DataType.INT64, False)]),
            has_header=True,
        )
        ctx.register_csv(
            "r", _write_csv(tmp_path / "r.csv", "k,w", right),
            Schema([Field("k", dtype, False),
                    Field("w", DataType.INT64, False)]),
            has_header=True,
        )
        got = _rows(ctx, "SELECT v, w FROM l JOIN r ON l.k = r.k")
        lf = pd.DataFrame(left, columns=["k", "v"])
        rf = pd.DataFrame(right, columns=["k", "w"])
        exp = _pd_rows(lf.merge(rf, on="k"), ["v", "w"])
        assert got == exp
        # -0.0 joined 0.0 above: equal SQL values must meet


class TestPinnedBuild:
    def test_warm_probe_reuses_pinned_build_zero_h2d(self, jctx):
        q = "SELECT seq, name FROM fact JOIN dim ON fact.k = dim.k"
        s0 = _counts()
        _rows(jctx, q)
        s1 = _counts()
        # different predicate -> result cache miss, same build subtree
        _rows(jctx, q + " WHERE x > 5")
        s2 = _counts()
        assert _delta(s1, s2, "join.build.reuse") == 1
        assert _delta(s1, s2, "device.launches.join.build") == 0
        # the warm probe moved ZERO build-side bytes: its H2D
        # transfers are probe-input-only, strictly fewer than the cold
        # pass which also uploaded the build artifact
        cold = _delta(s0, s1, "device.h2d.transfers")
        warm = _delta(s1, s2, "device.h2d.transfers")
        assert warm < cold

    def test_distinct_key_columns_distinct_pins(self, jctx):
        # same build subtree joined on DIFFERENT right-side key columns
        # must not share a pinned artifact (regression: a k-keyed build
        # served a grp-keyed probe)
        a = _rows(jctx, "SELECT seq, name FROM fact JOIN dim ON fact.k = dim.k")
        b = _rows(jctx, "SELECT seq, name FROM fact JOIN dim ON fact.k = dim.grp")
        exp_a = _pd_rows(jctx._fact.merge(jctx._dim, on="k"), ["seq", "name"])
        exp_b = _pd_rows(
            jctx._fact.merge(jctx._dim, left_on="k", right_on="grp"),
            ["seq", "name"])
        assert a == exp_a
        assert b == exp_b


def _plan_of(ctx, sql):
    from datafusion_tpu.sql.parser import parse_sql

    return ctx._plan(parse_sql(sql))


class TestJoinPlanIR:
    def test_json_roundtrip(self, jctx):
        from datafusion_tpu.plan.logical import Join, LogicalPlan

        plan = _plan_of(
            jctx, "SELECT seq, name FROM fact JOIN dim ON fact.k = dim.k")
        wire = plan.to_json()
        back = LogicalPlan.from_json(wire)
        assert back.to_json() == wire

        def find_join(p):
            if isinstance(p, Join):
                return p
            for c in p.children():
                j = find_join(c)
                if j is not None:
                    return j
            return None

        assert find_join(back) is not None

    def test_verifier_accepts_join(self, jctx):
        from datafusion_tpu.analysis.verify import verify_plan

        plan = _plan_of(
            jctx, "SELECT seq, name FROM fact LEFT JOIN dim ON fact.k = dim.k")
        assert verify_plan(plan).ok

    def test_pushdown_through_join(self, jctx):
        from datafusion_tpu.plan.logical import Join, TableScan

        # ctx._plan already runs push_down_projection
        opt = _plan_of(
            jctx, "SELECT seq, name FROM fact JOIN dim ON fact.k = dim.k")

        def scans(p, out):
            if isinstance(p, TableScan):
                out.append(p)
            for c in p.children():
                scans(c, out)
            return out

        got = {s.table_name: s.projection for s in scans(opt, [])}
        # fact needs k (key) + seq; dim needs k (key) + name — x and
        # grp must be trimmed before any byte is parsed or shipped
        assert got["fact"] == [0, 1]
        assert got["dim"] == [0, 1]

        def find_join(p):
            if isinstance(p, Join):
                return p
            for c in p.children():
                j = find_join(c)
                if j is not None:
                    return j

        j = find_join(opt)
        assert j.on == [(0, 0)]  # keys remapped to trimmed positions

    def test_parser_rejects_non_equi(self, jctx):
        from datafusion_tpu.errors import DataFusionError

        with pytest.raises(DataFusionError):
            _plan_of(jctx,
                     "SELECT seq FROM fact JOIN dim ON fact.k > dim.k")


class TestShuffleUnits:
    def test_partition_deterministic_and_content_hashed(self):
        from datafusion_tpu.exec.batch import StringDictionary
        from datafusion_tpu.join.core import partition_of

        keys = np.array([5, 17, 5, 99, -3], np.int64)
        a = partition_of([keys], [None], 4)
        b = partition_of([keys.copy()], [None], 4)
        assert (a == b).all()
        assert (a[0] == a[2]).all()  # equal keys, equal partition
        # utf8: two dictionaries with DIFFERENT code orders for the
        # same strings must partition identically (content, not codes)
        d1, d2 = StringDictionary(), StringDictionary()
        c1 = d1.encode(["x", "y", "z"])
        c2 = d2.encode(["z", "y", "x"])[::-1].copy()
        p1 = partition_of([c1], [None], 8, dicts=[d1])
        p2 = partition_of([c2], [None], 8, dicts=[d2])
        assert (p1 == p2).all()

    def test_split_merge_dedup_roundtrip(self):
        from datafusion_tpu.parallel import shuffle

        raw = {
            "num_rows": 40,
            "columns": [
                np.arange(40, dtype=np.int64),
                {"codes": (np.arange(40) % 3).astype(np.int32),
                 "values": ["a", "b", "c"]},
            ],
            "validity": [None, np.array([True] * 39 + [False])],
        }
        blocks = shuffle.split_blocks(raw, [0], 5, ("frag-fp", "L", 5, [0]))
        assert len(blocks) == 5
        assert sum(b["num_rows"] for b in blocks) == 40
        rt = [shuffle.decode_block(shuffle.encode_block(b, None))
              for b in blocks]
        s0 = _counts()
        # the same blocks delivered twice (replayed map task): the
        # merge must drop the duplicates by fingerprint, not double the rows
        cols, valids, dicts, total = shuffle.merge_side(rt + rt)
        s1 = _counts()
        assert total == 40
        assert sorted(cols[0].tolist()) == list(range(40))
        assert _delta(s0, s1, "shuffle.dedup_drops") == 5
        assert dicts[1] is not None and valids[1] is not None

    def test_reduce_join_parity(self):
        from datafusion_tpu.parallel import shuffle

        rng = np.random.default_rng(3)
        lk = rng.integers(0, 25, 300)
        rk = rng.integers(0, 25, 60)
        lraw = {"num_rows": 300,
                "columns": [lk.astype(np.int64),
                            np.arange(300, dtype=np.int64)],
                "validity": [None, None]}
        rraw = {"num_rows": 60,
                "columns": [rk.astype(np.int64),
                            np.arange(60, dtype=np.int64)],
                "validity": [None, None]}
        for join_type, how in (("inner", "inner"), ("left", "left")):
            tot = 0
            for p in range(4):
                lb = shuffle.split_blocks(lraw, [0], 4, ("l",))
                rb = shuffle.split_blocks(rraw, [0], 4, ("r",))
                out = shuffle.reduce_join([lb[p]], [rb[p]], [(0, 0)],
                                          join_type)
                tot += out["num_rows"]
            exp = pd.DataFrame({"k": lk}).merge(
                pd.DataFrame({"k": rk}), on="k", how=how).shape[0]
            assert tot == exp, (join_type, tot, exp)
