"""Durability layer: WAL + snapshots + crash-only recovery.

Covers the segment-file log itself (`utils/wal.py`: append/recover
roundtrip with non-contiguous revisions, rev dedup, torn-tail
truncation in place, CRC damage detection, mid-log tears dropping the
segments written over the hole, snapshot compaction + segment reaping,
tmp-leftover cleanup, invalid-snapshot fallback, the atomic JSON
manifest helpers), node-level crash recovery (`ClusterNode(wal_dir=)`:
full state equality across a kill, revision continuity, durability
before ack under seeded disk faults, and the WAL-off A/B — no WAL dir
means byte-identical behaviour and zero WAL surface), lease re-arm
semantics (persisted remaining TTL, never a fresh grant; a lease that
expired before the crash stays dead via the deadline note's coverage
cutoff), and the snapshot-resync truncation edge (a partially
caught-up standby that falls off the event window resyncs by full
snapshot exactly once — no duplicated or skipped events — including
under a seeded `cluster.snapshot` fault).
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from datafusion_tpu.cache.result import CachedResult
from datafusion_tpu.cluster import ClusterNode, ClusterState, LocalClusterClient
from datafusion_tpu.errors import ExecutionError
from datafusion_tpu.testing import faults
from datafusion_tpu.utils.wal import (
    WriteAheadLog,
    atomic_write_json,
    read_json,
)


def _ev(rev, key="k", value=1):
    return {"kind": "put", "rev": rev, "key": key, "value": value}


def _append(log, *revs):
    log.append([(_ev(r, key=f"k{r}", value=r), None) for r in revs])


def _snapshot(num_rows=3):
    return CachedResult(
        [np.arange(num_rows, dtype=np.int64),
         np.asarray([0, 1, 0][:num_rows], np.int32)],
        [None, np.asarray([True, False, True][:num_rows])],
        [None, ("x", "y")],
        num_rows,
        64,
    )


# -- the log itself -------------------------------------------------------


class TestWalUnit:
    def test_append_recover_roundtrip(self, tmp_path):
        d = str(tmp_path)
        log = WriteAheadLog(d)
        log.recover()
        # revisions are strictly increasing but NOT contiguous (entry
        # revs interleave event revs)
        _append(log, 1, 3, 7)
        log.close()
        log2 = WriteAheadLog(d)
        snap, events, _ = log2.recover()
        assert snap is None
        assert [e["rev"] for e in events] == [1, 3, 7]
        assert [e["key"] for e in events] == ["k1", "k3", "k7"]
        assert log2.last_rev == 7
        assert log2.recovery["replayed_events"] == 3
        assert log2.recovery["torn_tails"] == 0

    def test_reoffered_tail_dedups(self, tmp_path):
        log = WriteAheadLog(str(tmp_path))
        log.recover()
        _append(log, 1, 2)
        # concurrent syncers re-offer overlapping tails
        _append(log, 1, 2, 3)
        log.close()
        log2 = WriteAheadLog(str(tmp_path))
        _, events, _ = log2.recover()
        assert [e["rev"] for e in events] == [1, 2, 3]

    def test_torn_tail_truncated_in_place(self, tmp_path):
        d = str(tmp_path)
        log = WriteAheadLog(d)
        log.recover()
        _append(log, 1, 2)
        log.close()
        seg = os.path.join(d, "wal-00000001.seg")
        good = os.path.getsize(seg)
        with open(seg, "ab") as f:
            f.write(b"\x00" * 7)  # a crash mid-header
        log2 = WriteAheadLog(d)
        _, events, _ = log2.recover()
        assert [e["rev"] for e in events] == [1, 2]
        assert log2.recovery["torn_tails"] == 1
        assert os.path.getsize(seg) == good  # truncated back in place
        _append(log2, 3)  # appendable right after
        log2.close()
        log3 = WriteAheadLog(d)
        _, events, _ = log3.recover()
        assert [e["rev"] for e in events] == [1, 2, 3]
        assert log3.recovery["torn_tails"] == 0

    def test_crc_damage_drops_the_record(self, tmp_path):
        d = str(tmp_path)
        log = WriteAheadLog(d)
        log.recover()
        _append(log, 1, 2)
        log.close()
        seg = os.path.join(d, "wal-00000001.seg")
        with open(seg, "r+b") as f:
            f.seek(-1, os.SEEK_END)  # flip a byte inside rev 2's payload
            last = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([last[0] ^ 0xFF]))
        log2 = WriteAheadLog(d)
        _, events, _ = log2.recover()
        assert [e["rev"] for e in events] == [1]
        assert log2.recovery["torn_tails"] == 1
        assert log2.last_rev == 1

    def test_mid_log_tear_drops_later_segments(self, tmp_path):
        d = str(tmp_path)
        # segment_bytes=1: every record rotates into its own segment
        log = WriteAheadLog(d, segment_bytes=1)
        log.recover()
        _append(log, 1)
        _append(log, 2)
        _append(log, 3)
        log.close()
        assert os.path.exists(os.path.join(d, "wal-00000003.seg"))
        # tear the MIDDLE of the log: segment 2 loses its tail
        seg2 = os.path.join(d, "wal-00000002.seg")
        with open(seg2, "r+b") as f:
            f.truncate(os.path.getsize(seg2) // 2)
        log2 = WriteAheadLog(d, segment_bytes=1)
        _, events, _ = log2.recover()
        # segment 3 was written on top of lost history: replaying it
        # would silently skip rev 2, so it is dropped instead
        assert [e["rev"] for e in events] == [1]
        assert log2.last_rev == 1
        assert log2.recovery["dropped_records"] == 1
        assert log2.recovery["torn_tails"] == 1

    def test_snapshot_compacts_and_reaps(self, tmp_path):
        d = str(tmp_path)
        log = WriteAheadLog(d, segment_bytes=1)
        log.recover()
        _append(log, 1)
        _append(log, 2)
        _append(log, 3)
        log.write_snapshot({"rev": 2, "kv": {"compacted": True}})
        names = sorted(os.listdir(d))
        # segments fully covered by the snapshot are reaped; the live
        # segment (rev 3) and anything past the snapshot survive
        assert "wal-00000001.seg" not in names
        assert "wal-00000002.seg" not in names
        assert "wal-00000003.seg" in names
        assert "snapshot-00000002.snap" in names
        # a newer snapshot reaps the older one
        log.write_snapshot({"rev": 3, "kv": {"compacted": 2}})
        names = sorted(os.listdir(d))
        assert "snapshot-00000002.snap" not in names
        # a stale snapshot offer is a no-op
        log.write_snapshot({"rev": 2, "kv": {}})
        assert log.snapshot_rev == 3
        log.close()
        log2 = WriteAheadLog(d)
        snap, events, _ = log2.recover()
        assert snap == {"rev": 3, "kv": {"compacted": 2}}
        assert events == []  # everything the snapshot covers is skipped
        assert log2.last_rev == 3 and log2.snapshot_rev == 3

    def test_should_snapshot_threshold(self, tmp_path):
        log = WriteAheadLog(str(tmp_path), snapshot_bytes=1)
        log.recover()
        assert not log.should_snapshot()  # nothing to compact yet
        _append(log, 1)
        assert log.should_snapshot()
        log.write_snapshot({"rev": 1})
        assert not log.should_snapshot()  # no new state past the snap
        log.close()

    def test_tmp_leftovers_reaped_on_recovery(self, tmp_path):
        d = str(tmp_path)
        leftover = os.path.join(d, "snapshot-00000009.snap.tmp")
        with open(leftover, "wb") as f:
            f.write(b"half-written")
        log = WriteAheadLog(d)
        log.recover()
        assert not os.path.exists(leftover)
        log.close()

    def test_invalid_newer_snapshot_falls_back_to_older(self, tmp_path):
        d = str(tmp_path)
        log = WriteAheadLog(d)
        log.recover()
        _append(log, 1)
        log.write_snapshot({"rev": 1, "kv": {"good": True}})
        log.close()
        with open(os.path.join(d, "snapshot-00000009.snap"), "wb") as f:
            f.write(b"\xde\xad\xbe\xef not a snapshot")
        log2 = WriteAheadLog(d)
        snap, _, _ = log2.recover()
        assert snap == {"rev": 1, "kv": {"good": True}}
        assert log2.snapshot_rev == 1

    def test_deadline_note_carries_coverage_cutoff(self, tmp_path):
        d = str(tmp_path)
        log = WriteAheadLog(d, deadline_interval_s=0.0)
        log.recover()
        _append(log, 1, 2, 3)
        assert log.note_deadlines(lambda: {"L1": 5.0}) is True
        log.close()
        log2 = WriteAheadLog(d)
        _, _, deadlines = log2.recover()
        assert deadlines == {"L1": 5.0}
        # the note covered everything up to rev 3: a lease granted at
        # rev <= 3 but absent from the note was dead when it was taken
        assert log2.deadline_cutoff_rev == 3

    def test_deadline_note_rate_limited(self, tmp_path):
        log = WriteAheadLog(str(tmp_path), deadline_interval_s=60.0)
        log.recover()
        _append(log, 1)
        assert log.note_deadlines(lambda: {"L": 1.0}) is True
        assert log.note_deadlines(lambda: {"L": 1.0}) is False
        log.close()

    def test_bad_sync_policy_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            WriteAheadLog(str(tmp_path), sync="eventually")

    def test_manifest_shape(self, tmp_path):
        log = WriteAheadLog(str(tmp_path))
        log.recover()
        _append(log, 1)
        m = log.manifest()
        assert m["last_rev"] == 1 and m["snapshot_rev"] == 0
        assert isinstance(m["segments"], int) and m["segments"] == 1
        assert m["appends"] == 1 and m["bytes_written"] > 0
        assert m["sync"] == "always" and m["recovery"]["recovered_rev"] == 0
        log.close()

    def test_atomic_json_roundtrip_and_corrupt_read(self, tmp_path):
        path = str(tmp_path / "manifest.json")
        atomic_write_json(path, {"pins": ["t"]})
        assert read_json(path) == {"pins": ["t"]}
        assert not os.path.exists(path + ".tmp")
        with open(path, "wb") as f:
            f.write(b"{torn")
        assert read_json(path) is None  # corrupt -> None, never raise
        assert read_json(str(tmp_path / "missing.json")) is None


# -- node-level crash recovery --------------------------------------------


class TestNodeRecovery:
    def test_full_state_survives_a_kill(self, tmp_path):
        d = str(tmp_path)
        node = ClusterNode(addr="a:1", wal_dir=d)
        client = LocalClusterClient(node)
        g = client.lease_grant(30.0)
        client.put("workers/w:9", {"addr": "w:9"}, lease=g["lease"])
        client.put("config/x", {"nested": [1, 2]})
        client.invalidate("t")
        entry = _snapshot()
        client.result_publish("fp", entry, 64, ("t",))
        term, rev = node.term, node.state._rev
        epoch = node.state.membership()["epoch"]
        del node, client  # crash: no stop(), no flush()
        node2 = ClusterNode(addr="a:1", wal_dir=d)
        assert node2.recovered_revisions == rev
        assert node2.term == term and node2.state._rev == rev
        assert node2.state.membership()["epoch"] == epoch
        assert node2.state.get("config/x") == {"nested": [1, 2]}
        assert node2.state.membership()["workers"].keys() == {"w:9"}
        stored = node2.state.result_get("fp")
        assert stored is not None
        np.testing.assert_array_equal(
            stored["snapshot"]["columns"][0], entry.columns[0])
        assert node2.status()["wal"]["recovery"]["replayed_events"] > 0

    def test_revision_continuity_across_restarts(self, tmp_path):
        d = str(tmp_path)
        node = ClusterNode(wal_dir=d)
        LocalClusterClient(node).put("a", 1)
        rev1 = node.state._rev
        del node
        node2 = ClusterNode(wal_dir=d)
        LocalClusterClient(node2).put("b", 2)
        assert node2.state._rev > rev1  # no rev reuse after recovery
        del node2
        node3 = ClusterNode(wal_dir=d)
        assert node3.state.get("a") == 1 and node3.state.get("b") == 2

    def test_disk_fault_refuses_the_ack(self, tmp_path):
        node = ClusterNode(wal_dir=str(tmp_path))
        with faults.scoped({"rules": [
            {"site": "wal.write", "op": "raise",
             "exc": "OSError", "count": 1},
        ]}):
            out = node.handle_request(
                {"type": "kv_put", "key": "k", "value": 1})
            assert out["type"] == "error"
            assert out["code"] == "wal_unavailable"
        # the fault was transient: the next attempt lands durably
        out = node.handle_request({"type": "kv_put", "key": "k", "value": 2})
        assert out["type"] == "ok"
        del node
        node2 = ClusterNode(wal_dir=str(tmp_path))
        assert node2.state.get("k") == 2

    def test_wal_off_is_byte_identical(self, tmp_path, monkeypatch):
        monkeypatch.delenv("DATAFUSION_TPU_WAL_DIR", raising=False)
        plain = ClusterNode(addr="a:1")
        walled = ClusterNode(addr="a:1", wal_dir=str(tmp_path))
        assert plain.wal is None and walled.wal is not None
        # zero WAL surface with durability off
        assert "wal" not in plain.status()
        assert not any(k.startswith("wal.") for k in plain.gauges())
        assert "wal" in walled.status()
        reqs = [
            {"type": "kv_put", "key": "a", "value": 1},
            {"type": "kv_put", "key": "b", "value": {"x": [1, 2]}},
            {"type": "kv_get", "key": "b"},
            {"type": "invalidate", "table": "t"},
            {"type": "kv_delete", "key": "a"},
            {"type": "kv_range", "prefix": ""},
            {"type": "events", "since": 0},
        ]
        for msg in reqs:
            assert plain.handle_request(dict(msg)) \
                == walled.handle_request(dict(msg))


# -- lease re-arm semantics -----------------------------------------------


class TestLeaseRearm:
    def test_rearm_uses_persisted_remaining_never_full_ttl(self):
        st = ClusterState()
        g = st.lease_grant(10.0, now=0.0)
        st.put("workers/w", {}, lease=g["lease"], now=0.0)
        st.rearm_leases({g["lease"]: 1.5}, now=100.0)
        assert st.get("workers/w", now=101.0) is not None
        # 1.5s remaining, not a fresh 10s grant
        assert st.get("workers/w", now=102.0) is None

    def test_rearm_zero_dies_on_first_sweep(self):
        st = ClusterState()
        g = st.lease_grant(10.0, now=0.0)
        st.put("workers/w", {}, lease=g["lease"], now=0.0)
        st.rearm_leases({g["lease"]: 0.0}, now=100.0)
        assert st.get("workers/w", now=100.001) is None

    def test_rearm_caps_at_the_ttl(self):
        st = ClusterState()
        g = st.lease_grant(2.0, now=0.0)
        st.rearm_leases({g["lease"]: 99.0}, now=100.0)
        assert st._leases[g["lease"]].expires == pytest.approx(102.0)

    def test_dead_lease_stays_dead_across_crash(self, tmp_path, monkeypatch):
        """Regression: a lease that expired BEFORE the crash is absent
        from the deadline note (the note excludes expired leases), but
        its grant event still replays — without the note's coverage
        cutoff the full-TTL fallback would revive it, masking a dead
        worker for a whole extra TTL after every restart."""
        monkeypatch.setenv("DATAFUSION_TPU_WAL_DEADLINE_S", "0.0")
        d = str(tmp_path)
        node = ClusterNode(wal_dir=d)
        client = LocalClusterClient(node)
        g = client.lease_grant(0.4)
        client.put("workers/dead", {}, lease=g["lease"])
        time.sleep(0.6)
        # this write sweeps the expired lease AND syncs a deadline
        # note that no longer mentions it
        client.put("config/x", 1)
        del node, client  # crash
        node2 = ClusterNode(wal_dir=d)
        st = node2.state
        # granted at rev <= the note's cutoff but absent from it:
        # re-armed at zero, gone on the first sweep — never 0.4s alive
        exp = st._leases[g["lease"]].expires if g["lease"] in st._leases \
            else None
        assert exp is None or exp - time.monotonic() <= 0.05
        assert st.get("workers/dead") is None

    def test_live_lease_rearms_with_remaining(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DATAFUSION_TPU_WAL_DEADLINE_S", "0.0")
        d = str(tmp_path)
        node = ClusterNode(wal_dir=d)
        client = LocalClusterClient(node)
        g = client.lease_grant(30.0)
        client.put("workers/live", {}, lease=g["lease"])
        del node, client
        node2 = ClusterNode(wal_dir=d)
        remaining = node2.state._leases[g["lease"]].expires - time.monotonic()
        assert 0.0 < remaining <= 30.0
        assert node2.state.get("workers/live") is not None

    def test_lease_granted_after_the_note_gets_full_ttl(self, tmp_path):
        # note cadence bounds this window: a grant the note never saw
        # has no persisted deadline -> bounded full-TTL fallback
        d = str(tmp_path)
        log = WriteAheadLog(d, deadline_interval_s=0.0)
        log.recover()
        _append(log, 1)
        log.note_deadlines(lambda: {})  # cutoff = 1
        log.append([({"kind": "lease_grant", "rev": 2,
                      "lease": "late", "ttl_s": 10.0}, None)])
        log.close()
        node = ClusterNode(wal_dir=d)
        assert node.wal.deadline_cutoff_rev == 1
        lease = node.state._leases["late"]
        assert lease.expires - time.monotonic() == pytest.approx(10.0, abs=1.0)


# -- snapshot-resync truncation edge --------------------------------------


def _pair(election_timeout_s=1.0):
    a = ClusterNode(addr="a:1")
    b = ClusterNode(addr="b:2", standby_of=a,
                    election_timeout_s=election_timeout_s)
    return a, b, LocalClusterClient([a, b])


class TestSnapshotResyncTruncation:
    def _blow_the_window(self, client, n=1200):
        for i in range(n):  # past the 1024-event retention window
            client.invalidate(f"t{i}")

    def test_partially_caught_up_standby_resyncs_once(self):
        a, b, client = _pair()
        g = client.lease_grant(30.0)
        client.put("workers/w:9", {"addr": "w:9"}, lease=g["lease"])
        client.put("config/x", 1)
        assert b.replicate_once() > 0  # partial catch-up, then fall off
        mid_rev = b.state._rev
        self._blow_the_window(client)
        assert b.replicate_once() == -1  # full snapshot, not a tail
        assert b.snapshots_applied == 1
        # nothing duplicated, nothing skipped
        assert b.state._rev == a.state._rev > mid_rev
        assert b.state.membership()["epoch"] == a.state.membership()["epoch"]
        assert b.state.membership()["workers"].keys() == {"w:9"}
        assert b.state.get("config/x") == 1
        # incremental shipping resumes cleanly after the resync
        client.put("config/y", 2)
        assert b.replicate_once() >= 1
        assert b.snapshots_applied == 1  # no second snapshot needed
        assert b.state.get("config/y") == 2

    def test_resync_survives_a_snapshot_fault(self):
        a, b, client = _pair()
        client.lease_grant(30.0)   # rev 1: keeps the floor at 1 so
        client.put("config/x", 1)  # the first pull ships events
        assert b.replicate_once() > 0
        self._blow_the_window(client)
        with faults.scoped({"rules": [
            {"site": "cluster.snapshot", "op": "raise",
             "exc": "ExecutionError", "count": 1},
        ]}):
            with pytest.raises(ExecutionError):
                b.replicate_once()
            assert b.snapshots_applied == 0  # the failed pull changed nothing
        assert b.replicate_once() == -1  # the retry resyncs
        assert b.snapshots_applied == 1
        assert b.state._rev == a.state._rev
        assert b.state.get("config/x") == 1


# -- debug-bundle durability block ----------------------------------------


class TestBundleWalBlock:
    def test_bundle_reports_live_wal_manifests(self, tmp_path):
        from datafusion_tpu.obs.httpd import build_bundle

        node = ClusterNode(wal_dir=str(tmp_path))
        LocalClusterClient(node).put("a", 1)
        doc = build_bundle(profile_seconds=0.0)
        manifests = [m for m in doc.get("wal", [])
                     if m["dir"] == node.wal.dir]
        assert len(manifests) == 1
        assert manifests[0]["last_rev"] == node.state._rev
        node.wal.close()
        # a closed WAL drops out of the bundle
        doc = build_bundle(profile_seconds=0.0)
        assert all(m["dir"] != node.wal.dir for m in doc.get("wal", []))
