"""Native C++ runtime tests: parity between the C++ CSV reader and the
pyarrow-backed one, and end-to-end engine behavior on the native path."""

import os

import numpy as np
import pytest

from datafusion_tpu import DataType, Field, Schema
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.io.readers import CsvReader
from datafusion_tpu.native import build_library, native_available

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "test", "data")

pytestmark = pytest.mark.skipif(
    not (build_library() and native_available()), reason="native library unavailable"
)


def _native_reader(*args, **kw):
    from datafusion_tpu.native.csv import NativeCsvReader

    return NativeCsvReader(*args, **kw)


def _collect_reader(reader):
    """(columns, validity, decoded strings) concatenated across batches."""
    cols = None
    for batch in reader.batches():
        n = batch.num_rows
        vals = []
        for i in range(batch.num_columns):
            c = np.asarray(batch.data[i])[:n]
            if batch.dicts[i] is not None:
                c = batch.dicts[i].decode(c).copy()
            v = batch.validity[i]
            v = np.ones(n, bool) if v is None else np.asarray(v)[:n]
            vals.append((c, v))
        if cols is None:
            cols = [([c], [v]) for c, v in vals]
        else:
            for i, (c, v) in enumerate(vals):
                cols[i][0].append(c)
                cols[i][1].append(v)
    if cols is None:
        return []
    return [
        (np.concatenate(cs), np.concatenate(vs)) for cs, vs in cols
    ]


def _assert_reader_parity(path, schema, has_header, batch_size=64, projection=None):
    native = _collect_reader(
        _native_reader(path, schema, has_header, batch_size, projection)
    )
    arrow = _collect_reader(
        CsvReader(path, schema, has_header, batch_size, projection)
    )
    assert len(native) == len(arrow)
    for i, ((nc, nv), (ac, av)) in enumerate(zip(native, arrow)):
        np.testing.assert_array_equal(nv, av, err_msg=f"validity col {i}")
        # compare only valid positions (null fill values may differ)
        if nc.dtype == object:
            assert nc[nv].tolist() == ac[av].tolist(), f"col {i}"
        else:
            np.testing.assert_array_equal(nc[nv], ac[av], err_msg=f"col {i}")


UK_SCHEMA = Schema(
    [
        Field("city", DataType.UTF8, False),
        Field("lat", DataType.FLOAT64, False),
        Field("lng", DataType.FLOAT64, False),
    ]
)

ALL_TYPES_SCHEMA = Schema(
    [
        Field("c_bool", DataType.BOOLEAN, False),
        Field("c_uint8", DataType.UINT8, False),
        Field("c_uint16", DataType.UINT16, False),
        Field("c_uint32", DataType.UINT32, False),
        Field("c_uint64", DataType.UINT64, False),
        Field("c_int8", DataType.INT8, False),
        Field("c_int16", DataType.INT16, False),
        Field("c_int32", DataType.INT32, False),
        Field("c_int64", DataType.INT64, False),
        Field("c_float32", DataType.FLOAT32, False),
        Field("c_float64", DataType.FLOAT64, False),
        Field("c_utf8", DataType.UTF8, False),
    ]
)

NULL_SCHEMA = Schema(
    [
        Field("c_int", DataType.INT32, True),
        Field("c_float", DataType.FLOAT32, True),
        Field("c_string", DataType.UTF8, True),
        Field("c_bool", DataType.BOOLEAN, True),
    ]
)


class TestNativeCsvParity:
    def test_uk_cities_headerless(self):
        _assert_reader_parity(
            os.path.join(DATA, "uk_cities.csv"), UK_SCHEMA, has_header=False,
            batch_size=7,
        )

    def test_all_types_quoted_multiline_strings(self):
        # row 26's c_utf8 contains a quoted embedded newline
        _assert_reader_parity(
            os.path.join(DATA, "all_types_flat.csv"), ALL_TYPES_SCHEMA,
            has_header=False, batch_size=100,
        )

    def test_null_test_with_header(self):
        _assert_reader_parity(
            os.path.join(DATA, "null_test.csv"), NULL_SCHEMA, has_header=True,
        )

    def test_projection(self):
        _assert_reader_parity(
            os.path.join(DATA, "uk_cities.csv"), UK_SCHEMA, has_header=False,
            projection=[1, 0],
        )

    def test_open_error(self):
        from datafusion_tpu.errors import IoError

        with pytest.raises(IoError):
            list(_native_reader("/nonexistent.csv", UK_SCHEMA, False, 64).batches())

    def test_malformed_row_errors(self, tmp_path):
        from datafusion_tpu.errors import IoError

        p = tmp_path / "bad.csv"
        p.write_text("a,1.0,2.0\nb,3.0\n")
        with pytest.raises(IoError):
            list(_native_reader(str(p), UK_SCHEMA, False, 64).batches())


class TestNativeEngine:
    def test_sql_through_native_reader(self, monkeypatch):
        # the native C++ reader is the explicit-selection path (the
        # default is the faster pyarrow SIMD parser)
        monkeypatch.setenv("DATAFUSION_TPU_CSV_READER", "native")
        ctx = ExecutionContext(batch_size=8)
        ctx.register_csv("cities", os.path.join(DATA, "uk_cities.csv"),
                         UK_SCHEMA, has_header=False)
        from datafusion_tpu.native.csv import NativeCsvReader

        assert isinstance(ctx.datasources["cities"]._reader, NativeCsvReader)
        t = ctx.sql_collect(
            "SELECT city, lat + lng FROM cities WHERE lat > 51.0 AND lat < 53"
        )
        assert t.num_rows == 18
        t2 = ctx.sql_collect("SELECT COUNT(1), MIN(lat), MAX(lat) FROM cities")
        assert t2.to_rows()[0][0] == 37

    def test_partitioned_native_shared_dicts(self, tmp_path):
        from datafusion_tpu.parallel import PartitionedContext, make_mesh

        paths = []
        for p in range(3):
            f = tmp_path / f"p{p}.csv"
            f.write_text("k,v\n" + "".join(
                f"{k},{i}\n" for i, k in enumerate(["x", "y", "z"][p % 3:] + ["x"])
            ))
            paths.append(str(f))
        schema = Schema([Field("k", DataType.UTF8, False), Field("v", DataType.INT64, False)])
        ctx = PartitionedContext(mesh=make_mesh(2), batch_size=4)
        ctx.register_partitioned_csv("t", paths, schema)
        got = {
            r[0]: r[1] for r in ctx.sql_collect(
                "SELECT k, COUNT(v) FROM t GROUP BY k"
            ).to_rows()
        }
        import csv as _csv

        want = {}
        for path in paths:
            with open(path) as fh:
                for row in list(_csv.reader(fh))[1:]:
                    want[row[0]] = want.get(row[0], 0) + 1
        assert got == want


class TestRegressions:
    def test_count_star_survives_pushdown(self, tmp_path):
        """push_down_projection must preserve count_star: COUNT(1)
        counts rows, not non-null values of column 0."""
        from datafusion_tpu import f as aggf

        p = tmp_path / "n.csv"
        p.write_text("a,b,c\n,x,1\n5,x,2\n,y,3\n")
        schema = Schema([
            Field("a", DataType.INT64, True),
            Field("b", DataType.UTF8, False),
            Field("c", DataType.INT64, False),
        ])
        ctx = ExecutionContext()
        ctx.register_csv("t", str(p), schema)
        got = sorted(
            ctx.table("t").aggregate(["b"], [aggf.count()]).collect().to_rows()
        )
        assert got == [("x", 2), ("y", 1)]
        got_sql = sorted(
            ctx.sql_collect("SELECT b, COUNT(1) FROM t GROUP BY b").to_rows()
        )
        assert got_sql == [("x", 2), ("y", 1)]

    def test_bool_spellings_match_pyarrow(self, tmp_path):
        p = tmp_path / "b.csv"
        p.write_text("x\nTrue\nFALSE\ntrue\n0\n")
        schema = Schema([Field("x", DataType.BOOLEAN, False)])
        _assert_reader_parity(str(p), schema, has_header=True)

    def test_native_projection_skips_columns(self, tmp_path):
        """A projected native scan must not choke on (or pay for)
        unprojected columns — even unparseable ones."""
        p = tmp_path / "w.csv"
        p.write_text("1,notanumber,2.5\n3,alsobad,4.5\n")
        schema = Schema([
            Field("a", DataType.INT64, False),
            Field("bad", DataType.INT64, False),
            Field("c", DataType.FLOAT64, False),
        ])
        r = _native_reader(str(p), schema, False, 64, projection=[0, 2])
        out = _collect_reader(r)
        np.testing.assert_array_equal(out[0][0], [1, 3])
        np.testing.assert_array_equal(out[1][0], [2.5, 4.5])


class TestNativeRangeChecks:
    def test_int_out_of_range_errors(self, tmp_path):
        # ADVICE: 300 in an Int8 column must error (as the pyarrow
        # fallback does), not silently wrap to 44
        from datafusion_tpu.errors import IoError

        schema = Schema([Field("v", DataType.INT8, False)])
        p = tmp_path / "over.csv"
        p.write_text("300\n")
        with pytest.raises(IoError):
            list(_native_reader(str(p), schema, False, 64).batches())
        p.write_text("-129\n")
        with pytest.raises(IoError):
            list(_native_reader(str(p), schema, False, 64).batches())
        p.write_text("127\n-128\n")
        (col, _), = _collect_reader(_native_reader(str(p), schema, False, 64))
        assert col.tolist() == [127, -128]

    def test_uint_out_of_range_errors(self, tmp_path):
        from datafusion_tpu.errors import IoError

        schema = Schema([Field("v", DataType.UINT16, False)])
        p = tmp_path / "over.csv"
        p.write_text("65536\n")
        with pytest.raises(IoError):
            list(_native_reader(str(p), schema, False, 64).batches())
        p.write_text("65535\n0\n")
        (col, _), = _collect_reader(_native_reader(str(p), schema, False, 64))
        assert col.tolist() == [65535, 0]
