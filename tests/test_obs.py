"""Observability subsystem (datafusion_tpu/obs/): hierarchical spans,
trace-context propagation (in-process and across a real worker
subprocess), per-operator stats, EXPLAIN ANALYZE invariants, and the
Chrome-trace / Prometheus exporters."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.obs import trace
from datafusion_tpu.obs.explain import ExplainAnalyzeResult

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = Schema(
    [
        Field("region", DataType.UTF8, False),
        Field("v", DataType.INT64, False),
        Field("x", DataType.FLOAT64, True),
    ]
)


def _write_csv(path, rows=300, seed=7):
    rng = np.random.default_rng(seed)
    regions = ["north", "south", "east", "west"]
    with open(path, "w", encoding="utf-8") as f:
        f.write("region,v,x\n")
        for _ in range(rows):
            r = regions[rng.integers(0, len(regions))]
            x = "" if rng.random() < 0.1 else f"{rng.uniform(-5, 5):.6f}"
            f.write(f"{r},{int(rng.integers(-1000, 1000))},{x}\n")
    return str(path)


@pytest.fixture()
def ctx(tmp_path):
    c = ExecutionContext(device="cpu")
    c.register_csv("t", _write_csv(tmp_path / "t.csv"), SCHEMA)
    return c


class TestSpans:
    def test_nesting_and_attrs(self):
        with trace.session() as tc:
            with trace.span("outer", kind="test") as outer:
                with trace.span("inner", shard=3) as inner:
                    assert trace.current_span() is inner
                assert trace.current_span() is outer
        recorded = trace.drain(tc.trace_id)
        by_name = {s["name"]: s for s in recorded}
        assert set(by_name) == {"outer", "inner"}
        assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
        assert by_name["outer"]["attrs"] == {"kind": "test"}
        assert by_name["inner"]["attrs"] == {"shard": 3}
        assert by_name["inner"]["trace_id"] == tc.trace_id
        for s in recorded:
            assert s["end_ns"] >= s["start_ns"]

    def test_disabled_mode_is_allocation_free(self):
        assert not trace.enabled()
        # the no-op context manager is a process-wide singleton: the
        # hot path allocates nothing per call
        assert trace.span("a") is trace.span("b")
        with trace.span("a") as sp:
            assert sp is None
        assert trace.begin_span("x") is None
        trace.finish_span(None)  # no-op, no error

    def test_disabled_mode_records_no_operator_stats(self, ctx):
        rel = ctx.sql("SELECT region, v FROM t WHERE v > 0")
        from datafusion_tpu.exec.materialize import collect

        collect(rel)
        # lazily-created stats never materialize on an uninstrumented run
        assert rel._op_stats is None
        assert rel.child._op_stats is None

    def test_session_restores_disabled_state(self):
        assert not trace.enabled()
        with trace.session():
            assert trace.enabled()
        assert not trace.enabled()

    def test_overlapping_sessions_keep_collection_on(self):
        # sessions are a depth counter, not a flag flip: a session
        # beginning AND ending while another thread's session is still
        # active must not turn collection off under it
        import threading

        started, release = threading.Event(), threading.Event()
        results = {}

        def holder():
            with trace.session() as tc:
                started.set()
                release.wait(timeout=10)
                results["enabled_inside"] = trace.enabled()
                results["trace_id"] = tc.trace_id

        t = threading.Thread(target=holder)
        t.start()
        try:
            assert started.wait(timeout=10)
            with trace.session():
                pass  # full session lifecycle while holder is active
            assert trace.enabled(), "sibling session lost collection"
        finally:
            release.set()
            t.join(timeout=10)
        assert results["enabled_inside"] is True
        assert not trace.enabled()
        trace.drain()

    def test_buffer_cap_drops_not_grows(self):
        import datafusion_tpu.obs.trace as t

        old_max = t._MAX_SPANS
        t._MAX_SPANS = 2
        try:
            with trace.session() as tc:
                for i in range(5):
                    with trace.span(f"s{i}"):
                        pass
            assert len(trace.drain(tc.trace_id)) <= 2
        finally:
            t._MAX_SPANS = old_max
            trace.drain()  # leave a clean buffer for other tests


class TestTraceContextWire:
    def test_wire_roundtrip(self):
        tc = trace.TraceContext("abc123", "span9")
        back = trace.TraceContext.from_wire(tc.to_wire())
        assert back.trace_id == "abc123" and back.span_id == "span9"
        assert trace.TraceContext.from_wire(None) is None
        assert trace.TraceContext.from_wire({}) is None
        assert trace.TraceContext.from_wire({"nope": 1}) is None

    def test_adopt_parents_and_force_enables(self):
        assert not trace.enabled()
        wire = {"trace_id": "feedc0de00000001", "parent_span_id": "p" * 16}
        with trace.adopt(wire):
            assert trace.enabled()  # force-enabled for the request
            with trace.span("worker.fragment", shard=0):
                pass
        assert not trace.enabled()
        got = trace.drain("feedc0de00000001")
        assert len(got) == 1
        assert got[0]["parent_id"] == "p" * 16
        assert got[0]["trace_id"] == "feedc0de00000001"

    def test_adopt_invalid_is_noop(self):
        with trace.adopt(None) as tc:
            assert tc is None
            assert not trace.enabled()

    def test_adopt_is_thread_scoped(self):
        """A worker thread serving a traced request must not turn
        collection on for sibling handler threads serving untraced
        requests (orphan spans would fill the bounded buffer)."""
        import threading

        seen = {}
        with trace.adopt({"trace_id": "aaaa000011112222"}):
            assert trace.enabled()

            def probe():
                seen["enabled"] = trace.enabled()
                with trace.span("should_not_record"):
                    pass

            t = threading.Thread(target=probe)
            t.start()
            t.join(timeout=10)
        assert seen["enabled"] is False
        assert trace.drain("aaaa000011112222") == []
        assert all(
            s["name"] != "should_not_record" for s in trace.drain()
        )

    def test_ingest_rejects_garbage_keeps_good(self):
        good = {
            "name": "w", "trace_id": "t1", "span_id": "s1",
            "parent_id": None, "start_ns": 1, "end_ns": 2,
        }
        assert trace.ingest([good, "garbage", {"name": "incomplete"}]) == 1
        assert [s["name"] for s in trace.drain("t1")] == ["w"]


class TestExplainAnalyze:
    def test_rows_match_plain_run(self, ctx):
        sql = "SELECT region, v + 1 FROM t WHERE v > 0"
        plain = ctx.sql_collect(sql)
        res = ctx.sql_collect(f"EXPLAIN ANALYZE {sql}")
        assert isinstance(res, ExplainAnalyzeResult)
        # the analyzed run IS a real run: same rows out
        assert res.result.num_rows == plain.num_rows
        assert sorted(res.result.to_rows()) == sorted(plain.to_rows())
        # root operator stats agree with the materialized result
        assert res.root.stats.rows_out == plain.num_rows
        assert res.root.stats.batches_out >= 1
        assert res.root.stats.time_s > 0
        assert res.wall_s >= res.root.stats.time_s

    def test_operator_tree_and_scan_rows(self, ctx):
        res = ctx.sql_collect(
            "EXPLAIN ANALYZE SELECT region, SUM(v), COUNT(1) FROM t "
            "WHERE v > -2000 GROUP BY region"
        )
        report = res.report()
        assert "Aggregate[" in report and "Scan[Csv" in report
        # the scan feeds every input row to the aggregate
        tree = {rel.op_label(): rel for _, rel in self._tree(res)}
        scan = next(v for k, v in tree.items() if k.startswith("Scan"))
        assert scan.stats.rows_out == 300
        assert repr(res) == report

    @staticmethod
    def _tree(res):
        from datafusion_tpu.obs.stats import collect_tree

        return collect_tree(res.root)

    def test_explain_without_analyze_still_plans_only(self, ctx):
        from datafusion_tpu.exec.context import ExplainResult

        out = ctx.sql_collect("EXPLAIN SELECT region FROM t")
        assert isinstance(out, ExplainResult)

    def test_parser_analyze_flag(self):
        from datafusion_tpu.sql import ast
        from datafusion_tpu.sql.parser import parse_sql

        node = parse_sql("EXPLAIN ANALYZE SELECT 1")
        assert isinstance(node, ast.SqlExplain) and node.analyze
        node = parse_sql("explain analyze select 1")
        assert isinstance(node, ast.SqlExplain) and node.analyze
        node = parse_sql("EXPLAIN SELECT 1")
        assert isinstance(node, ast.SqlExplain) and not node.analyze

    def test_chrome_trace_schema(self, ctx):
        res = ctx.sql_collect("EXPLAIN ANALYZE SELECT v FROM t WHERE v > 0")
        ct = res.chrome_trace()
        json.dumps(ct)  # serializable
        events = ct["traceEvents"]
        xs = [e for e in events if e["ph"] == "X"]
        assert xs, "no complete events"
        for e in xs:
            assert {"name", "ts", "dur", "pid", "tid", "args"} <= set(e)
            assert e["dur"] >= 0
            assert e["args"]["trace_id"] == res.trace_id
        metas = [e for e in events if e["ph"] == "M"]
        assert any(m["name"] == "process_name" for m in metas)

    def test_write_chrome_trace(self, ctx, tmp_path):
        res = ctx.sql_collect("EXPLAIN ANALYZE SELECT v FROM t")
        path = res.write_chrome_trace(str(tmp_path / "trace.json"))
        with open(path) as f:
            loaded = json.load(f)
        assert loaded["traceEvents"]

    def test_cli_backslash_explain(self, ctx):
        import io

        from datafusion_tpu.cli import Console

        out = io.StringIO()
        console = Console(ctx, out=out)
        assert console.handle_command("\\explain SELECT region FROM t;")
        text = out.getvalue()
        assert "EXPLAIN ANALYZE" in text and "Scan[Csv" in text
        out.truncate(0)
        assert console.handle_command("\\explain")
        assert "Usage" in out.getvalue()


class TestMeshDeadline:
    """ROADMAP follow-on: the single-host mesh path honors the ambient
    per-query deadline instead of running unbounded."""

    def _pctx(self, tmp_path, **kw):
        from datafusion_tpu.parallel.partition import PartitionedContext

        paths = [
            _write_csv(tmp_path / f"p{i}.csv", rows=200, seed=i)
            for i in range(3)
        ]
        pctx = PartitionedContext(n_devices=2, **kw)
        pctx.register_partitioned_csv("t", paths, SCHEMA)
        return pctx

    def test_expired_deadline_aborts_mesh_query(self, tmp_path):
        from datafusion_tpu.errors import QueryDeadlineError
        from datafusion_tpu.exec.materialize import collect

        pctx = self._pctx(tmp_path, query_deadline_s=1e-9)
        with pytest.raises(QueryDeadlineError):
            collect(pctx.sql("SELECT region, SUM(v) FROM t GROUP BY region"))

    def test_generous_deadline_passes_and_matches(self, tmp_path):
        from datafusion_tpu.exec.materialize import collect

        pctx = self._pctx(tmp_path, query_deadline_s=300.0)
        sql = "SELECT region, SUM(v), COUNT(1) FROM t GROUP BY region"
        got = sorted(collect(pctx.sql(sql)).to_rows())
        want = sorted(collect(self._pctx(tmp_path).sql(sql)).to_rows())
        assert got == want

    def test_env_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DATAFUSION_TPU_QUERY_DEADLINE_S", "123.5")
        pctx = self._pctx(tmp_path)
        assert pctx.query_deadline_s == 123.5


@pytest.fixture(scope="module")
def obs_worker():
    """One real worker OS process (the cross-process propagation leg)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "datafusion_tpu.worker",
         "--bind", "127.0.0.1:0", "--device", "cpu"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    try:
        line = proc.stdout.readline()
        assert "listening on" in line, line
        host, port = line.strip().rsplit(" ", 1)[1].rsplit(":", 1)
        yield (host, int(port))
    finally:
        proc.terminate()
        proc.wait(timeout=10)


class TestWorkerPropagation:
    def _dctx(self, tmp_path, addr):
        from datafusion_tpu.exec.datasource import CsvDataSource
        from datafusion_tpu.parallel.coordinator import DistributedContext
        from datafusion_tpu.parallel.partition import PartitionedDataSource

        paths = [
            _write_csv(tmp_path / f"d{i}.csv", rows=150, seed=10 + i)
            for i in range(3)
        ]
        dctx = DistributedContext([addr])
        dctx.register_datasource(
            "t",
            PartitionedDataSource(
                [CsvDataSource(p, SCHEMA, True, 131072) for p in paths]
            ),
        )
        return dctx

    def test_explain_analyze_merges_worker_spans(self, tmp_path, obs_worker):
        dctx = self._dctx(tmp_path, obs_worker)
        res = dctx.sql_collect(
            "EXPLAIN ANALYZE SELECT region, SUM(v), MIN(v) FROM t "
            "GROUP BY region"
        )
        assert isinstance(res, ExplainAnalyzeResult)
        # ONE trace id across coordinator and worker timelines
        assert {s["trace_id"] for s in res.spans} == {res.trace_id}
        frags = [s for s in res.spans if s["name"] == "worker.fragment"]
        assert len(frags) == 3  # one per partition
        assert all(str(s["proc"]).startswith("worker") for s in frags)
        dispatches = {
            s["span_id"]: s for s in res.spans if s["name"] == "coord.dispatch"
        }
        # every worker fragment span parents under a dispatch span
        for s in frags:
            assert s["parent_id"] in dispatches
            assert dispatches[s["parent_id"]]["attrs"]["shard"] == \
                s["attrs"]["shard"]
        # the report names them
        assert "worker-side" in res.report()
        json.dumps(res.chrome_trace())

    def test_untraced_requests_carry_no_trace(self, tmp_path, obs_worker):
        """Tracing off => requests ship no trace key and responses ship
        no spans (the disabled path stays lean on the wire too)."""
        trace.drain()  # start from a clean buffer
        dctx = self._dctx(tmp_path, obs_worker)
        rows = dctx.sql_collect(
            "SELECT region, SUM(v) FROM t GROUP BY region"
        )
        assert rows.num_rows == 4
        assert trace.spans() == []


class TestPrometheusExport:
    def test_counters_render_after_query(self, ctx):
        from datafusion_tpu.obs.export import prometheus_text

        ctx.sql_collect("SELECT region, SUM(v) FROM t GROUP BY region")
        text = prometheus_text()
        assert "datafusion_tpu_timing_seconds_total" in text
        # dotted engine names keep their dots in label values (the
        # sanitization fix: label values escape, not flatten)
        assert 'datafusion_tpu_events_total{name="scan.rows"}' in text
        # ctx.metrics_text() is the same exposition plus this process's
        # histogram quantile gauges (query latency, per-table scans)
        from datafusion_tpu.obs.aggregate import histogram_gauges

        assert ctx.metrics_text() == prometheus_text(
            extra_gauges=histogram_gauges()
        )
        # exposition format sanity: every sample line is name{labels} value
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            name_part, value = line.rsplit(" ", 1)
            float(value)
            assert "{" in name_part and name_part.endswith('"}')

    def test_extra_gauges(self):
        from datafusion_tpu.obs.export import prometheus_text
        from datafusion_tpu.utils.metrics import Metrics

        m = Metrics()
        m.add("x.y", 3)
        m.observe("stage-a", 0.5)
        text = prometheus_text(m, extra_gauges={"spans_buffered": 7})
        assert 'datafusion_tpu_events_total{name="x.y"} 3' in text
        assert 'stage="stage-a"' in text
        assert 'datafusion_tpu_gauge{name="spans_buffered"} 7' in text
