"""Legacy golden-corpus conformance tests.

`test/data/expected/` holds 66 golden outputs from the pre-rewrite
reference engine (SURVEY §4: "a ready-made conformance suite the
rebuild can re-attach"); the rewrite never re-attached them and the
defining test sources are not in the v0.5.1 snapshot.  The queries
below were reconstructed by matching each golden file against the
fixture data (`all_types_flat.csv/parquet`, `numerics.csv`,
`null_test.csv`, `uk_cities.csv`).

Comparison is type-aware: float fields compare by parsed value
(tolerating shortest-repr formatting differences between engines),
ints/bools/strings compare exactly.

Excluded goldens, with reasons:
- c_int8_{eq,gt,gteq,lt,lteq,col_eq,scalar_gt}.csv are EMPTY — the
  pre-rewrite engine returned no rows for int8-vs-literal ordered
  comparisons (its noteq golden proves the data has matching rows, so
  these are artifacts of a reference bug, not a spec).
- aggregate goldens' MIN/MAX(c_utf8) fields: the golden prints the
  same string for both min and max per group — another pre-rewrite
  artifact; the numeric fields of those rows ARE asserted.
- parquet aggregate SUM(c_int32)/SUM(c_int64): golden values reflect
  the reference's 32/64-bit overflow behavior (c_int32 sum shows
  i32::MAX); this engine accumulates in 64-bit.
- test_sqrt/test_limit use a 1..10 integer table absent from the
  fixtures; rebuilt in-memory with the same values.
- test_df_udf_udt is the DataFrame-API twin of test_sql_udf_udt (same
  golden), asserted through the SQL path.
"""

import csv
import math
import os

import numpy as np
import pytest

from datafusion_tpu import DataType, Field, Schema
from datafusion_tpu.exec.context import ExecutionContext

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "test", "data")
EXPECTED = os.path.join(DATA, "expected")

ALL_TYPES_SCHEMA = Schema(
    [
        Field("c_bool", DataType.BOOLEAN, False),
        Field("c_uint8", DataType.UINT8, False),
        Field("c_uint16", DataType.UINT16, False),
        Field("c_uint32", DataType.UINT32, False),
        Field("c_uint64", DataType.UINT64, False),
        Field("c_int8", DataType.INT8, False),
        Field("c_int16", DataType.INT16, False),
        Field("c_int32", DataType.INT32, False),
        Field("c_int64", DataType.INT64, False),
        Field("c_float32", DataType.FLOAT32, False),
        Field("c_float64", DataType.FLOAT64, False),
        Field("c_utf8", DataType.UTF8, False),
    ]
)

NULL_TEST_SCHEMA = Schema(
    [
        Field("c_int", DataType.INT32, True),
        Field("c_float", DataType.FLOAT32, True),
        Field("c_string", DataType.UTF8, True),
        Field("c_bool", DataType.BOOLEAN, True),
    ]
)

NUMERICS_SCHEMA = Schema(
    [
        Field("a", DataType.INT64, False),
        Field("b", DataType.INT64, False),
        Field("a_f", DataType.FLOAT32, False),
        Field("b_f", DataType.FLOAT32, False),
    ]
)

UK_SCHEMA = Schema(
    [
        Field("city", DataType.UTF8, False),
        Field("lat", DataType.FLOAT64, False),
        Field("lng", DataType.FLOAT64, False),
    ]
)


@pytest.fixture(scope="module")
def ctx():
    c = ExecutionContext(batch_size=4096)
    c.register_csv("all_types", os.path.join(DATA, "all_types_flat.csv"),
                   ALL_TYPES_SCHEMA, has_header=False)
    c.register_parquet("all_types_pq", os.path.join(DATA, "all_types_flat.parquet"))
    c.register_csv("null_test", os.path.join(DATA, "null_test.csv"),
                   NULL_TEST_SCHEMA, has_header=True)
    c.register_csv("numerics", os.path.join(DATA, "numerics.csv"),
                   NUMERICS_SCHEMA, has_header=True)
    c.register_csv("uk_cities", os.path.join(DATA, "uk_cities.csv"),
                   UK_SCHEMA, has_header=False)
    return c


def golden_lines(name):
    with open(os.path.join(EXPECTED, name), encoding="utf-8") as f:
        return [l for l in f.read().splitlines() if l != ""]


def _parse_field(s: str):
    s = s.strip()
    if s in ("true", "false"):
        return s == "true"
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        return s


def _value(v):
    if v is None:
        return None
    if isinstance(v, (np.bool_, bool)):
        return bool(v)
    if isinstance(v, (np.integer, int)):
        return int(v)
    if isinstance(v, (np.floating, float)):
        return float(v)
    return str(v)


def _eq(got, want):
    if isinstance(want, float) or isinstance(got, float):
        g, w = float(got), float(want)
        if math.isnan(g) and math.isnan(w):
            return True
        # shortest-repr differences between engines: compare values,
        # tolerating one half-ulp of float32 for f32-printed fields
        return math.isclose(g, w, rel_tol=1e-6, abs_tol=1e-9)
    return got == want


def assert_rows_match(table, name, left_fields=None, right_fields=0, ncols=None):
    """Compare engine output against a golden file.

    Golden rows are unquoted comma-joins, so utf8 fields may contain
    commas: `left_fields` takes that many fields from the left and
    `right_fields` from the right of each golden line, skipping the
    middle; None compares every field (only safe when the final column
    is the sole utf8 one, handled via maxsplit).
    """
    rows = table.to_rows()
    want = golden_lines(name)
    assert len(rows) == len(want), f"{name}: {len(rows)} rows vs golden {len(want)}"
    for row, line in zip(rows, want):
        if left_fields is None:
            n = ncols if ncols is not None else len(row)
            fields = line.split(",", n - 1)
            got_vals = [_value(v) for v in row]
        else:
            parts = line.split(",")
            fields = parts[:left_fields] + (
                parts[len(parts) - right_fields:] if right_fields else []
            )
            got_vals = [_value(v) for v in row[: left_fields]] + (
                [_value(v) for v in row[len(row) - right_fields:]]
                if right_fields
                else []
            )
        assert len(got_vals) == len(fields), f"{name}: field count {len(got_vals)} vs {len(fields)}\n{line}"
        for g, f in zip(got_vals, fields):
            w = _parse_field(f)
            assert _eq(g, w), f"{name}: {g!r} != {w!r} in line {line!r}"


# ---------------------------------------------------------------- filters --

FILTER_CASES = [
    # (golden file, SQL)
    ("c_int8_noteq.csv", "SELECT c_int8 FROM all_types WHERE c_int8 != 0"),
    ("c_int8_positive.csv", "SELECT c_int8 FROM all_types WHERE c_int8 >= 0"),
    ("c_int8_negative.csv", "SELECT c_int8 FROM all_types WHERE c_int8 < 0"),
    ("c_int8_range_inclusive.csv",
     "SELECT c_int8 FROM all_types WHERE c_int8 >= 2 AND c_int8 <= 100"),
    ("c_int8_range_exclusive.csv",
     "SELECT c_int8 FROM all_types WHERE c_int8 > 100"),
    ("c_int8_col_gt.csv", "SELECT c_int8 FROM all_types WHERE c_int8 > c_int16"),
    ("c_int8_col_gteq.csv", "SELECT c_int8 FROM all_types WHERE c_int8 >= c_int16"),
    ("c_int8_col_lt.csv", "SELECT c_int8 FROM all_types WHERE c_int8 < c_int16"),
    ("c_int8_col_lteq.csv", "SELECT c_int8 FROM all_types WHERE c_int8 <= c_int16"),
    ("c_int8_col_noteq.csv", "SELECT c_int8 FROM all_types WHERE c_int8 != c_int16"),
    ("c_int16_positive.csv", "SELECT c_int16 FROM all_types WHERE c_int16 >= 0"),
    ("c_int16_negative.csv", "SELECT c_int16 FROM all_types WHERE c_int16 < 0"),
    ("c_int32_positive.csv", "SELECT c_int32 FROM all_types WHERE c_int32 >= 0"),
    ("c_int32_negative.csv", "SELECT c_int32 FROM all_types WHERE c_int32 < 0"),
    ("c_int64_positive.csv", "SELECT c_int64 FROM all_types WHERE c_int64 >= 0"),
    ("c_int64_negative.csv", "SELECT c_int64 FROM all_types WHERE c_int64 < 0"),
    ("c_float32_high.csv", "SELECT c_float32 FROM all_types WHERE c_float32 > 0.5"),
    ("c_float32_low.csv", "SELECT c_float32 FROM all_types WHERE c_float32 < 0.5"),
    ("c_float64_high.csv", "SELECT c_float64 FROM all_types WHERE c_float64 > 0.5"),
    ("c_float64_low.csv", "SELECT c_float64 FROM all_types WHERE c_float64 < 0.5"),
]

CAST_CASES = [
    ("c_int8_cast.csv",
     "SELECT CAST(c_int8 AS SMALLINT) FROM all_types WHERE c_int8 < 0"),
    ("c_int16_cast.csv",
     "SELECT CAST(c_int16 AS INT) FROM all_types WHERE c_int16 < 0"),
    ("c_int32_cast.csv",
     "SELECT CAST(c_int32 AS BIGINT) FROM all_types WHERE c_int32 < 0"),
    ("c_int64_cast.csv",
     "SELECT c_int64 FROM all_types WHERE c_int64 < 0"),
    ("c_uint8_cast.csv", "SELECT CAST(c_uint8 AS SMALLINT) FROM all_types"),
    ("c_uint16_cast.csv", "SELECT CAST(c_uint16 AS INT) FROM all_types"),
    ("c_uint32_cast.csv", "SELECT CAST(c_uint32 AS BIGINT) FROM all_types"),
    ("c_uint64_cast.csv", "SELECT c_uint64 FROM all_types"),
    ("c_float32_cast.csv",
     "SELECT c_float32 FROM all_types WHERE c_float32 < CAST(0.5 AS FLOAT)"),
    ("c_float64_cast.csv",
     "SELECT c_float64 FROM all_types WHERE c_float64 < CAST(0.5 AS DOUBLE)"),
    # uint32-literal coercion family: predicates true for every row
    ("c_float32_high_uint32.csv",
     "SELECT c_float32 FROM all_types WHERE c_float32 > CAST(0 AS INT)"),
    ("c_float32_low_uint32.csv",
     "SELECT c_float32 FROM all_types WHERE c_float32 < CAST(1 AS INT)"),
    ("c_float32_cast_uint32.csv",
     "SELECT c_float32 FROM all_types WHERE c_float32 <= CAST(1 AS INT)"),
]


class TestFilterGoldens:
    @pytest.mark.parametrize("name,sql", FILTER_CASES, ids=[c[0] for c in FILTER_CASES])
    def test_filter(self, ctx, name, sql):
        assert_rows_match(ctx.sql_collect(sql), name)

    @pytest.mark.parametrize("name,sql", CAST_CASES, ids=[c[0] for c in CAST_CASES])
    def test_cast(self, ctx, name, sql):
        assert_rows_match(ctx.sql_collect(sql), name)

    def test_query_all_types(self, ctx):
        table = ctx.sql_collect(
            "SELECT c_bool, c_uint8, c_uint16, c_uint32, c_uint64, c_int8, "
            "c_int16, c_int32, c_int64, c_float32, c_float64, c_utf8 "
            "FROM all_types WHERE c_float64 < 0.1"
        )
        assert_rows_match(table, "csv_query_all_types.csv", ncols=12)

    def test_parquet_query_all_types(self, ctx):
        table = ctx.sql_collect(
            "SELECT c_bool, c_uint8, c_uint16, c_uint32, c_uint64, c_int8, "
            "c_int16, c_int32, c_int64, c_float32, c_float64, c_utf8 "
            "FROM all_types_pq WHERE c_float64 < 0.1"
        )
        assert_rows_match(table, "parquet_query_all_types.csv", ncols=12)


# ----------------------------------------------------------------- nulls --

class TestNullGoldens:
    def test_is_null(self, ctx):
        assert_rows_match(
            ctx.sql_collect("SELECT c_int FROM null_test WHERE c_float IS NULL"),
            "is_null_csv.csv",
        )

    def test_is_not_null(self, ctx):
        assert_rows_match(
            ctx.sql_collect("SELECT c_int FROM null_test WHERE c_float IS NOT NULL"),
            "is_not_null_csv.csv",
        )


# -------------------------------------------------------------- numerics --

NUMERIC_OPS = [
    ("numerics_plus.csv", "+"),
    ("numerics_minus.csv", "-"),
    ("numerics_multiply.csv", "*"),
    ("numerics_divide.csv", "/"),
    ("numerics_modulo.csv", "%"),
]


class TestNumericsGoldens:
    @pytest.mark.parametrize("name,op", NUMERIC_OPS, ids=[c[0] for c in NUMERIC_OPS])
    def test_binary_op(self, ctx, name, op):
        sql = (
            f"SELECT a {op} b, a {op} 2, a {op} 2.5, "
            f"a_f {op} b_f, a_f {op} 2, a_f {op} 2.5 FROM numerics"
        )
        assert_rows_match(ctx.sql_collect(sql), name)


# ------------------------------------------------------------ aggregates --

class TestAggregateGoldens:
    def test_csv_aggregate_all_types(self, ctx):
        # golden layout: count, count, then min/max per column in order;
        # the final MIN/MAX(c_utf8) pair is excluded (pre-rewrite
        # artifact: golden shows the same string for both)
        table = ctx.sql_collect(
            "SELECT COUNT(1), COUNT(c_bool), "
            "MIN(c_bool), MAX(c_bool), MIN(c_uint8), MAX(c_uint8), "
            "MIN(c_uint16), MAX(c_uint16), MIN(c_uint32), MAX(c_uint32), "
            "MIN(c_uint64), MAX(c_uint64), MIN(c_int8), MAX(c_int8), "
            "MIN(c_int16), MAX(c_int16), MIN(c_int32), MAX(c_int32), "
            "MIN(c_int64), MAX(c_int64), MIN(c_float32), MAX(c_float32), "
            "MIN(c_float64), MAX(c_float64) FROM all_types"
        )
        assert_rows_match(table, "csv_aggregate_all_types.csv", left_fields=24)

    def test_parquet_aggregate_all_types(self, ctx):
        # same 24 leading fields, plus the tail of SUMs; SUM(c_int32) and
        # SUM(c_int64) are excluded (reference overflow artifacts: the
        # golden's int32 sum is exactly i32::MAX)
        table = ctx.sql_collect(
            "SELECT COUNT(1), COUNT(c_bool), "
            "MIN(c_bool), MAX(c_bool), MIN(c_uint8), MAX(c_uint8), "
            "MIN(c_uint16), MAX(c_uint16), MIN(c_uint32), MAX(c_uint32), "
            "MIN(c_uint64), MAX(c_uint64), MIN(c_int8), MAX(c_int8), "
            "MIN(c_int16), MAX(c_int16), MIN(c_int32), MAX(c_int32), "
            "MIN(c_int64), MAX(c_int64), MIN(c_float32), MAX(c_float32), "
            "MIN(c_float64), MAX(c_float64) FROM all_types_pq"
        )
        assert_rows_match(table, "parquet_aggregate_all_types.csv", left_fields=24)
        # narrow-int sums widen via CAST: the reference planner types
        # SUM(x) as x's type, but the golden's values are the widened
        # sums (SUM(c_int8) = -169, outside int8)
        sums = ctx.sql_collect(
            "SELECT SUM(CAST(c_int8 AS BIGINT)), SUM(CAST(c_int16 AS BIGINT)), "
            "SUM(CAST(c_uint8 AS INT)), SUM(CAST(c_uint16 AS INT)), "
            "SUM(CAST(c_uint32 AS BIGINT)), SUM(c_uint64), "
            "SUM(c_float32), SUM(c_float64) "
            "FROM all_types_pq"
        ).to_rows()[0]
        tail = [_parse_field(f) for f in
                golden_lines("parquet_aggregate_all_types.csv")[0].split(",")[-10:]]
        want = [tail[0], tail[1], tail[4], tail[5], tail[6], tail[7], tail[8], tail[9]]
        for g, w in zip(sums, want):
            assert _eq(_value(g), w), f"SUM mismatch: {g} vs {w}"

    def test_csv_aggregate_by_c_bool(self, ctx):
        table = ctx.sql_collect(
            "SELECT c_bool, MIN(c_uint8), MAX(c_uint8), "
            "MIN(c_uint16), MAX(c_uint16), MIN(c_uint32), MAX(c_uint32), "
            "MIN(c_uint64), MAX(c_uint64), MIN(c_int8), MAX(c_int8), "
            "MIN(c_int16), MAX(c_int16), MIN(c_int32), MAX(c_int32), "
            "MIN(c_int64), MAX(c_int64), MIN(c_float32), MAX(c_float32), "
            "MIN(c_float64), MAX(c_float64) FROM all_types GROUP BY c_bool"
        )
        rows = sorted(table.to_rows(), key=lambda r: r[0])  # false, true
        want = golden_lines("csv_aggregate_by_c_bool.csv")
        assert len(rows) == len(want)
        for row, line in zip(rows, want):
            fields = [_parse_field(f) for f in line.split(",")[:21]]
            for g, w in zip([_value(v) for v in row], fields):
                assert _eq(g, w), f"{g!r} != {w!r} in {line[:80]!r}"

    def test_sql_min_max(self, ctx):
        assert_rows_match(
            ctx.sql_collect(
                "SELECT MIN(lat), MAX(lat), MIN(lng), MAX(lng) FROM uk_cities"
            ),
            "test_sql_min_max.csv",
        )


# -------------------------------------------------- uk_cities / UDF / misc --

class TestUkCitiesGoldens:
    def test_filter(self, ctx):
        table = ctx.sql_collect(
            "SELECT city, lat, lng FROM uk_cities WHERE lat > 52.0"
        )
        rows = table.to_rows()
        want = golden_lines("test_filter.csv")
        assert len(rows) == len(want)
        for (city, lat, lng), line in zip(rows, want):
            # city names contain commas: take lat/lng from the right
            parts = line.split(",")
            assert _eq(float(lat), float(parts[-2]))
            assert _eq(float(lng), float(parts[-1]))
            assert ",".join(parts[:-2]) == city

    def _geo_ctx(self):
        from datafusion_tpu.cli import make_context

        c = make_context()
        c.register_csv("uk_cities", os.path.join(DATA, "uk_cities.csv"),
                       UK_SCHEMA, has_header=False)
        return c

    def test_simple_predicate(self):
        ctx = self._geo_ctx()
        table = ctx.sql_collect(
            "SELECT ST_AsText(ST_Point(lat, lng)) FROM uk_cities WHERE lat < 53.0"
        )
        got = [r[0] for r in table.to_rows()]
        assert got == golden_lines("test_simple_predicate.csv")

    def test_chaining_functions(self):
        ctx = self._geo_ctx()
        table = ctx.sql_collect(
            "SELECT ST_AsText(ST_Point(lat, lng)) FROM uk_cities"
        )
        assert [r[0] for r in table.to_rows()] == golden_lines(
            "test_chaining_functions.csv"
        )

    def test_sql_udf_udt(self):
        # the golden prints the Point UDT's Display: "lat, lng"
        ctx = self._geo_ctx()
        table = ctx.sql_collect("SELECT ST_Point(lat, lng) FROM uk_cities")
        assert [r[0] for r in table.to_rows()] == golden_lines("test_sql_udf_udt.csv")

    def test_df_udf_udt_same_golden(self):
        assert golden_lines("test_df_udf_udt.csv") == golden_lines(
            "test_sql_udf_udt.csv"
        )


class TestMiscGoldens:
    def test_cast_null_test(self, ctx):
        table = ctx.sql_collect(
            "SELECT c_int, CAST(c_int AS SMALLINT), CAST(c_int AS INT), "
            "CAST(c_int AS BIGINT), c_float, CAST(c_float AS FLOAT), "
            "c_string, c_string FROM null_test WHERE c_float < 3.0"
        )
        assert_rows_match(table, "test_cast.csv", left_fields=6)

    def test_sqrt(self):
        # the 1..10 fixture table is not in the snapshot; rebuild it
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        schema = Schema([Field("c_int", DataType.INT64, False)])
        batch = make_host_batch(schema, [np.arange(1, 11, dtype=np.int64)], [None])
        c = ExecutionContext()
        c.register_datasource("t", MemoryDataSource(schema, [batch]))
        table = c.sql_collect("SELECT c_int, sqrt(c_int) FROM t")
        assert_rows_match(table, "test_sqrt.csv")

    def test_limit(self):
        from datafusion_tpu.exec.batch import make_host_batch
        from datafusion_tpu.exec.datasource import MemoryDataSource

        schema = Schema([Field("c_int", DataType.INT64, False)])
        batch = make_host_batch(schema, [np.arange(1, 11, dtype=np.int64)], [None])
        c = ExecutionContext()
        c.register_datasource("t", MemoryDataSource(schema, [batch]))
        table = c.sql_collect("SELECT c_int, sqrt(c_int) FROM t LIMIT 5")
        assert_rows_match(table, "test_limit.csv")
