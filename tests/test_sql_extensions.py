"""SQL surface completed beyond the reference's TODOs: wildcard,
HAVING / ORDER BY / LIMIT over aggregates, MIN/MAX over strings, the
PhysicalPlan executor (Write/Show), unsigned-literal adaptation."""

import os

import pytest

from datafusion_tpu import DataType, Field, Schema
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.parallel import PhysicalPlan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "test", "data")

UK_SCHEMA = Schema(
    [
        Field("city", DataType.UTF8, False),
        Field("lat", DataType.FLOAT64, False),
        Field("lng", DataType.FLOAT64, False),
    ]
)


@pytest.fixture()
def ctx():
    c = ExecutionContext(batch_size=7)  # multi-batch: dictionaries grow
    c.register_csv("uk", os.path.join(DATA, "uk_cities.csv"),
                   UK_SCHEMA, has_header=False)
    return c


def _cities():
    import csv

    with open(os.path.join(DATA, "uk_cities.csv")) as f:
        return [(r[0], float(r[1]), float(r[2])) for r in csv.reader(f)]


class TestAggregatePathCompletion:
    def test_order_by_aggregate_with_limit(self, ctx):
        got = ctx.sql_collect(
            "SELECT city, MIN(lat) FROM uk GROUP BY city ORDER BY MIN(lat) LIMIT 3"
        ).to_rows()
        want = sorted(((c, lat) for c, lat, _ in _cities()), key=lambda t: t[1])[:3]
        assert got == want

    def test_order_by_aggregate_desc(self, ctx):
        got = ctx.sql_collect(
            "SELECT city, MAX(lat) FROM uk GROUP BY city ORDER BY MAX(lat) DESC LIMIT 2"
        ).to_rows()
        want = sorted(((c, lat) for c, lat, _ in _cities()),
                      key=lambda t: -t[1])[:2]
        assert got == want

    def test_having_filters_groups(self, tmp_path):
        p = tmp_path / "t.csv"
        p.write_text("k,v\na,1\na,2\nb,3\nb,4\nb,5\nc,6\n")
        schema = Schema([Field("k", DataType.UTF8, False),
                         Field("v", DataType.INT64, False)])
        c = ExecutionContext()
        c.register_csv("t", str(p), schema)
        got = sorted(c.sql_collect(
            "SELECT k, COUNT(1) FROM t GROUP BY k HAVING COUNT(1) > 1"
        ).to_rows())
        assert got == [("a", 2), ("b", 3)]

    def test_having_on_sum_with_order(self, tmp_path):
        p = tmp_path / "t.csv"
        p.write_text("k,v\na,1\na,2\nb,30\nc,5\nc,6\n")
        schema = Schema([Field("k", DataType.UTF8, False),
                         Field("v", DataType.INT64, False)])
        c = ExecutionContext()
        c.register_csv("t", str(p), schema)
        got = c.sql_collect(
            "SELECT k, SUM(v) FROM t GROUP BY k HAVING SUM(v) > 3 "
            "ORDER BY SUM(v) DESC"
        ).to_rows()
        assert got == [("b", 30), ("c", 11)]

    def test_aggregate_not_in_select_rejected(self, ctx):
        with pytest.raises(Exception, match="SELECT list"):
            ctx.sql_collect(
                "SELECT city, MIN(lat) FROM uk GROUP BY city ORDER BY MAX(lat)"
            )


class TestStringMinMax:
    def test_global_min_max_city(self, ctx):
        got = ctx.sql_collect("SELECT MIN(city), MAX(city) FROM uk").to_rows()
        cities = [c for c, _, _ in _cities()]
        assert got == [(min(cities), max(cities))]

    def test_grouped_string_min_max_with_nulls(self, tmp_path):
        p = tmp_path / "s.csv"
        p.write_text("k,s\n1,beta\n1,\n2,zeta\n1,alpha\n2,gamma\n")
        schema = Schema([Field("k", DataType.INT64, False),
                         Field("s", DataType.UTF8, True)])
        c = ExecutionContext()
        c.register_csv("t", str(p), schema)
        got = sorted(c.sql_collect(
            "SELECT k, MIN(s), MAX(s) FROM t GROUP BY k"
        ).to_rows())
        assert got == [(1, "alpha", "beta"), (2, "gamma", "zeta")]

    def test_partitioned_string_minmax_falls_back(self, tmp_path):
        from datafusion_tpu.parallel import PartitionedContext, make_mesh

        paths = []
        for i, rows in enumerate([["b", "c"], ["a", "d"]]):
            f = tmp_path / f"p{i}.csv"
            f.write_text("s\n" + "".join(f"{r}\n" for r in rows))
            paths.append(str(f))
        schema = Schema([Field("s", DataType.UTF8, False)])
        c = PartitionedContext(mesh=make_mesh(2))
        c.register_partitioned_csv("t", paths, schema)
        assert c.sql_collect("SELECT MIN(s), MAX(s) FROM t").to_rows() == [("a", "d")]


class TestPhysicalExecutor:
    def test_write_and_show(self, ctx, tmp_path):
        plan = ctx._plan(
            __import__("datafusion_tpu.sql.parser", fromlist=["parse_sql"]).parse_sql(
                "SELECT city, lat FROM uk WHERE lat > 57"
            )
        )
        out = tmp_path / "out.csv"
        n = ctx.execute_physical(
            PhysicalPlan("write", plan, filename=str(out), file_format="csv")
        )
        assert n == 3
        lines = out.read_text().splitlines()
        assert lines[0] == "city,lat" and len(lines) == 4

        shown = ctx.execute_physical(PhysicalPlan("show", plan, count=2))
        assert shown.num_rows == 2

    def test_interactive_round_trips_wire_format(self, ctx):
        from datafusion_tpu.exec.materialize import collect
        from datafusion_tpu.sql.parser import parse_sql

        plan = ctx._plan(parse_sql("SELECT COUNT(1) FROM uk"))
        pp = PhysicalPlan.from_json(PhysicalPlan("interactive", plan).to_json())
        rel = ctx.execute_physical(pp)
        assert collect(rel).to_rows() == [(37,)]
