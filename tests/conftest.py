"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the standard JAX trick for
testing multi-chip sharding without TPUs) — equivalent in spirit to the
reference's planned docker-compose multi-worker smoketest
(`scripts/smoketest.sh:30-66`), but hermetic.  Must run before jax is
imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# this machine's sitecustomize registers the TPU tunnel backend and
# overrides the env var at interpreter boot; re-pin the config too
import jax

jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest


@pytest.fixture(scope="session")
def test_data_dir():
    """Directory of CSV/NDJSON/Parquet fixtures (mirrored from the
    reference's `test/data/`)."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "test", "data"
    )
