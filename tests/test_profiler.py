"""Host-side sampling profiler (obs/profiler.py) + unified debug HTTP
plane (obs/httpd.py): sampler lifecycle, collapsed/speedscope output,
phase and trace attribution, the DF005/DF007 lint contract on the
sample path, host-resource gauges, the hardened HBM capacity probe,
the debug endpoints against an in-process server, and the
`debug-bundle` CLI."""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from datafusion_tpu.obs import profiler
from datafusion_tpu.utils import metrics as umetrics
from datafusion_tpu.utils.metrics import METRICS


def _busy_under_timer(stage: str, stop: threading.Event):
    with METRICS.timer(stage):
        x = 0
        while not stop.is_set():
            x += 1
        return x


def _capture_busy(stage: str = "scan.parse", seconds: float = 0.4,
                  hz: float = 250.0):
    """Run a busy thread inside `with METRICS.timer(stage)` under a
    scoped capture; returns the report."""
    stop = threading.Event()
    t = threading.Thread(
        target=_busy_under_timer, args=(stage, stop),
        name=f"busy-{stage}", daemon=True,
    )
    with profiler.profile(hz=hz, name="test") as cap:
        t.start()
        time.sleep(seconds)
        stop.set()
        t.join()
    return cap.report()


class TestSamplerLifecycle:
    def test_no_thread_when_idle(self):
        assert not profiler.PROFILER.running()
        assert profiler.PROFILER.active_captures() == 0
        # the publication tables are torn down too: disabled-mode
        # Metrics.timer pays one global read, publishes nothing
        assert umetrics.PROFILE_STAGES is None
        assert umetrics.PROFILE_TRACES is None

    def test_start_stop_tears_down_thread_and_tables(self):
        cap = profiler.PROFILER.start_capture(hz=200)
        try:
            assert profiler.PROFILER.running()
            assert umetrics.PROFILE_STAGES is not None
        finally:
            rep = profiler.PROFILER.stop_capture(cap)
        assert not profiler.PROFILER.running()
        assert umetrics.PROFILE_STAGES is None
        assert rep.duration_s >= 0

    def test_overlapping_captures_share_one_thread(self):
        a = profiler.PROFILER.start_capture(hz=100)
        b = profiler.PROFILER.start_capture(hz=100)
        try:
            assert profiler.PROFILER.active_captures() == 2
            threads = [
                t for t in threading.enumerate()
                if t.name == "df-tpu-profiler"
            ]
            assert len(threads) == 1
        finally:
            profiler.PROFILER.stop_capture(a)
            assert profiler.PROFILER.running()  # b still sampling
            profiler.PROFILER.stop_capture(b)
        assert not profiler.PROFILER.running()

    def test_continuous_default_off_and_idempotent(self):
        # default env (unset) = no continuous capture, no thread
        assert not profiler.continuous_running()
        assert profiler.maybe_start_continuous() is False
        assert profiler.continuous_report() is None

    def test_disabled_scope_is_noop(self):
        with profiler.profile(enabled=False) as cap:
            assert cap is None
        assert not profiler.PROFILER.running()

    def test_samples_accumulate(self):
        rep = _capture_busy(seconds=0.3)
        assert rep.samples > 5
        assert rep.hz == 250.0


class TestAttribution:
    def test_phase_attribution_via_stage_timer(self):
        # a thread busy inside `with METRICS.timer("scan.parse")` must
        # attribute to the "decode" phase (obs/device._PHASE_TIMERS)
        rep = _capture_busy("scan.parse", seconds=0.4)
        phases = rep.phase_samples()
        assert phases.get("decode", 0) > 3, phases
        # and the busy function itself is a top decode frame
        tops = [label for label, _n in rep.top_frames(5, "decode")]
        assert any("_busy_under_timer" in t or "is_set" in t
                   for t in tops), tops

    def test_phase_attribution_execute(self):
        rep = _capture_busy("device.dispatch", seconds=0.3)
        assert rep.phase_samples().get("execute", 0) > 3

    def test_unknown_stage_maps_to_other(self):
        rep = _capture_busy("parse", seconds=0.3)  # not a phase timer
        phases = rep.phase_samples()
        assert phases.get("other", 0) > 3
        assert "decode" not in phases or phases["decode"] < phases["other"]

    def test_trace_correlation_via_session(self):
        from datafusion_tpu.obs import trace as obs_trace

        stop = threading.Event()
        tid_trace = {}

        def traced_busy():
            with obs_trace.session() as tc:
                tid_trace["trace_id"] = tc.trace_id
                x = 0
                while not stop.is_set():
                    x += 1

        t = threading.Thread(target=traced_busy, daemon=True)
        with profiler.profile(hz=250) as cap:
            t.start()
            time.sleep(0.4)
            stop.set()
            t.join()
        rep = cap.report()
        assert rep.trace_counts.get(tid_trace["trace_id"], 0) > 3, (
            rep.trace_counts
        )
        # table restored after the session ended (inside the capture
        # the thread unpublished on session exit)
        assert umetrics.PROFILE_TRACES is None

    def test_trace_correlation_via_adopt(self):
        from datafusion_tpu.obs import trace as obs_trace

        with profiler.profile(hz=100):
            with obs_trace.adopt({"trace_id": "feedbeef00000000"}):
                tbl = umetrics.PROFILE_TRACES
                assert tbl[threading.get_ident()] == "feedbeef00000000"
            assert threading.get_ident() not in umetrics.PROFILE_TRACES


class TestOutputFormats:
    def test_collapsed_round_trips_counts(self):
        rep = _capture_busy(seconds=0.3)
        text = rep.collapsed()
        assert text
        total = 0
        for line in text.splitlines():
            stack, _, count = line.rpartition(" ")
            assert stack and count.isdigit(), line
            assert ";" in stack  # thread prefix + >=1 frame
            total += int(count)
        assert total == rep.samples

    def test_speedscope_schema_and_round_trip(self):
        rep = _capture_busy(seconds=0.3)
        doc = rep.speedscope()
        # schema essentials speedscope.app requires
        assert doc["$schema"].endswith("file-format-schema.json")
        assert doc["shared"]["frames"] and doc["profiles"]
        json.dumps(doc)  # serializable
        # round-trip: frames table + samples/weights reconstruct the
        # exact per-stack sample counts
        rebuilt: dict = {}
        for prof in doc["profiles"]:
            assert prof["type"] == "sampled"
            assert len(prof["samples"]) == len(prof["weights"])
            assert prof["endValue"] == sum(prof["weights"])
            for stack, w in zip(prof["samples"], prof["weights"]):
                frames = tuple(
                    doc["shared"]["frames"][i]["name"] for i in stack
                )
                rebuilt[frames] = rebuilt.get(frames, 0) + w
        want: dict = {}
        for (_tid, _phase, frames), n in rep.stacks.items():
            want[frames] = want.get(frames, 0) + n
        assert rebuilt == want

    def test_to_json_is_bounded_and_complete(self):
        rep = _capture_busy(seconds=0.3)
        doc = rep.to_json(max_lines=2)
        assert doc["samples"] == rep.samples
        assert doc["phases"]
        assert len(doc["collapsed"].splitlines()) <= 2
        json.dumps(doc)

    def test_stack_cap_folds_into_truncated(self):
        cap = profiler.ProfileCapture(hz=10)
        saved = profiler._MAX_STACKS
        profiler.configure(max_stacks=2)
        try:
            cap._fold(1, "other", ("a",), None)
            cap._fold(1, "other", ("b",), None)
            cap._fold(1, "other", ("c",), None)  # over the cap
            cap._fold(1, "other", ("d",), None)
        finally:
            profiler.configure(max_stacks=saved)
        assert cap.samples == 4
        assert cap.truncated == 2
        key = (1, "other", ("(truncated)",))
        assert cap.stacks[key] == 2


class TestLintContract:
    """DF005 (no locks) and DF007 (no blocking IO) cover the sampler
    path — both the real module staying clean and the rules actually
    firing on synthetic violations."""

    def _lint(self, src: str, relpath: str = "datafusion_tpu/obs/profiler.py"):
        from datafusion_tpu.analysis import lint

        return lint.lint_source(src, relpath)

    def test_real_module_is_clean(self):
        import datafusion_tpu.obs.profiler as mod

        with open(mod.__file__, "r", encoding="utf-8") as f:
            findings = self._lint(f.read())
        assert findings == [], findings

    def test_df005_catches_lock_in_fold(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def _fold(self, k):\n"
            "        with self._lock:\n"
            "            self.d[k] = 1\n"
        )
        rules = {f.rule for f in self._lint(src)}
        assert "DF005" in rules

    def test_df007_catches_blocking_io_in_sampler(self):
        src = (
            "class P:\n"
            "    def _sample_once(self, me):\n"
            "        with open('/tmp/x', 'w') as f:\n"
            "            f.write('x')\n"
            "    def _run(self):\n"
            "        import time\n"
            "        time.sleep(1)\n"
        )
        findings = self._lint(src)
        df007 = [f for f in findings if f.rule == "DF007"]
        names = " ".join(f.message for f in df007)
        assert "open()" in names and "sleep()" in names

    def test_df007_ignores_non_sampler_functions(self):
        src = (
            "def report():\n"
            "    with open('/tmp/x', 'w') as f:\n"
            "        f.write('x')\n"
        )
        assert [f for f in self._lint(src) if f.rule == "DF007"] == []

    def test_metrics_stage_helpers_stay_lock_free(self):
        import datafusion_tpu.utils.metrics as mod

        with open(mod.__file__, "r", encoding="utf-8") as f:
            findings = self._lint(
                f.read(), "datafusion_tpu/utils/metrics.py"
            )
        assert findings == [], findings


class TestHostGauges:
    def test_refresh_sets_rss_and_fds(self):
        from datafusion_tpu.obs.aggregate import refresh_host_gauges

        g = refresh_host_gauges()
        # Linux CI: /proc exists; the gauges are real and positive
        assert g.get("host.rss_bytes", 0) > 0
        assert g.get("host.rss_peak_bytes", 0) >= g["host.rss_bytes"] // 2
        assert g.get("host.open_fds", 0) > 0
        assert METRICS.gauges["host.rss_bytes"] == g["host.rss_bytes"]

    def test_node_snapshot_carries_host_gauges(self):
        from datafusion_tpu.obs.aggregate import node_snapshot

        snap = node_snapshot()
        assert snap["gauges"].get("host.rss_bytes", 0) > 0

    def test_fleet_sums_host_gauges(self):
        from datafusion_tpu.obs.aggregate import FleetAggregator

        agg = FleetAggregator(include_local=False)
        for i, rss in enumerate((100, 250)):
            agg.ingest(f"w{i}", {
                "ts": time.time(), "histograms": {}, "counts": {},
                "gauges": {"host.rss_bytes": rss, "host.open_fds": 10},
            })
        g = agg.gauges()
        assert g["fleet.host.rss_bytes"] == 350
        assert g["fleet.host.open_fds"] == 20

    def test_gc_pause_accrues(self):
        import gc

        from datafusion_tpu.obs import aggregate as agg

        assert agg._gc_callback in gc.callbacks  # installed at import
        before = METRICS.counts.get("host.gc_collections", 0)
        gc.collect()
        assert METRICS.counts.get("host.gc_collections", 0) > before
        assert METRICS.timings.get("host.gc_pause", 0) >= 0


class TestCapacityProbe:
    """memory_stats() hardening: partial/raising/non-dict backends go
    cleanly dormant (None) instead of risking a KeyError path."""

    @pytest.fixture(autouse=True)
    def _no_env(self, monkeypatch):
        monkeypatch.delenv("DATAFUSION_TPU_HBM_BYTES", raising=False)

    def _with_devices(self, monkeypatch, devices):
        import jax

        from datafusion_tpu.obs import device as obs_device

        monkeypatch.setattr(jax, "devices", lambda: devices)
        return obs_device.hbm_capacity_bytes()

    def test_full_stats_sum(self, monkeypatch):
        class _Dev:
            def memory_stats(self):
                return {"bytes_limit": 1 << 30, "bytes_in_use": 5}

        assert self._with_devices(monkeypatch, [_Dev(), _Dev()]) \
            == 2 * (1 << 30)

    def test_partial_dict_without_limit_is_dormant(self, monkeypatch):
        class _Partial:
            def memory_stats(self):
                # the real-world shape: the call EXISTS, the dict is
                # populated, bytes_limit just isn't in it
                return {"bytes_in_use": 123, "peak_bytes_in_use": 456}

        assert self._with_devices(monkeypatch, [_Partial()]) is None

    def test_raising_backend_is_dormant(self, monkeypatch):
        class _Raises:
            def memory_stats(self):
                raise NotImplementedError("plugin backend")

        assert self._with_devices(monkeypatch, [_Raises()]) is None

    def test_non_dict_stats_is_dormant(self, monkeypatch):
        class _Weird:
            def memory_stats(self):
                return "1GiB"

        assert self._with_devices(monkeypatch, [_Weird()]) is None

    def test_zero_or_bogus_limit_is_dormant(self, monkeypatch):
        class _Zero:
            def memory_stats(self):
                return {"bytes_limit": 0}

        class _Str:
            def memory_stats(self):
                return {"bytes_limit": "big"}

        assert self._with_devices(monkeypatch, [_Zero()]) is None
        assert self._with_devices(monkeypatch, [_Str()]) is None

    def test_env_override_wins(self, monkeypatch):
        from datafusion_tpu.obs import device as obs_device

        monkeypatch.setenv("DATAFUSION_TPU_HBM_BYTES", "1e9")
        assert obs_device.hbm_capacity_bytes() == int(1e9)


@pytest.fixture(scope="class")
def debug_server():
    from datafusion_tpu.obs.httpd import start_debug_server

    srv = start_debug_server(-1, label="test:1")
    assert srv is not None
    yield srv
    srv.close()


def _get(srv, path, timeout=30):
    with urllib.request.urlopen(srv.url + path, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


class TestDebugHttpPlane:
    def test_port_off_by_default(self):
        from datafusion_tpu.obs.httpd import start_debug_server

        assert start_debug_server(0) is None
        assert start_debug_server(None) is None

    def test_index(self, debug_server):
        status, ctype, body = _get(debug_server, "/")
        assert status == 200 and ctype.startswith("text/plain")
        assert b"/debug/bundle" in body

    def test_metrics(self, debug_server):
        status, ctype, body = _get(debug_server, "/debug/metrics")
        assert status == 200
        assert ctype.startswith("text/plain")
        assert b"datafusion_tpu_events_total" in body
        assert b'name="host.rss_bytes"' in body
        # the absorbed legacy path serves the same exposition
        status2, _ct, body2 = _get(debug_server, "/metrics")
        assert status2 == 200
        assert b"datafusion_tpu_events_total" in body2

    def test_flights_and_trace_filter(self, debug_server):
        from datafusion_tpu.obs import recorder, trace as obs_trace

        recorder.record("test.noise", k=1)
        with obs_trace.session() as tc:
            recorder.record("test.signal", k=2)
        status, ctype, body = _get(debug_server, "/debug/flights")
        assert status == 200 and ctype.startswith("application/json")
        doc = json.loads(body)
        kinds = {e["kind"] for e in doc["events"]}
        assert {"test.noise", "test.signal"} <= kinds
        # ?trace_id= narrows to the one query
        status, _ct, body = _get(
            debug_server, f"/debug/flights?trace_id={tc.trace_id}"
        )
        doc = json.loads(body)
        assert {e["kind"] for e in doc["events"]} == {"test.signal"}

    def test_hbm(self, debug_server):
        status, ctype, body = _get(debug_server, "/debug/hbm")
        assert status == 200 and ctype.startswith("application/json")
        doc = json.loads(body)
        assert doc["enabled"] is True
        assert "live_bytes" in doc and "owners" in doc

    def test_top(self, debug_server):
        status, ctype, body = _get(debug_server, "/debug/top")
        assert status == 200 and ctype.startswith("text/plain")
        assert body.decode().startswith("fleet:")

    def test_profile_formats(self, debug_server):
        status, ctype, body = _get(
            debug_server, "/debug/profile?seconds=0.2"
        )
        assert status == 200 and ctype.startswith("application/json")
        doc = json.loads(body)
        assert doc["profiles"]  # speedscope by default
        status, ctype, body = _get(
            debug_server, "/debug/profile?seconds=0.2&format=collapsed"
        )
        assert status == 200 and ctype.startswith("text/plain")
        assert body.strip()
        status, _ct, body = _get(
            debug_server, "/debug/profile?seconds=0.2&format=json&hz=200"
        )
        doc = json.loads(body)
        assert doc["samples"] > 0 and doc["hz"] == 200.0

    def test_bundle_completeness(self, debug_server):
        status, ctype, body = _get(debug_server, "/debug/bundle?seconds=0.2")
        assert status == 200 and ctype.startswith("application/json")
        doc = json.loads(body)
        assert doc["type"] == "debug_bundle"
        for key in ("config", "metrics", "gauges", "flights", "hbm",
                    "profile", "slo"):
            assert key in doc, key
        assert doc["profile"]["samples"] > 0
        assert "datafusion_tpu_events_total" in doc["metrics"]
        assert isinstance(doc["flights"]["events"], list)
        assert "env" in doc["config"] and "pid" in doc["config"]

    def test_404(self, debug_server):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(debug_server, "/debug/nope")
        assert ei.value.code == 404

    def test_status_and_healthz(self, debug_server):
        for path in ("/status", "/healthz", "/debug/status"):
            status, _ct, body = _get(debug_server, path)
            assert status == 200
            assert json.loads(body)["type"] == "status"

    def test_no_sampler_thread_left_behind(self, debug_server):
        _get(debug_server, "/debug/profile?seconds=0.1")
        deadline = time.monotonic() + 5
        while profiler.PROFILER.running() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not profiler.PROFILER.running()


class TestWorkerDebugPlane:
    def test_worker_http_serves_debug_catalog(self):
        from datafusion_tpu.parallel.worker import serve

        server = serve("127.0.0.1:0", device="cpu", http_port=-1)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        http = server.http_server
        try:
            assert http is not None
            assert server.worker_state.debug_port == http.port
            status, _ct, body = _get(http, "/status")
            assert json.loads(body)["type"] == "status"
            status, _ct, body = _get(http, "/debug/metrics")
            assert b"datafusion_tpu_events_total" in body
            status, _ct, body = _get(http, "/debug/bundle?seconds=0.1")
            doc = json.loads(body)
            assert doc["profile"]["samples"] > 0
            # the worker's own status rides the bundle
            assert doc["status"]["type"] == "status"
        finally:
            http.close()
            server.shutdown()
            server.server_close()

    def test_agent_advertises_debug_port_in_lease(self):
        from datafusion_tpu.cluster.agent import WorkerClusterAgent
        from datafusion_tpu.cluster.client import LocalClusterClient
        from datafusion_tpu.cluster.service import ClusterState

        class _State:
            batch_size = 1024
            fragment_cache = None
            debug_port = 18422

        state = ClusterState()
        agent = WorkerClusterAgent(
            LocalClusterClient(state), "10.0.0.9:7", _State()
        )
        agent.poll_once()
        info = state.membership()["workers"]["10.0.0.9:7"]
        assert info["debug_port"] == 18422

        class _NoDebug:
            batch_size = 1024
            fragment_cache = None

        agent2 = WorkerClusterAgent(
            LocalClusterClient(state), "10.0.0.10:7", _NoDebug()
        )
        agent2.poll_once()
        info2 = state.membership()["workers"]["10.0.0.10:7"]
        assert "debug_port" not in info2


class TestDebugBundleCli:
    def test_local_bundle(self, tmp_path, capsys):
        from datafusion_tpu.cli import main

        out = tmp_path / "bundles"
        rc = main(["debug-bundle", "--out", str(out), "--seconds", "0.1"])
        assert rc == 0
        files = list(out.glob("bundle-*.json"))
        assert len(files) == 1
        doc = json.loads(files[0].read_text())
        assert doc["type"] == "debug_bundle"
        assert doc["profile"]["samples"] > 0

    def test_workers_mode_pulls_each_member(self, tmp_path):
        from datafusion_tpu.cli import run_debug_bundle
        from datafusion_tpu.obs.httpd import start_debug_server

        a = start_debug_server(-1, label="a:1")
        b = start_debug_server(-1, label="b:2")
        try:
            workers = (f"127.0.0.1:{a.port},127.0.0.1:{b.port}")
            import io

            buf = io.StringIO()
            rc = run_debug_bundle(None, workers, str(tmp_path), 0.1,
                                  out=buf)
            assert rc == 0, buf.getvalue()
            files = sorted(tmp_path.glob("bundle-*.json"))
            assert len(files) == 2
            for f in files:
                doc = json.loads(f.read_text())
                assert doc["profile"]["samples"] > 0
                assert "metrics" in doc and "hbm" in doc
        finally:
            a.close()
            b.close()

    def test_member_without_debug_port_fails(self, tmp_path):
        import io

        from datafusion_tpu.cli import run_debug_bundle
        from datafusion_tpu.cluster.client import LocalClusterClient
        from datafusion_tpu.cluster.service import ClusterState

        state = ClusterState()
        c = LocalClusterClient(state)
        lease = c.lease_grant(30.0)["lease"]
        c.put("workers/1.2.3.4:9", {"addr": "1.2.3.4:9"}, lease=lease)
        import datafusion_tpu.cluster as cluster_mod

        saved = cluster_mod.connect
        cluster_mod.connect = lambda _t: c
        try:
            buf = io.StringIO()
            rc = run_debug_bundle("fake:1", None, str(tmp_path), 0.1,
                                  out=buf)
        finally:
            cluster_mod.connect = saved
        assert rc == 1
        assert "NO debug port" in buf.getvalue()

    def test_write_local_bundle_for_ci(self, tmp_path):
        from datafusion_tpu.obs.httpd import write_local_bundle

        path = write_local_bundle(str(tmp_path), reason="smoke_failure",
                                  profile_seconds=0.1)
        doc = json.loads(open(path).read())
        assert doc["reason"] == "smoke_failure"
        assert doc["profile"]["samples"] > 0


class TestExplainAnalyzeProfile:
    def test_per_phase_top_frames(self, tmp_path):
        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.context import ExecutionContext

        path = tmp_path / "t.csv"
        rng = np.random.default_rng(7)
        with open(path, "w") as f:
            f.write("k,v\n")
            for i in range(30000):
                f.write(f"k{i % 13},{rng.integers(0, 1000)}\n")
        ctx = ExecutionContext(device="cpu")
        schema = Schema([Field("k", DataType.UTF8, False),
                         Field("v", DataType.INT64, False)])
        ctx.register_csv("t", str(path), schema, has_header=True)
        res = ctx.sql_collect(
            "EXPLAIN ANALYZE SELECT k, SUM(v) FROM t GROUP BY k"
        )
        assert res.host_profile is not None
        assert res.host_profile.samples > 0
        by_phase = res.host_profile.by_phase(3)
        assert by_phase, "no phases sampled"
        for _phase, d in by_phase.items():
            assert 1 <= len(d["top_frames"]) <= 3
            for label, count in d["top_frames"]:
                assert isinstance(label, str) and count >= 1
        assert "Host profile" in res.report()
        # sampler tore down with the scope
        assert not profiler.PROFILER.running()

    def test_opt_out_env(self, tmp_path, monkeypatch):
        import numpy as np

        from datafusion_tpu.datatypes import DataType, Field, Schema
        from datafusion_tpu.exec.context import ExecutionContext

        monkeypatch.setenv("DATAFUSION_TPU_PROFILE_EXPLAIN", "0")
        path = tmp_path / "t.csv"
        with open(path, "w") as f:
            f.write("v\n1\n2\n3\n")
        ctx = ExecutionContext(device="cpu")
        schema = Schema([Field("v", DataType.INT64, False)])
        ctx.register_csv("t", str(path), schema, has_header=True)
        res = ctx.sql_collect("EXPLAIN ANALYZE SELECT v FROM t")
        assert res.host_profile is None
        assert "Host profile" not in res.report()
