"""Planner golden tests.

The 12 tests from the reference (`src/sqlplanner.rs:522-772`) ported
verbatim — same SQL, same expected plan pretty-print, same mock catalog
(6-column `person` table + `sqrt` scalar function).  These encode the
exact plan-shape semantics the engine must reproduce.
"""

import pytest

from datafusion_tpu import DataType, Field, FunctionMeta, Schema
from datafusion_tpu.errors import NotSupportedError, ParserError, PlanError
from datafusion_tpu.plan.expr import FunctionType
from datafusion_tpu.sql.optimizer import push_down_projection
from datafusion_tpu.sql.parser import parse_sql
from datafusion_tpu.sql.planner import SqlToRel


class MockSchemaProvider:
    # ported from sqlplanner.rs:742-770
    def get_table_meta(self, name):
        if name == "person":
            return Schema(
                [
                    Field("id", DataType.UINT32, False),
                    Field("first_name", DataType.UTF8, False),
                    Field("last_name", DataType.UTF8, False),
                    Field("age", DataType.INT32, False),
                    Field("state", DataType.UTF8, False),
                    Field("salary", DataType.FLOAT64, False),
                ]
            )
        return None

    def get_function_meta(self, name):
        if name == "sqrt":
            return FunctionMeta(
                "sqrt",
                [Field("n", DataType.FLOAT64, False)],
                DataType.FLOAT64,
                FunctionType.Scalar,
            )
        return None


def quick_test(sql: str, expected: str):
    planner = SqlToRel(MockSchemaProvider())
    plan = planner.sql_to_rel(parse_sql(sql))
    assert repr(plan) == expected


def test_select_no_relation():
    quick_test("SELECT 1", "Projection: Int64(1)\n  EmptyRelation")


def test_select_scalar_func_with_literal_no_relation():
    quick_test(
        "SELECT sqrt(9)",
        "Projection: sqrt(CAST(Int64(9) AS Float64))\n  EmptyRelation",
    )


def test_select_simple_selection():
    quick_test(
        "SELECT id, first_name, last_name FROM person WHERE state = 'CO'",
        "Projection: #0, #1, #2\n"
        '  Selection: #4 Eq Utf8("CO")\n'
        "    TableScan: person projection=None",
    )


def test_select_compound_selection():
    quick_test(
        "SELECT id, first_name, last_name "
        "FROM person WHERE state = 'CO' AND age >= 21 AND age <= 65",
        "Projection: #0, #1, #2\n"
        '  Selection: #4 Eq Utf8("CO") And CAST(#3 AS Int64) GtEq Int64(21)'
        " And CAST(#3 AS Int64) LtEq Int64(65)\n"
        "    TableScan: person projection=None",
    )


def test_select_all_boolean_operators():
    quick_test(
        "SELECT age, first_name, last_name "
        "FROM person "
        "WHERE age = 21 "
        "AND age != 21 "
        "AND age > 21 "
        "AND age >= 21 "
        "AND age < 65 "
        "AND age <= 65",
        "Projection: #3, #1, #2\n"
        "  Selection: CAST(#3 AS Int64) Eq Int64(21)"
        " And CAST(#3 AS Int64) NotEq Int64(21)"
        " And CAST(#3 AS Int64) Gt Int64(21)"
        " And CAST(#3 AS Int64) GtEq Int64(21)"
        " And CAST(#3 AS Int64) Lt Int64(65)"
        " And CAST(#3 AS Int64) LtEq Int64(65)\n"
        "    TableScan: person projection=None",
    )


def test_select_simple_aggregate():
    quick_test(
        "SELECT MIN(age) FROM person",
        "Aggregate: groupBy=[[]], aggr=[[MIN(#3)]]\n"
        "  TableScan: person projection=None",
    )


def test_sum_aggregate():
    quick_test(
        "SELECT SUM(age) from person",
        "Aggregate: groupBy=[[]], aggr=[[SUM(#3)]]\n"
        "  TableScan: person projection=None",
    )


def test_select_simple_aggregate_with_groupby():
    quick_test(
        "SELECT state, MIN(age), MAX(age) FROM person GROUP BY state",
        "Aggregate: groupBy=[[#4]], aggr=[[MIN(#3), MAX(#3)]]\n"
        "  TableScan: person projection=None",
    )


def test_select_count_one():
    quick_test(
        "SELECT COUNT(1) FROM person",
        "Aggregate: groupBy=[[]], aggr=[[COUNT(#0)]]\n"
        "  TableScan: person projection=None",
    )


def test_select_scalar_func():
    quick_test(
        "SELECT sqrt(age) FROM person",
        "Projection: sqrt(CAST(#3 AS Float64))\n"
        "  TableScan: person projection=None",
    )


def test_select_order_by():
    quick_test(
        "SELECT id FROM person ORDER BY id",
        "Sort: #0 ASC\n"
        "  Projection: #0\n"
        "    TableScan: person projection=None",
    )


def test_select_order_by_desc():
    quick_test(
        "SELECT id FROM person ORDER BY id DESC",
        "Sort: #0 DESC\n"
        "  Projection: #0\n"
        "    TableScan: person projection=None",
    )


def test_select_order_limit():
    quick_test(
        "SELECT id FROM person ORDER BY id DESC LIMIT 10",
        "Limit: 10\n"
        "  Sort: #0 DESC\n"
        "    Projection: #0\n"
        "      TableScan: person projection=None",
    )


def test_select_limit():
    quick_test(
        "SELECT id FROM person LIMIT 10",
        "Limit: 10\n"
        "  Projection: #0\n"
        "    TableScan: person projection=None",
    )


# -- beyond the ported 12: behaviors the rebuild completes --


def test_select_wildcard():
    # reference left SELECT * unimplemented (sqlplanner.rs:225-229)
    quick_test(
        "SELECT * FROM person",
        "Projection: #0, #1, #2, #3, #4, #5\n"
        "  TableScan: person projection=None",
    )


def test_aggregate_with_order_by_and_limit():
    # reference TODO at sqlplanner.rs:111-117
    quick_test(
        "SELECT state, MIN(age) FROM person GROUP BY state ORDER BY state LIMIT 3",
        "Limit: 3\n"
        "  Sort: #0 ASC\n"
        "    Aggregate: groupBy=[[#4]], aggr=[[MIN(#3)]]\n"
        "      TableScan: person projection=None",
    )


def test_is_null_and_alias():
    quick_test(
        "SELECT age AS years FROM person WHERE state IS NOT NULL",
        "Projection: #3\n"
        "  Selection: #4 IS NOT NULL\n"
        "    TableScan: person projection=None",
    )
    planner = SqlToRel(MockSchemaProvider())
    plan = planner.sql_to_rel(parse_sql("SELECT age AS years FROM person"))
    assert plan.schema.names() == ["years"]


def test_having_not_implemented():
    planner = SqlToRel(MockSchemaProvider())
    with pytest.raises(NotSupportedError):
        planner.sql_to_rel(parse_sql("SELECT age FROM person HAVING age > 1"))


def test_unknown_table_and_function():
    planner = SqlToRel(MockSchemaProvider())
    with pytest.raises(PlanError, match="no schema found"):
        planner.sql_to_rel(parse_sql("SELECT a FROM missing"))
    with pytest.raises(PlanError, match="Invalid function"):
        planner.sql_to_rel(parse_sql("SELECT nope(id) FROM person"))


def test_limit_must_be_number():
    planner = SqlToRel(MockSchemaProvider())
    with pytest.raises(PlanError, match="LIMIT parameter is not a number"):
        planner.sql_to_rel(parse_sql("SELECT id FROM person LIMIT id"))


def test_parse_errors():
    for bad in ["SELEC 1", "SELECT 'unterminated", "SELECT (1", "SELECT 1 FROM"]:
        with pytest.raises(ParserError):
            parse_sql(bad)


def test_create_external_table():
    from datafusion_tpu.sql import ast

    stmt = parse_sql(
        "CREATE EXTERNAL TABLE uk_cities (city VARCHAR(100) NOT NULL, "
        "lat DOUBLE NOT NULL, lng DOUBLE NOT NULL) "
        "STORED AS CSV WITHOUT HEADER ROW LOCATION 'test/data/uk_cities.csv'"
    )
    assert isinstance(stmt, ast.SqlCreateExternalTable)
    assert stmt.name == "uk_cities"
    assert [c.name for c in stmt.columns] == ["city", "lat", "lng"]
    assert stmt.columns[0].data_type == ast.SqlType.Varchar
    assert not stmt.columns[0].allow_null
    assert stmt.file_type == ast.FileType.CSV
    assert stmt.header_row is False
    assert stmt.location == "test/data/uk_cities.csv"

    stmt2 = parse_sql("CREATE EXTERNAL TABLE t STORED AS PARQUET LOCATION 'x.parquet'")
    assert stmt2.columns == []
    assert stmt2.file_type == ast.FileType.Parquet


def test_push_down_projection():
    planner = SqlToRel(MockSchemaProvider())
    plan = planner.sql_to_rel(
        parse_sql("SELECT id, first_name FROM person WHERE age > 21")
    )
    optimized = push_down_projection(plan)
    # scan reads only columns {0,1,3}; references remapped to new positions
    assert repr(optimized) == (
        "Projection: #0, #1\n"
        "  Selection: CAST(#2 AS Int64) Gt Int64(21)\n"
        "    TableScan: person projection=Some([0, 1, 3])"
    )
    assert optimized.schema.names() == ["id", "first_name"]


def test_push_down_projection_aggregate():
    planner = SqlToRel(MockSchemaProvider())
    plan = planner.sql_to_rel(
        parse_sql("SELECT state, MIN(age) FROM person GROUP BY state")
    )
    optimized = push_down_projection(plan)
    assert repr(optimized) == (
        "Aggregate: groupBy=[[#1]], aggr=[[MIN(#0)]]\n"
        "  TableScan: person projection=Some([3, 4])"
    )


def test_push_down_keeps_bare_scan_intact():
    planner = SqlToRel(MockSchemaProvider())
    plan = planner.sql_to_rel(parse_sql("SELECT * FROM person"))
    optimized = push_down_projection(plan)
    assert optimized.schema.names() == [
        "id", "first_name", "last_name", "age", "state", "salary",
    ]


def test_statement_splitting():
    from datafusion_tpu.sql.parser import split_statements

    stmts = split_statements(
        "-- comment\nSELECT 1;\nSELECT 'a;b';\n  \nSELECT 2"
    )
    assert stmts == ["SELECT 1", "SELECT 'a;b'", "SELECT 2"]


def test_aggregate_under_scalar_function_in_having():
    # ScalarFunction args participate in the post-aggregate rewrite:
    # an aggregate inside a function resolves to its output column when
    # it appears in the SELECT list ...
    quick_test(
        "SELECT state, SUM(salary) FROM person GROUP BY state "
        "HAVING sqrt(SUM(salary)) > 10",
        "Selection: sqrt(#1) Gt CAST(Int64(10) AS Float64)\n"
        "  Aggregate: groupBy=[[#4]], aggr=[[SUM(#5)]]\n"
        "    TableScan: person projection=None",
    )
    # ... and is rejected with a plan-time diagnostic when it does not.
    planner = SqlToRel(MockSchemaProvider())
    with pytest.raises(PlanError, match="must also appear"):
        planner.sql_to_rel(
            parse_sql(
                "SELECT state, SUM(salary) FROM person GROUP BY state "
                "HAVING sqrt(MAX(salary)) > 10"
            )
        )
