"""_MeshStacker (parallel/partition.py): per-shard direct device
placement for mesh rounds — no host stacking, no cross-device reshard."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from datafusion_tpu.parallel.mesh import make_mesh
from datafusion_tpu.parallel.partition import _MeshStacker


@pytest.fixture(scope="module")
def stacker():
    n = len(jax.devices())
    if n < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    return _MeshStacker(make_mesh(n))


class TestMeshStacker:
    def test_put_places_each_shard_on_its_device(self, stacker):
        n = stacker.n
        shards = [np.full(16, i, np.float64) for i in range(n)]
        arr = stacker.put(shards)
        assert arr.shape == (n, 16)
        for sh in arr.addressable_shards:
            s_i = sh.index[0].start
            np.testing.assert_array_equal(np.asarray(sh.data)[0], shards[s_i])

    def test_take_roundtrip(self, stacker):
        n = stacker.n
        shards = [np.arange(8, dtype=np.int32) + 100 * i for i in range(n)]
        arr = stacker.put(shards)
        for i in range(n):
            np.testing.assert_array_equal(stacker.take(arr, i), shards[i])

    def test_fill_cached_and_readonly(self, stacker):
        a = stacker.fill(32, np.float64)
        b = stacker.fill(32, np.float64)
        assert a is b  # cached
        with pytest.raises((ValueError, RuntimeError)):
            a[0] = 1.0  # shared constants must be immutable
        t = stacker.fill(32, bool, True)
        assert t.all() and t.dtype == bool

    def test_pad(self, stacker):
        arr = np.arange(5, dtype=np.float64)
        padded = stacker.pad(arr, 8)
        assert padded.shape == (8,)
        np.testing.assert_array_equal(padded[:5], arr)
        assert (padded[5:] == 0).all()
        same = stacker.pad(np.arange(8), 8)
        assert same.shape == (8,)

    def test_sharded_array_feeds_shard_map(self, stacker):
        # the consumer contract: shard_map over the mesh sees each
        # device's own block with no resharding collective
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from datafusion_tpu.parallel.mesh import MESH_AXIS
        from datafusion_tpu.parallel.partition import shard_map

        n = stacker.n
        arr = stacker.put([np.full(16, float(i)) for i in range(n)])

        f = jax.jit(
            shard_map(
                lambda x: x.sum(axis=1, keepdims=True),
                mesh=stacker.mesh,
                in_specs=(P(MESH_AXIS),),
                out_specs=P(MESH_AXIS),
            )
        )
        out = np.asarray(f(arr)).ravel()
        np.testing.assert_allclose(out, [16.0 * i for i in range(n)])
