"""Selector event loop (utils/eventloop.py) + the servers riding it.

Covers the loop primitives (frame round trips, per-connection request
ordering, timers, socketserver-facade lifecycle), the fleet-scale
contract — hundreds of PARKED long-poll watches on one cluster service
node must cost file descriptors, not threads (thread count asserted) —
the debug HTTP plane's event-loop transport (keep-alive, bearer-token
auth with constant-time compare, loopback bind default), and the worker
agent's re-register storm controls (capped full-jitter backoff,
bounded re-register stagger).
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from datafusion_tpu.cluster import connect
from datafusion_tpu.cluster.service import serve as serve_cluster
from datafusion_tpu.parallel.wire import (
    _LEN,
    encode_frame,
    frame_nbytes,
    parse_frame,
    recv_msg,
    send_msg,
)
from datafusion_tpu.utils.eventloop import (
    LoopServer,
    ServerLoop,
    WireConnection,
    default_pool_size,
)


def _start(server):
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t


class TestServerLoop:
    def _echo_server(self):
        loop = ServerLoop(name="test-echo")

        def on_message(conn, msg):
            if msg.get("type") == "park":
                # deferred reply from a timer: the parked-request shape
                loop.call_later(
                    float(msg.get("delay_s", 0.05)),
                    lambda: conn.reply(msg, {"type": "parked_reply",
                                             "n": msg.get("n")}),
                )
                return
            conn.reply(msg, {"type": "echo", "n": msg.get("n")})

        lsock = loop.listen(
            "127.0.0.1", 0,
            lambda lp, s, a: WireConnection(lp, s, a, on_message),
        )
        return LoopServer(loop, lsock)

    def test_frame_roundtrip_and_ordering(self):
        server = self._echo_server()
        _start(server)
        try:
            host, port = server.server_address[:2]
            with socket.create_connection((host, port), timeout=5) as s:
                s.settimeout(5.0)
                # several pipelined frames in one connection answer in
                # order (the threaded handler's sequential contract)
                for i in range(5):
                    send_msg(s, {"type": "echo", "n": i})
                for i in range(5):
                    out = recv_msg(s)
                    assert out == {"type": "echo", "n": i}
        finally:
            server.shutdown()
            server.server_close()

    def test_parked_reply_after_timer(self):
        server = self._echo_server()
        _start(server)
        try:
            host, port = server.server_address[:2]
            with socket.create_connection((host, port), timeout=5) as s:
                s.settimeout(5.0)
                send_msg(s, {"type": "park", "n": 7, "delay_s": 0.05})
                t0 = time.monotonic()
                out = recv_msg(s)
                assert out["type"] == "parked_reply" and out["n"] == 7
                assert time.monotonic() - t0 >= 0.04
        finally:
            server.shutdown()
            server.server_close()

    def test_shutdown_without_serve_forever(self):
        # construct-then-close must not hang (fixture teardown shape)
        server = self._echo_server()
        server.shutdown()
        server.server_close()

    def test_large_binary_frame_roundtrip(self):
        import numpy as np

        from datafusion_tpu.parallel.wire import BinWriter, dec_array, enc_array

        loop = ServerLoop(name="test-bin")

        def on_message(conn, msg):
            arr = dec_array(msg["payload"])
            bw = BinWriter()
            conn.reply(msg, {"type": "sum", "total": int(arr.sum()),
                             "echo": enc_array(arr, bw)}, bw)

        lsock = loop.listen(
            "127.0.0.1", 0,
            lambda lp, s, a: WireConnection(lp, s, a, on_message),
        )
        server = LoopServer(loop, lsock)
        _start(server)
        try:
            host, port = server.server_address[:2]
            a = np.arange(300_000, dtype=np.int64)
            with socket.create_connection((host, port), timeout=10) as s:
                s.settimeout(10.0)
                bw = BinWriter()
                send_msg(s, {"type": "sum", "wire_version": 2,
                             "payload": enc_array(a, bw)}, bw, crc=True)
                out = recv_msg(s)
            assert out["total"] == int(a.sum())
            np.testing.assert_array_equal(dec_array(out["echo"]), a)
        finally:
            server.shutdown()
            server.server_close()

    def test_encode_frame_matches_send_msg_bytes(self):
        chunks = encode_frame({"type": "x", "v": 1})
        assert frame_nbytes(chunks) == sum(len(bytes(c)) for c in chunks)
        payload = b"".join(bytes(memoryview(c).cast("B")) for c in chunks)
        (n,) = _LEN.unpack(payload[:8])
        assert parse_frame(bytearray(payload[8:8 + n])) == \
            {"type": "x", "v": 1}


class TestParkedWatchScale:
    N_WATCHES = 220

    def test_hundreds_of_parked_watches_cost_no_threads(self):
        """The fleet-scale acceptance shape, in miniature: ≥200 parked
        long-poll watches on ONE service node, thread count bounded by
        the executor pool (not the connection count), and one event
        wakes them all."""
        server = serve_cluster("127.0.0.1:0")
        _start(server)
        socks = []
        try:
            host, port = server.server_address[:2]
            client = connect(f"{host}:{port}")
            rev0 = client.membership()["rev"]
            before = threading.active_count()
            for _ in range(self.N_WATCHES):
                s = socket.create_connection((host, port), timeout=10)
                s.settimeout(30.0)
                send_msg(s, {"type": "watch", "since": rev0,
                             "timeout_s": 25.0})
                socks.append(s)
            deadline = time.monotonic() + 10.0
            while client.status()["parked_watchers"] < self.N_WATCHES:
                assert time.monotonic() < deadline, (
                    f"only {client.status()['parked_watchers']} parked"
                )
                time.sleep(0.05)
            grown = threading.active_count() - before
            # the whole point: parked watches are fd + waiter entries,
            # not threads.  Allow the executor pool plus a little slack.
            assert grown <= default_pool_size() + 2, (
                f"{grown} new threads for {self.N_WATCHES} parked watches"
            )
            # one client-visible event wakes every parked watcher
            client.invalidate("wake_t")
            woken = 0
            for s in socks:
                out = recv_msg(s)
                assert out["type"] == "watch" and out["fired"] is True
                kinds = [e["kind"] for e in out["events"]]
                assert kinds == ["invalidate"]
                woken += 1
            assert woken == self.N_WATCHES
            assert client.status()["parked_watchers"] == 0
        finally:
            for s in socks:
                try:
                    s.close()
                except OSError:
                    pass
            server.shutdown()
            server.server_close()


class TestDebugHttpPlane:
    def _server(self, monkeypatch, token=None):
        from datafusion_tpu.obs.httpd import DebugServer

        if token is None:
            monkeypatch.delenv("DATAFUSION_TPU_DEBUG_TOKEN", raising=False)
        else:
            monkeypatch.setenv("DATAFUSION_TPU_DEBUG_TOKEN", token)
        return DebugServer(0, "127.0.0.1", label="test:http")

    def _get(self, url, token=None):
        headers = {"Authorization": f"Bearer {token}"} if token else {}
        req = urllib.request.Request(url, headers=headers)
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read()

    def test_endpoints_over_eventloop(self, monkeypatch):
        srv = self._server(monkeypatch)
        try:
            code, body = self._get(f"{srv.url}/status")
            assert code == 200 and json.loads(body)["node"] == "test:http"
            code, body = self._get(f"{srv.url}/debug/metrics")
            assert code == 200 and b"# TYPE" in body
            code, body = self._get(f"{srv.url}/debug/flights")
            assert code == 200 and "events" in json.loads(body)
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(f"{srv.url}/debug/nope")
            assert ei.value.code == 404
        finally:
            srv.close()

    def test_keepalive_serves_sequential_requests(self, monkeypatch):
        srv = self._server(monkeypatch)
        try:
            host, port = srv.server_address[:2]
            with socket.create_connection((host, port), timeout=10) as s:
                s.settimeout(10.0)
                for _ in range(3):
                    s.sendall(b"GET /healthz HTTP/1.1\r\n"
                              b"Host: x\r\nConnection: keep-alive\r\n\r\n")
                    head = b""
                    while b"\r\n\r\n" not in head:
                        head += s.recv(4096)
                    assert b"200 OK" in head
                    assert b"keep-alive" in head
                    body_at = head.index(b"\r\n\r\n") + 4
                    clen = int(
                        [ln for ln in head.split(b"\r\n")
                         if ln.lower().startswith(b"content-length")][0]
                        .split(b":")[1]
                    )
                    body = head[body_at:]
                    while len(body) < clen:
                        body += s.recv(4096)
                    assert json.loads(body[:clen])["type"] == "status"
        finally:
            srv.close()

    def test_token_guards_debug_paths_not_probes(self, monkeypatch):
        srv = self._server(monkeypatch, token="sekrit-42")
        try:
            # probe surface stays open (liveness checks carry no token)
            code, _ = self._get(f"{srv.url}/healthz")
            assert code == 200
            # /debug/* and /metrics are guarded
            for path in ("/debug/metrics", "/metrics", "/debug/flights",
                         "/debug/bundle?seconds=0"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    self._get(f"{srv.url}{path}")
                assert ei.value.code == 401, path
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(f"{srv.url}/debug/metrics", token="wrong")
            assert ei.value.code == 401
            code, body = self._get(f"{srv.url}/debug/metrics",
                                   token="sekrit-42")
            assert code == 200 and b"# TYPE" in body
        finally:
            srv.close()

    def test_auth_uses_constant_time_compare(self):
        from datafusion_tpu.obs.httpd import _authorized

        assert _authorized({}, None)
        assert _authorized({"authorization": "Bearer tok"}, "tok")
        assert _authorized({"authorization": "bearer tok"}, "tok")
        assert not _authorized({"authorization": "Bearer nope"}, "tok")
        assert not _authorized({}, "tok")

    def test_bind_defaults_to_loopback(self, monkeypatch):
        from datafusion_tpu.obs.httpd import debug_bind_host

        monkeypatch.delenv("DATAFUSION_TPU_DEBUG_BIND", raising=False)
        assert debug_bind_host("0.0.0.0") == "127.0.0.1"
        assert debug_bind_host("10.1.2.3") == "127.0.0.1"
        assert debug_bind_host("127.0.0.1") == "127.0.0.1"
        assert debug_bind_host(None) == "127.0.0.1"
        monkeypatch.setenv("DATAFUSION_TPU_DEBUG_BIND", "0.0.0.0")
        assert debug_bind_host("127.0.0.1") == "0.0.0.0"


class TestAgentStormControls:
    def _agent(self, **kw):
        from datafusion_tpu.cluster import ClusterState, LocalClusterClient
        from datafusion_tpu.cluster.agent import WorkerClusterAgent

        class _WS:
            batch_size = 4
            fragment_cache = None

        return WorkerClusterAgent(
            LocalClusterClient(ClusterState()), "w:1", _WS(),
            ttl_s=6.0, **kw,
        )

    def test_retry_delay_backs_off_with_jitter_and_cap(self):
        agent = self._agent()
        assert agent._retry_delay_s() == agent.refresh_s  # healthy: fixed
        agent._failures = 1
        delays = {agent._retry_delay_s() for _ in range(64)}
        assert all(0.05 <= d <= agent._backoff_cap_s for d in delays)
        assert len(delays) > 8  # jittered, not a constant
        agent._failures = 50  # deep failure: capped at one TTL
        for _ in range(64):
            assert agent._retry_delay_s() <= agent._backoff_cap_s
        assert agent._backoff_cap_s == pytest.approx(6.0)

    def test_register_stagger_bounded(self):
        agent = self._agent()
        cap = min(agent.reregister_jitter_s, agent.refresh_s)
        samples = [agent._register_stagger_s() for _ in range(128)]
        assert all(0.0 <= s <= cap for s in samples)
        assert len({round(s, 6) for s in samples}) > 16  # spread, not a spike

    def test_poll_once_stays_deterministic_without_stagger(self):
        # direct drivers (tests, failover chaos) must see an immediate
        # re-register — the stagger only arms on the background loop
        agent = self._agent()
        agent.poll_once()
        assert agent.lease is not None
        lease = agent.lease
        agent.client.lease_revoke(lease)
        t0 = time.monotonic()
        agent.poll_once()
        assert time.monotonic() - t0 < 0.5
        assert agent.reregistrations == 1 and agent.lease != lease

    def test_failures_reset_on_success(self):
        agent = self._agent()
        agent._failures = 3
        agent.poll_once(stagger=False)
        # the loop resets on success; emulate its bookkeeping contract
        agent._failures = 0
        assert agent._retry_delay_s() == agent.refresh_s
