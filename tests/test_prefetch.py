"""Staged-prefetch pipeline tests (exec/prefetch.py).

The pipeline is gated to accelerator devices (pipeline_enabled);
DATAFUSION_TPU_PREFETCH=1 forces it on so the CPU test mesh exercises
the staged path end-to-end, including result parity with the serial
path and exception propagation across the producer thread.
"""

import numpy as np
import pytest

from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.errors import IoError
from datafusion_tpu.exec.batch import make_host_batch
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.datasource import DataSource, MemoryDataSource
from datafusion_tpu.exec.prefetch import pipeline_enabled, staged_prefetch


SCHEMA = Schema(
    [
        Field("k", DataType.INT64, False),
        Field("v", DataType.FLOAT64, False),
    ]
)


def _source(rows=10_000, batches=5, groups=17):
    rng = np.random.default_rng(5)
    out = []
    for _ in range(batches):
        out.append(
            make_host_batch(
                SCHEMA,
                [
                    rng.integers(0, groups, rows).astype(np.int64),
                    rng.uniform(0, 100, rows),
                ],
                [None, None],
                [None, None],
            )
        )
    return MemoryDataSource(SCHEMA, out)


def _run(sql, src, monkeypatch, force):
    monkeypatch.setenv("DATAFUSION_TPU_PREFETCH", force)
    ctx = ExecutionContext(device="cpu")
    ctx.register_datasource("t", src)
    return ctx.sql_collect(sql)


@pytest.mark.parametrize(
    "sql",
    [
        "SELECT k, SUM(v), AVG(v), COUNT(1) FROM t GROUP BY k",
        "SELECT k, v * 2 FROM t WHERE v > 50.0",
    ],
)
def test_staged_matches_serial(sql, monkeypatch):
    src = _source()
    serial = _run(sql, src, monkeypatch, "0")
    staged = _run(sql, src, monkeypatch, "1")
    assert sorted(serial.to_rows()) == sorted(staged.to_rows())


def test_pipeline_enabled_knob(monkeypatch):
    monkeypatch.setenv("DATAFUSION_TPU_PREFETCH", "1")
    assert pipeline_enabled(None) is True
    monkeypatch.setenv("DATAFUSION_TPU_PREFETCH", "0")
    assert pipeline_enabled(None) is False
    monkeypatch.delenv("DATAFUSION_TPU_PREFETCH")
    # CPU-only test mesh: auto means off
    assert pipeline_enabled(None) is False


class _ExplodingSource(DataSource):
    def __init__(self, inner, explode_after):
        self._inner = inner
        self._explode_after = explode_after

    @property
    def schema(self):
        return self._inner.schema

    def batches(self):
        for i, b in enumerate(self._inner.batches()):
            if i == self._explode_after:
                raise IoError("disk vanished mid-scan")
            yield b


def test_producer_exception_propagates(monkeypatch):
    src = _ExplodingSource(_source(), explode_after=2)
    with pytest.raises(IoError, match="disk vanished"):
        _run("SELECT k, SUM(v) FROM t GROUP BY k", src, monkeypatch, "1")


def test_stage_callback_exception_propagates():
    def bad_stage(b):
        raise ValueError("stage blew up")

    it = staged_prefetch(iter([1, 2, 3]), stage=bad_stage)
    with pytest.raises(ValueError, match="stage blew up"):
        list(it)


def test_early_abandonment_stops_producer():
    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield i

    it = staged_prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()  # consumer walks away; producer must not spin forever
    import time

    time.sleep(0.3)
    assert len(produced) < 100


def test_order_preserved():
    items = list(staged_prefetch(iter(range(57)), stage=lambda x: None))
    assert items == list(range(57))
