"""Streaming ingestion + incrementally maintained materialized views
(datafusion_tpu/ingest).

The contract under test:
- appends are durable-then-applied: an acked append survives a crash
  (ingest-log replay, including a torn log tail), and a WAL write
  failure acks NOTHING (`wal_unavailable` — retry later, the log's
  revision dedup absorbs replays);
- every append bumps the table's data version, which folds into query
  fingerprints beside the catalog version — cached results stop
  matching instead of serving stale rows;
- an incrementally maintained view is EXACT: at every cut (creation,
  empty delta, single-row delta, wide delta, null-bearing delta) its
  contents are bit-identical to a full batch rescan of the defining
  query;
- unsupported view shapes fall back to counted full recomputes and
  stay exact;
- subscribers park on a view revision and wake when it advances;
- the freshness SLO kind (`DATAFUSION_TPU_SLO_<NAME>_FRESHNESS_S`)
  reads the live view lags;
- cross-query megabatching extends past Aggregate: same-shape TopK
  (ORDER BY ... LIMIT) and Projection/Selection pipelines fold into
  ONE fused launch per batch group, demultiplexed exactly per query.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.errors import IngestError, IngestUnavailableError
from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.datasource import MemoryDataSource
from datafusion_tpu.utils.metrics import METRICS

SCHEMA = Schema([
    Field("g", DataType.UTF8, False),
    Field("v", DataType.INT64, False),
    Field("w", DataType.FLOAT64, False),
])

VIEW_SQL = ("SELECT g, SUM(v), COUNT(1), AVG(w), MIN(w), MAX(w) "
            "FROM t GROUP BY g")


def _base_batch(rows: int = 256, seed: int = 0):
    rng = np.random.default_rng(seed)
    d = StringDictionary()
    codes = d.encode([f"g{j}" for j in rng.integers(0, 5, rows)])
    v = rng.integers(0, 1000, rows).astype(np.int64)
    w = np.round(rng.uniform(0, 100, rows), 3)
    return make_host_batch(SCHEMA, [codes, v, w], dicts=[d, None, None])


def _ctx(result_cache: bool = False) -> ExecutionContext:
    ctx = (ExecutionContext() if result_cache
           else ExecutionContext(result_cache=False))
    ctx.register_datasource("t", MemoryDataSource(SCHEMA, [_base_batch()]))
    return ctx


def _delta(i: int, rows: int):
    rng = np.random.default_rng(100 + i)
    return {
        "g": [f"g{j}" for j in rng.integers(0, 7, rows)],
        "v": [int(x) for x in rng.integers(0, 1000, rows)],
        "w": [round(float(x), 3) for x in rng.uniform(0, 100, rows)],
    }


class TestAppendPath:
    def test_append_visible_and_versions_bump(self):
        ctx = _ctx()
        ing = ctx.ingest()
        before_rows = len(ctx.sql_collect("SELECT g FROM t").to_rows())
        cat0 = ctx.catalog_version("t")
        ack = ing.append("t", {"g": ["zz"], "v": [1], "w": [0.5]})
        assert ack["rows"] == 1 and ack["rev"] == 1
        assert ctx.catalog_version("t") > cat0  # attach + apply both bump
        rows = ctx.sql_collect("SELECT g FROM t").to_rows()
        assert len(rows) == before_rows + 1
        assert ("zz",) in rows

    def test_fingerprint_changes_per_append(self):
        from datafusion_tpu.sql.parser import parse_sql

        ctx = _ctx()
        ing = ctx.ingest()
        ing.append("t", _delta(0, 3))
        plan = ctx._plan(parse_sql("SELECT g, SUM(v) FROM t GROUP BY g"))
        fp0 = ctx.query_fingerprint(plan)
        assert ctx.query_fingerprint(plan) == fp0  # stable between appends
        ing.append("t", _delta(1, 3))
        # the data version folds in beside the catalog version: the
        # same plan over grown data is DIFFERENT work
        assert ctx.query_fingerprint(plan) != fp0

    def test_cached_result_invalidated_by_append(self):
        ctx = _ctx(result_cache=True)
        ing = ctx.ingest()
        sql = "SELECT SUM(v) FROM t"
        (first,) = ctx.sql_collect(sql).to_rows()
        (warm,) = ctx.sql_collect(sql).to_rows()  # served warm
        assert warm == first
        ing.append("t", {"g": ["x"], "v": [10_000_000], "w": [1.0]})
        (after,) = ctx.sql_collect(sql).to_rows()
        assert after[0] == first[0] + 10_000_000  # NOT the stale entry

    def test_schema_mismatch_rejected_before_log(self):
        ctx = _ctx()
        ing = ctx.ingest()
        with pytest.raises(IngestError):
            ing.append("t", {"g": ["a"], "v": [1]})  # missing w
        with pytest.raises(IngestError):
            ing.append("t", {"g": ["a"], "v": [1], "w": [1.0],
                             "bogus": [1]})
        with pytest.raises(IngestError):
            ing.append("t", {"g": ["a", "b"], "v": [1], "w": [1.0]})
        assert ing.status()["rev"] == 0  # nothing acked

    def test_wal_unavailable_acks_nothing(self, tmp_path, monkeypatch):
        ctx = _ctx()
        ing = ctx.ingest(wal_dir=str(tmp_path))
        ing.append("t", _delta(0, 2))
        rows0 = len(ctx.sql_collect("SELECT g FROM t").to_rows())

        def broken(entries):
            raise OSError("disk full")

        monkeypatch.setattr(ing._wal, "append", broken)
        with pytest.raises(IngestUnavailableError):
            ing.append("t", _delta(1, 2))
        # the failed append applied nothing (its revision is burned,
        # not acked — see test_failed_log_write_burns_its_revision)
        assert len(ctx.sql_collect("SELECT g FROM t").to_rows()) == rows0
        monkeypatch.undo()
        ack = ing.append("t", _delta(1, 2))  # the retry lands cleanly
        assert ack["rev"] == 3  # rev 2 burned by the failed write


class TestRecovery:
    def test_crash_recovery_replays_acked_appends(self, tmp_path):
        wal = str(tmp_path)
        ctx = _ctx()
        ing = ctx.ingest(wal_dir=wal)
        ing.create_view("mv", VIEW_SQL)
        for i in range(3):
            ing.append("t", _delta(i, 5 + i))
        want_rows = sorted(ctx.sql_collect(VIEW_SQL).to_rows())
        want_rev = ing.view("mv").revision
        ing.close()
        del ctx, ing

        # a fresh process: base table DDL first, then log replay
        ctx2 = _ctx()
        ing2 = ctx2.ingest(wal_dir=wal)
        rec = ing2.recover()
        assert rec["appends_replayed"] == 3
        assert rec["views_recovered"] == 1
        assert rec["torn_tails"] == 0
        assert sorted(ing2.read_view("mv").to_rows()) == want_rows
        assert sorted(ctx2.sql_collect(VIEW_SQL).to_rows()) == want_rows
        # revision sequence continues for parked subscribers
        assert ing2.view("mv").revision == want_rev

    def test_torn_tail_keeps_every_acked_append(self, tmp_path):
        wal = str(tmp_path)
        ctx = _ctx()
        ing = ctx.ingest(wal_dir=wal)
        for i in range(2):
            ing.append("t", _delta(i, 4))
        want = sorted(ctx.sql_collect("SELECT g, v FROM t").to_rows())
        ing.close()
        del ctx, ing
        segs = sorted(p for p in os.listdir(wal) if p.endswith(".seg"))
        with open(os.path.join(wal, segs[-1]), "ab") as f:
            f.write(b"\x00" * 11)  # crash mid-record header

        ctx2 = _ctx()
        ing2 = ctx2.ingest(wal_dir=wal)
        rec = ing2.recover()
        assert rec["appends_replayed"] == 2  # both acked appends live
        assert rec["torn_tails"] == 1
        assert sorted(ctx2.sql_collect("SELECT g, v FROM t").to_rows()) \
            == want
        ack = ing2.append("t", _delta(9, 1))  # log appendable right after
        assert ack["rev"] == 3


    def test_failed_log_write_burns_its_revision(self, tmp_path,
                                                 monkeypatch):
        """The disk state after a failed WAL write is unknown: the
        record may be durable despite the error.  The failed append's
        revision must be BURNED — reusing it would let recovery's rev
        dedup drop a later ACKED append in favor of the torn record."""
        wal = str(tmp_path)
        ctx = _ctx()
        ing = ctx.ingest(wal_dir=wal)
        real_append = ing._wal.append

        def durable_then_error(entries):
            real_append(entries)  # the record lands on disk...
            raise OSError("fsync failed")  # ...but the ack path errors

        monkeypatch.setattr(ing._wal, "append", durable_then_error)
        with pytest.raises(IngestUnavailableError):
            ing.append("t", {"g": ["nacked"], "v": [-1], "w": [0.0]})
        monkeypatch.undo()
        ack = ing.append("t", {"g": ["acked"], "v": [5], "w": [0.0]})
        assert ack["rev"] == 2  # rev 1 burned by the failed write
        ing.close()
        del ctx, ing

        ctx2 = _ctx()
        ing2 = ctx2.ingest(wal_dir=wal)
        ing2.recover()
        rows = ctx2.sql_collect("SELECT g FROM t").to_rows()
        assert ("acked",) in rows  # the acked append ALWAYS survives
        assert ("nacked",) in rows  # durable superset of the ack stream


class TestIncrementalViews:
    def test_exact_parity_at_every_cut(self):
        ctx = _ctx()
        ing = ctx.ingest()
        view = ing.create_view("mv", VIEW_SQL)
        assert view.incremental, view.fallback_reason

        def check(cut: str):
            got = sorted(ing.read_view("mv").to_rows())
            want = sorted(ctx.sql_collect(VIEW_SQL).to_rows())
            assert got == want, f"divergence at cut {cut!r}"

        check("creation fold")
        ing.append("t", {"g": [], "v": [], "w": []})
        check("empty delta")
        ing.append("t", {"g": ["q"], "v": [7], "w": [3.25]})
        check("single row, new group")
        for i in range(4):
            ing.append("t", _delta(i, 50))
            check(f"wide delta {i}")
        launches0 = view.maintain_launches
        ing.append("t", _delta(99, 200))
        check("final delta")
        # ONE fused maintenance launch per delta, no full recomputes
        assert view.maintain_launches == launches0 + 1
        assert view.full_recomputes == 0

    def test_fallback_shapes_counted_and_exact(self):
        ctx = _ctx()
        ing = ctx.ingest()
        top = ing.create_view("top", "SELECT g, v FROM t ORDER BY v LIMIT 3")
        assert not top.incremental
        assert top.fallback_reason == "plan_shape"
        smin = ing.create_view("smin", "SELECT MIN(g) FROM t")
        assert not smin.incremental
        assert smin.fallback_reason == "string_minmax"
        ing.append("t", {"g": ["AA"], "v": [-5], "w": [0.0]})
        assert sorted(ing.read_view("top").to_rows()) == sorted(
            ctx.sql_collect("SELECT g, v FROM t ORDER BY v LIMIT 3")
            .to_rows())
        assert ing.read_view("smin").to_rows() == \
            ctx.sql_collect("SELECT MIN(g) FROM t").to_rows()
        assert top.full_recomputes >= 1
        assert METRICS.counts.get("view.fallback.plan_shape", 0) >= 1
        assert METRICS.counts.get("view.fallback.string_minmax", 0) >= 1

    def test_create_view_via_sql(self):
        ctx = _ctx()
        ctx.sql_collect(f"CREATE MATERIALIZED VIEW mv AS {VIEW_SQL}")
        ing = ctx.ingest()
        assert "mv" in ing.views()
        ing.append("t", _delta(0, 10))
        assert sorted(ing.read_view("mv").to_rows()) == \
            sorted(ctx.sql_collect(VIEW_SQL).to_rows())

    def test_subscription_wakes_on_advance(self):
        ctx = _ctx()
        ing = ctx.ingest()
        ing.create_view("mv", VIEW_SQL)
        rev0 = ing.view("mv").revision
        assert ing.wait_for("mv", rev0, timeout=0.05) is None  # no advance

        def feeder():
            time.sleep(0.05)
            ing.append("t", _delta(3, 2))

        th = threading.Thread(target=feeder)
        th.start()
        try:
            got = ing.wait_for("mv", rev0, timeout=10)
        finally:
            th.join()
        assert got == rev0 + 1

    def test_freshness_slo_reads_live_lags(self, monkeypatch):
        from datafusion_tpu.obs import slo

        objs = slo.objectives_from_env(
            {"DATAFUSION_TPU_SLO_MV_FRESHNESS_S": "0.5"})
        assert [(o.name, o.kind) for o in objs] == [("mv", "freshness_s")]
        ctx = _ctx()
        ing = ctx.ingest()
        view = ing.create_view("mv", VIEW_SQL)
        w = slo.SloWatchdog(capture_on_breach=False)
        w.objectives = objs
        (row,) = w.snapshot()
        assert not row["breached"]  # caught up: lag 0
        monkeypatch.setattr(view, "_pending_since", time.monotonic() - 2)
        (row,) = w.snapshot()
        assert row["breached"] and row["value"] >= 0.5


# -- cross-query megabatching beyond Aggregate -----------------------


def _csv(tmp_path, rows: int = 4000) -> str:
    rng = np.random.default_rng(0)
    path = str(tmp_path / "t.csv")
    with open(path, "w") as f:
        f.write("g,v,w\n")
        for _ in range(rows):
            f.write(f"g{int(rng.integers(0, 7))},"
                    f"{rng.integers(0, 100000)},{rng.random():.6f}\n")
    return path


def _rows_of(rel):
    from datafusion_tpu.exec.materialize import compact_batch

    rows = []
    for b in rel.batches():
        cols, _valids, dicts, n = compact_batch(b)
        decode = []
        for j, c in enumerate(cols):
            d = dicts[j]
            decode.append([d.values[x] for x in c[:n]] if d is not None
                          else list(c[:n]))
        rows += list(zip(*decode))
    return [(a, int(b)) for a, b in rows]


class TestMegabatchLanes:
    def test_topk_megabatch_direct_parity(self, tmp_path):
        from datafusion_tpu.exec.sort import SortRelation, run_topk_megabatch

        path = _csv(tmp_path)
        ctx0 = ExecutionContext()
        ctx0.register_csv("t", path, SCHEMA)
        solo = [ctx0.sql_collect(
            f"SELECT g, v FROM t ORDER BY v DESC LIMIT {k}").to_rows()
            for k in (5, 12, 7)]
        ctx = ExecutionContext()
        ctx.register_csv("t", path, SCHEMA)
        rels = [ctx.sql(f"SELECT g, v FROM t ORDER BY v DESC LIMIT {k}")
                for k in (5, 12, 7)]
        assert all(type(r) is SortRelation for r in rels)
        # the by-fingerprint kernel cache makes every limit share ONE
        # core — the precondition serve's grouping key relies on
        assert all(r.core is rels[0].core for r in rels)
        run_topk_megabatch(rels)
        for rel, want in zip(rels, solo):
            assert _rows_of(rel) == want

    def test_pipeline_megabatch_direct_parity(self, tmp_path):
        from datafusion_tpu.exec.aggregate import force_core_predicate
        from datafusion_tpu.exec.relation import (
            PipelineRelation,
            run_pipeline_megabatch,
        )

        path = _csv(tmp_path)
        ctx0 = ExecutionContext()
        ctx0.register_csv("t", path, SCHEMA)
        lits = (99000, 99900, 95000)
        solo = [ctx0.sql_collect(
            f"SELECT g, v FROM t WHERE v > {lit}").to_rows()
            for lit in lits]
        ctx = ExecutionContext()
        ctx.register_csv("t", path, SCHEMA)
        with force_core_predicate():
            rels = [ctx.sql(f"SELECT g, v FROM t WHERE v > {lit}")
                    for lit in lits]
        assert all(type(r) is PipelineRelation for r in rels)
        # literals parameterize into shared slots: ONE core, per-query
        # params, no host-side predicate residue
        assert all(r.core is rels[0].core for r in rels)
        assert all(r._host_pred_expr is None for r in rels)
        run_pipeline_megabatch(rels)
        for rel, want in zip(rels, solo):
            assert _rows_of(rel) == want

    def test_serve_groups_topk_and_pipeline(self, tmp_path):
        path = _csv(tmp_path)
        ctx0 = ExecutionContext()
        ctx0.register_csv("t", path, SCHEMA)
        solo_topk = [ctx0.sql_collect(
            f"SELECT g, v FROM t ORDER BY v DESC LIMIT {k}").to_rows()
            for k in (5, 12, 7)]
        solo_pipe = [ctx0.sql_collect(
            f"SELECT g, v FROM t WHERE v > {lit}").to_rows()
            for lit in (99000, 99900, 95000)]

        ctx = ExecutionContext(result_cache=False)
        ctx.register_csv("t", path, SCHEMA)
        c0 = METRICS.snapshot()["counts"]
        srv = ctx.serve(workers=2, window_s=0.05, megabatch_max=8)
        try:
            tickets = [srv.submit(
                f"SELECT g, v FROM t ORDER BY v DESC LIMIT {k}",
                client_id=f"c{i}") for i, k in enumerate((5, 12, 7))]
            got = [t.result(timeout=120).to_rows() for t in tickets]
            assert got == solo_topk
            t2 = [srv.submit(f"SELECT g, v FROM t WHERE v > {lit}",
                             client_id=f"c{i}")
                  for i, lit in enumerate((99000, 99900, 95000))]
            assert [t.result(timeout=120).to_rows() for t in t2] \
                == solo_pipe
        finally:
            srv.stop()
        c1 = METRICS.snapshot()["counts"]
        launched = (c1.get("serve.megabatch_launches", 0)
                    - c0.get("serve.megabatch_launches", 0))
        queries = (c1.get("serve.megabatch_queries", 0)
                   - c0.get("serve.megabatch_queries", 0))
        fallbacks = (c1.get("serve.megabatch_fallbacks", 0)
                     - c0.get("serve.megabatch_fallbacks", 0))
        assert launched >= 2  # at least one fused launch per lane
        assert queries >= 6
        assert fallbacks == 0
