"""The pyarrow confinement pool (io/io_thread.py): every pyarrow call
runs on persistent threads so short-lived server handler threads never
touch its native state (the round-3 worker SIGSEGV class)."""

import threading

import pytest

from datafusion_tpu.io.io_thread import _POOL, confined_iter, run_on_io_thread


class TestRunOnIoThread:
    def test_runs_off_caller_thread(self):
        seen = {}

        def probe():
            seen["thread"] = threading.current_thread().name
            return 41 + 1

        assert run_on_io_thread(probe) == 42
        assert seen["thread"].startswith("df-tpu-io")
        assert seen["thread"] != threading.current_thread().name

    def test_exceptions_propagate(self):
        with pytest.raises(ValueError, match="boom"):
            run_on_io_thread(lambda: (_ for _ in ()).throw(ValueError("boom")))

    def test_reentrant_submit_runs_inline(self):
        # a confined function calling a confined helper must not
        # deadlock: same-thread submits run inline
        def outer():
            return run_on_io_thread(lambda: threading.current_thread().name)

        name = _POOL[0].submit(outer)
        assert name.startswith("df-tpu-io")


class TestConfinedIter:
    def test_yields_in_order_on_pool_thread(self):
        names = []

        def gen():
            for i in range(5):
                names.append(threading.current_thread().name)
                yield i

        assert list(confined_iter(gen())) == [0, 1, 2, 3, 4]
        assert all(n.startswith("df-tpu-io") for n in names)
        assert len(set(names)) == 1  # per-generator thread affinity

    def test_exception_mid_stream(self):
        def gen():
            yield 1
            raise RuntimeError("mid-stream")

        it = confined_iter(gen())
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="mid-stream"):
            next(it)

    def test_abandoned_iterator_closes_generator(self):
        closed = threading.Event()

        def gen():
            try:
                while True:
                    yield 0
            finally:
                closed.set()

        it = confined_iter(gen())
        assert next(it) == 0
        it.close()  # abandon early
        assert closed.wait(timeout=10), "generator finally never ran"

    def test_many_concurrent_scans_from_fresh_threads(self):
        # the crash shape: scans driven from a churn of short-lived
        # threads — the confinement must serialize each generator onto
        # a stable pool thread regardless of the calling thread
        out = []
        lock = threading.Lock()

        def scan(tag):
            def gen():
                for i in range(50):
                    yield (tag, i)

            got = list(confined_iter(gen()))
            with lock:
                out.append((tag, got == [(tag, i) for i in range(50)]))

        threads = [threading.Thread(target=scan, args=(t,)) for t in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(out) == 16 and all(ok for _, ok in out)
