"""Chaos suite: deterministic fault injection through every recovery
path (SURVEY §5.3).

The fault plans are seeded and hit-counted (`testing/faults.py`), so
each scenario replays exactly: worker processes killed mid-fragment,
connection resets on response recv, corrupted frames, transient device
errors inside workers — in every case a distributed aggregate must
return results identical to the fault-free run, and the recovery
bookkeeping (failover order, probation re-admission, duplicate-response
dedup, deadlines) is asserted directly.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.errors import (
    DeviceTransientError,
    ExecutionError,
    QueryDeadlineError,
    TransientError,
    classify_transient,
)
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.parallel.coordinator import (
    DistributedContext,
    HeartbeatMonitor,
    WorkerHandle,
)
from datafusion_tpu.testing import faults
from datafusion_tpu.utils import retry
from datafusion_tpu.utils.deadline import Deadline, deadline_scope

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = Schema(
    [
        Field("region", DataType.UTF8, False),
        Field("city", DataType.UTF8, True),
        Field("v", DataType.INT64, False),
        Field("x", DataType.FLOAT64, True),
    ]
)

GROUP_SQL = (
    "SELECT region, SUM(v), COUNT(1), MIN(v), MAX(v), "
    "MIN(city), MAX(city) FROM t GROUP BY region"
)


def _write_partitions(tmp_path, n_parts=3, rows_per=300):
    rng = np.random.default_rng(23)
    regions = ["north", "south", "east", "west"]
    cities = [f"city{i}" for i in range(30)]
    paths = []
    for p in range(n_parts):
        path = tmp_path / f"part{p}.csv"
        with open(path, "w", encoding="utf-8") as f:
            f.write("region,city,v,x\n")
            for _ in range(rows_per):
                r = regions[rng.integers(0, len(regions))]
                c = cities[rng.integers(0, len(cities))] if rng.random() > 0.05 else ""
                f.write(f"{r},{c},{int(rng.integers(-1000, 1000))},"
                        f"{rng.uniform(-5, 5):.6f}\n")
        paths.append(str(path))
    return paths


def _spawn_worker(fault_plan=None, bind="127.0.0.1:0", extra_env=None):
    """One worker OS process; `fault_plan` rides the environment, so
    the injection config path itself is under test."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    if fault_plan is not None:
        env["DATAFUSION_TPU_FAULTS"] = json.dumps(fault_plan)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "datafusion_tpu.worker",
         "--bind", bind, "--device", "cpu"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    line = proc.stdout.readline()
    assert "listening on" in line, line
    host, port = line.strip().rsplit(" ", 1)[1].rsplit(":", 1)
    return proc, (host, int(port))


@pytest.fixture(scope="module")
def healthy_workers():
    procs, addrs = [], []
    try:
        for _ in range(2):
            proc, addr = _spawn_worker()
            procs.append(proc)
            addrs.append(addr)
        yield procs, addrs
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


def _register(ctx, paths):
    from datafusion_tpu.exec.datasource import CsvDataSource
    from datafusion_tpu.parallel.partition import PartitionedDataSource

    ctx.register_datasource(
        "t",
        PartitionedDataSource([CsvDataSource(p, SCHEMA, True, 131072) for p in paths]),
    )
    return ctx


def _rows(ctx, sql=GROUP_SQL):
    def key(row):
        return tuple((v is None, 0 if v is None else v) for v in row)

    return sorted(collect(ctx.sql(sql)).to_rows(), key=key)


def _local_want(paths, sql=GROUP_SQL):
    return _rows(_register(ExecutionContext(device="cpu"), paths), sql)


class TestFaultPlanMechanics:
    def test_after_and_count(self):
        with faults.scoped({"rules": [
            {"site": "s", "op": "raise", "exc": "ValueError",
             "after": 2, "count": 2},
        ]}) as plan:
            faults.check("s")  # hit 1: before `after`
            with pytest.raises(ValueError):
                faults.check("s")  # hit 2: fires
            with pytest.raises(ValueError):
                faults.check("s")  # hit 3: fires (count 2)
            faults.check("s")  # count exhausted
            snap = plan.snapshot()[0]
            assert (snap["hits"], snap["fired"]) == (4, 2)
        assert faults.active() is None

    def test_delay_range_draws_are_seeded(self):
        spec = {"site": "s", "op": "delay", "seconds": [0.0, 0.01],
                "count": 3}
        r1 = faults._Rule(spec, seed=9, index=0)
        r2 = faults._Rule(spec, seed=9, index=0)
        draws1 = [r1.delay_s("s", k) for k in range(1, 4)]
        draws2 = [r2.delay_s("s", k) for k in range(1, 4)]
        assert draws1 == draws2  # pure function of the plan
        assert len(set(draws1)) == 3  # per-firing ordinals differ
        assert all(0.0 <= d <= 0.01 for d in draws1)
        r3 = faults._Rule(spec, seed=10, index=0)
        assert r3.delay_s("s", 1) != draws1[0]  # seed moves the schedule
        # scalar form unchanged; malformed ranges rejected at install
        r4 = faults._Rule({"site": "s", "op": "delay", "seconds": 0.25},
                          0, 0)
        assert r4.delay_s("s", 1) == 0.25
        with pytest.raises(ValueError):
            faults._Rule({"site": "s", "op": "delay",
                          "seconds": [1.0, 0.5]}, 0, 0)

    def test_delay_range_fires_end_to_end(self):
        with faults.scoped({"seed": 3, "rules": [
            {"site": "s", "op": "delay", "seconds": [0.0, 0.001],
             "count": 0},
        ]}) as plan:
            faults.check("s")
            faults.check("s")
            assert plan.snapshot()[0]["fired"] == 2

    def test_site_glob_and_where(self):
        with faults.scoped({"rules": [
            {"site": "wire.*", "op": "raise", "exc": "ValueError",
             "where": {"shard": 1}, "count": 0},
        ]}):
            faults.check("device.call", shard=1)  # site mismatch
            faults.check("wire.send", shard=0)  # where mismatch
            with pytest.raises(ValueError):
                faults.check("wire.send", shard=1)

    def test_role_scoping(self):
        with faults.scoped({"rules": [
            {"site": "s", "op": "raise", "exc": "ValueError",
             "role": "worker", "count": 0},
        ]}):
            faults.check("s")  # this process is role "main"
            faults.set_role("worker")
            try:
                with pytest.raises(ValueError):
                    faults.check("s")
            finally:
                faults.set_role("main")

    def test_delay_and_seeded_probability(self):
        t0 = time.perf_counter()
        with faults.scoped({"seed": 5, "rules": [
            {"site": "s", "op": "delay", "seconds": 0.02, "count": 1},
        ]}):
            faults.check("s")
        assert time.perf_counter() - t0 >= 0.02

        def fired_sequence():
            with faults.scoped({"seed": 11, "rules": [
                {"site": "s", "op": "raise", "exc": "ValueError",
                 "p": 0.5, "count": 0},
            ]}):
                out = []
                for _ in range(20):
                    try:
                        faults.check("s")
                        out.append(0)
                    except ValueError:
                        out.append(1)
                return out

        seq = fired_sequence()
        assert seq == fired_sequence()  # same seed, same draws
        assert 0 < sum(seq) < 20

    def test_p_rule_virtual_hit_clock_is_per_site(self):
        """Each (rule, site) pair has its own hit clock: hits at one
        site never shift another site's draws (ROADMAP follow-on — the
        old shared-RNG stream reshuffled under interleaving)."""
        spec = {"seed": 9, "rules": [
            {"site": "*", "op": "raise", "exc": "ValueError",
             "p": 0.5, "count": 0},
        ]}

        def pattern(site, n, warmup_other=0):
            with faults.scoped(spec):
                for _ in range(warmup_other):
                    try:
                        faults.check("other.site")
                    except ValueError:
                        pass
                out = []
                for _ in range(n):
                    try:
                        faults.check(site)
                        out.append(0)
                    except ValueError:
                        out.append(1)
                return out

        base = pattern("a.site", 30)
        # interleaved traffic on another site leaves a.site's draws
        # untouched — the property that makes chaos soaks replayable
        assert pattern("a.site", 30, warmup_other=17) == base
        assert 0 < sum(base) < 30

    def test_p_rule_deterministic_under_thread_interleaving(self):
        """The SET of firing (site, hit-index) pairs is a pure function
        of the plan, so the per-site fire counts match no matter how
        many threads deliver the hits."""
        import threading

        spec = {"seed": 21, "rules": [
            {"site": "s", "op": "raise", "exc": "ValueError",
             "p": 0.3, "count": 0},
        ]}

        def run(n_threads, hits_total):
            fired = []
            lock = threading.Lock()

            def hammer(n):
                for _ in range(n):
                    try:
                        faults.check("s")
                    except ValueError:
                        with lock:
                            fired.append(1)

            with faults.scoped(spec):
                threads = [
                    threading.Thread(target=hammer,
                                     args=(hits_total // n_threads,))
                    for _ in range(n_threads)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=30)
            return len(fired)

        sequential = run(1, 120)
        assert 0 < sequential < 120
        for n_threads in (4, 8):
            assert run(n_threads, 120) == sequential

    def test_corrupt_is_deterministic_and_offsettable(self):
        data = bytes(range(64))
        spec = {"seed": 3, "rules": [
            {"site": "s", "op": "corrupt", "count": 0},
        ]}
        with faults.scoped(spec):
            a = bytes(faults.corrupt("s", data))
        with faults.scoped(spec):
            b = bytes(faults.corrupt("s", data))
        assert a == b != data
        with faults.scoped({"rules": [
            {"site": "s", "op": "corrupt", "offset": 0, "count": 1},
        ]}):
            c = bytes(faults.corrupt("s", data))
        assert c[0] == data[0] ^ 0x5A

    def test_install_from_file(self, tmp_path):
        p = tmp_path / "plan.json"
        p.write_text(json.dumps({"rules": [
            {"site": "s", "op": "raise", "exc": "ValueError"},
        ]}))
        try:
            faults.install(f"@{p}")
            with pytest.raises(ValueError):
                faults.check("s")
        finally:
            faults.clear()

    def test_unknown_exception_rejected_at_install(self):
        with pytest.raises(ValueError, match="unknown fault exception"):
            faults.install({"rules": [{"site": "s", "exc": "NoSuchError"}]})
        faults.clear()


class TestTypedRetry:
    def test_classification_is_typed(self):
        # the error types jax raises are matched by NAME (no jax import
        # needed to classify) and by leading status token — not by
        # scanning free text in the retry loop
        XlaRuntimeError = type("XlaRuntimeError", (Exception,), {})
        assert isinstance(
            classify_transient(XlaRuntimeError("UNAVAILABLE: socket closed")),
            DeviceTransientError,
        )
        assert isinstance(
            classify_transient(XlaRuntimeError("DEADLINE_EXCEEDED: rpc")),
            DeviceTransientError,
        )
        assert classify_transient(XlaRuntimeError("INVALID_ARGUMENT: shape")) is None
        # wrapped messages: the status token is not the leading word —
        # the marker fallback must still classify these as transient
        assert isinstance(
            classify_transient(
                XlaRuntimeError("Error executing computation: "
                                "UNAVAILABLE: channel closed")
            ),
            DeviceTransientError,
        )
        assert classify_transient(ValueError("UNAVAILABLE: nope")) is None
        assert isinstance(classify_transient(ConnectionResetError()), TransientError)
        # already-typed errors pass through unchanged
        e = DeviceTransientError("injected")
        assert classify_transient(e) is e

    def test_backoff_capped_exponential_full_jitter(self):
        retry.seed_backoff(1234)
        seq = [retry.backoff_s(a, base=0.25, cap=5.0) for a in range(1, 12)]
        retry.seed_backoff(1234)
        assert seq == [retry.backoff_s(a, base=0.25, cap=5.0) for a in range(1, 12)]
        for a, d in enumerate(seq, start=1):
            assert 0.0 <= d <= min(5.0, 0.25 * 2 ** (a - 1))
        # jitter: the ladder must not be the deterministic ceiling
        assert len({round(d, 6) for d in seq}) > 3

    def test_device_call_retries_typed_transients(self, monkeypatch):
        monkeypatch.setattr(retry, "_BASE_S", 0.001)
        calls = []
        with faults.scoped({"rules": [
            {"site": "device.call", "op": "raise",
             "exc": "DeviceTransientError", "count": 2},
        ]}):
            out = retry.device_call(lambda: calls.append(1) or "ok")
        assert out == "ok" and len(calls) == 1

    def test_device_call_permanent_error_raises_immediately(self):
        calls = []
        with faults.scoped({"rules": [
            {"site": "device.call", "op": "raise",
             "exc": "ExecutionError", "count": 0},
        ]}) as plan:
            with pytest.raises(ExecutionError):
                retry.device_call(lambda: calls.append(1))
            assert plan.snapshot()[0]["fired"] == 1  # no second attempt
        assert not calls

    def test_device_call_exhausts_attempts(self, monkeypatch):
        monkeypatch.setattr(retry, "_BASE_S", 0.001)
        monkeypatch.setattr(retry, "_ATTEMPTS", 3)
        with faults.scoped({"rules": [
            {"site": "device.call", "op": "raise",
             "exc": "DeviceTransientError", "count": 0},
        ]}) as plan:
            with pytest.raises(DeviceTransientError):
                retry.device_call(lambda: "never")
            assert plan.snapshot()[0]["fired"] == 3

    def test_deadline_bounds_retry_sleeps(self, monkeypatch):
        # backoff wants seconds; the deadline has milliseconds — the
        # call must fail fast with the typed deadline error, not sleep
        monkeypatch.setattr(retry, "_BASE_S", 30.0)
        monkeypatch.setattr(retry, "_CAP_S", 30.0)
        retry.seed_backoff(0)
        t0 = time.perf_counter()
        with faults.scoped({"rules": [
            {"site": "device.call", "op": "raise",
             "exc": "DeviceTransientError", "count": 0},
        ]}):
            with deadline_scope(Deadline.after(0.01)):
                with pytest.raises(QueryDeadlineError):
                    retry.device_call(lambda: "never")
        assert time.perf_counter() - t0 < 5.0


class _ScriptedHandle(WorkerHandle):
    """WorkerHandle whose request() runs a script instead of a socket."""

    def __init__(self, name, script, log):
        super().__init__(name, 0)
        self._script = script  # callable(msg) -> response dict (or raises)
        self._log = log
        self.probe_ok = False

    def request(self, msg, timeout=-1):
        self._log.append((self.host, msg.get("type")))
        return self._script(msg)

    def probe(self):
        self._log.append((self.host, "probe"))
        return self.probe_ok


class TestCoordinatorBookkeeping:
    def test_failover_reassigns_in_rotation_order(self):
        from datafusion_tpu.parallel.coordinator import _dispatch
        from datafusion_tpu.parallel.physical import PlanFragment

        log = []

        def dies(msg):
            raise ConnectionResetError("boom")

        a = _ScriptedHandle("a", dies, log)
        b = _ScriptedHandle("b", lambda m: {"type": "partial_state"}, log)
        frag = PlanFragment(0, 1, {}, {}, "q")
        out = _dispatch([a, b], [frag], "execute_fragment")
        assert [h for h, _ in log] == ["a", "b"]  # a fails, b takes over
        assert out[0][0] is frag and not a.alive and b.alive

    def test_no_workers_left_error_message(self):
        from datafusion_tpu.parallel.coordinator import _dispatch
        from datafusion_tpu.parallel.physical import PlanFragment

        log = []

        def dies(msg):
            raise ConnectionRefusedError("nope")

        handles = [_ScriptedHandle(n, dies, log) for n in ("a", "b")]
        with pytest.raises(ExecutionError, match="all 2 workers are down"):
            _dispatch(handles, [PlanFragment(0, 1, {}, {}, "q")], "execute_fragment")
        # the last-gasp probe rounds ran before giving up
        assert [h for h, k in log if k == "probe"]

    def test_dispatch_readmits_recovered_worker(self):
        from datafusion_tpu.parallel.coordinator import _dispatch
        from datafusion_tpu.parallel.physical import PlanFragment

        log = []
        state = {"calls": 0}

        def flaky(msg):
            state["calls"] += 1
            if state["calls"] == 1:
                raise ConnectionResetError("restarting")
            return {"type": "partial_state"}

        a = _ScriptedHandle("a", flaky, log)
        a.probe_ok = True  # "restarted" by the time dispatch re-probes
        out = _dispatch([a], [PlanFragment(0, 1, {}, {}, "q")], "execute_fragment")
        assert out[0][1]["type"] == "partial_state"
        assert a.alive  # re-admitted, not dead forever

    def test_worker_error_not_masked_by_lapsed_deadline(self):
        # a genuine worker error arriving just as the deadline lapses
        # must keep its message — only request TIMEOUTS convert
        from datafusion_tpu.parallel.coordinator import _dispatch
        from datafusion_tpu.parallel.physical import PlanFragment

        def slow_error(msg):
            time.sleep(0.08)
            raise ExecutionError("worker says: unknown aggregate")

        a = _ScriptedHandle("a", slow_error, [])
        with pytest.raises(ExecutionError, match="unknown aggregate"):
            _dispatch([a], [PlanFragment(0, 1, {}, {}, "q")],
                      "execute_fragment", Deadline.after(0.03))

    def test_dispatch_deadline_expires(self):
        from datafusion_tpu.parallel.coordinator import _dispatch
        from datafusion_tpu.parallel.physical import PlanFragment

        a = _ScriptedHandle("a", lambda m: {"type": "partial_state"}, [])
        with pytest.raises(QueryDeadlineError):
            _dispatch([a], [PlanFragment(0, 1, {}, {}, "q")],
                      "execute_fragment", Deadline.after(-1.0))

    def test_heartbeat_probation_and_failure_detection(self):
        log = []
        a = _ScriptedHandle("a", lambda m: None, log)
        mon = HeartbeatMonitor([a], interval=0.01, probation_pings=2,
                               fail_threshold=2)
        # up worker missing two consecutive probes goes down
        a.probe_ok = False
        mon.poll_once()
        assert a.alive  # one miss is not dead (slow != dead)
        mon.poll_once()
        assert not a.alive
        # recovery: two consecutive healthy probes = one probation cycle
        a.probe_ok = True
        mon.poll_once()
        assert not a.alive  # probation
        mon.poll_once()
        assert a.alive  # re-admitted

    def test_heartbeat_streaks_reset_on_external_state_flip(self):
        # dispatch failover flips alive between monitor cycles: stale
        # probe streaks must not shortcut probation / fail thresholds
        a = _ScriptedHandle("a", lambda m: None, [])
        mon = HeartbeatMonitor([a], interval=0.01, probation_pings=2,
                               fail_threshold=2)
        a.probe_ok = True
        for _ in range(5):
            mon.poll_once()  # long healthy streak
        a.mark_down()  # dispatch-side failover, not the monitor
        mon.poll_once()
        assert not a.alive  # stale ok-streak must not readmit instantly
        mon.poll_once()
        assert a.alive  # two FRESH consecutive probes readmit
        # symmetric: accumulate misses while down, then a dispatch-side
        # last-gasp re-admission — the stale bad-streak must not demote
        # the worker on its first missed probe
        a.probe_ok = False
        for _ in range(3):
            mon.poll_once()
        assert not a.alive
        a.readmit()
        mon.poll_once()
        assert a.alive  # one fresh miss < fail_threshold
        mon.poll_once()
        assert not a.alive  # two fresh consecutive misses demote


class TestDistributedChaos:
    """Real worker OS processes + seeded fault plans: distributed
    results must be identical to the fault-free local run."""

    def test_worker_killed_mid_fragment(self, tmp_path, healthy_workers):
        _, addrs = healthy_workers
        paths = _write_partitions(tmp_path)
        crashy, crashy_addr = _spawn_worker(fault_plan={"rules": [
            {"site": "worker.fragment", "op": "kill", "after": 1},
        ]})
        try:
            dctx = _register(DistributedContext([crashy_addr, *addrs]), paths)
            assert _rows(dctx) == _local_want(paths)
            assert crashy.wait(timeout=10) == 17  # died by injected fault
            crashy_handle = dctx.workers[0]
            assert not crashy_handle.alive  # marked down by failover
        finally:
            if crashy.poll() is None:
                crashy.terminate()
                crashy.wait(timeout=10)

    def test_connection_reset_on_recv(self, tmp_path, healthy_workers):
        # the response is lost AFTER the worker already executed the
        # fragment: failover replays it elsewhere, and the merge must
        # still fold each fragment exactly once
        _, addrs = healthy_workers
        paths = _write_partitions(tmp_path)
        dctx = _register(DistributedContext(addrs), paths)
        with faults.scoped({"rules": [
            {"site": "wire.recv", "op": "raise",
             "exc": "ConnectionResetError", "after": 1, "count": 1},
        ]}) as plan:
            got = _rows(dctx)
            assert plan.snapshot()[0]["fired"] == 1
        assert got == _local_want(paths)

    def test_corrupted_frame_fails_over(self, tmp_path, healthy_workers):
        _, addrs = healthy_workers
        paths = _write_partitions(tmp_path)
        dctx = _register(DistributedContext(addrs), paths)
        with faults.scoped({"rules": [
            {"site": "wire.recv.payload", "op": "corrupt",
             "offset": 0, "after": 1, "count": 1},
        ]}) as plan:
            got = _rows(dctx)
            assert plan.snapshot()[0]["fired"] == 1
        assert got == _local_want(paths)

    def test_transient_device_errors_inside_worker(self, tmp_path,
                                                   healthy_workers):
        _, addrs = healthy_workers
        paths = _write_partitions(tmp_path)
        flaky, flaky_addr = _spawn_worker(
            fault_plan={"rules": [
                # two consecutive transient device failures, then clean
                {"site": "device.call", "op": "raise",
                 "exc": "DeviceTransientError", "count": 2},
            ]},
            extra_env={"DATAFUSION_TPU_RETRY_BASE_S": "0.001"},
        )
        try:
            dctx = _register(DistributedContext([flaky_addr, *addrs]), paths)
            assert _rows(dctx) == _local_want(paths)
            assert flaky.poll() is None  # retried internally, still up
        finally:
            flaky.terminate()
            flaky.wait(timeout=10)

    def test_duplicate_response_not_double_merged(self, tmp_path,
                                                  healthy_workers,
                                                  monkeypatch):
        # simulate a replayed fragment whose first (merely slow)
        # response ALSO arrives: the merge must drop the duplicate, or
        # SUM/COUNT double and dictionary codes remap twice
        from datafusion_tpu.parallel import coordinator as coord_mod

        _, addrs = healthy_workers
        paths = _write_partitions(tmp_path)
        real = coord_mod._dispatch

        def duplicating(workers, fragments, request_type, deadline=None,
                        **kw):
            out = real(workers, fragments, request_type, deadline, **kw)
            return out + [out[0]]

        monkeypatch.setattr(coord_mod, "_dispatch", duplicating)
        dctx = _register(DistributedContext(addrs), paths)
        assert _rows(dctx) == _local_want(paths)
        from datafusion_tpu.utils.metrics import METRICS

        assert METRICS.snapshot()["counts"].get(
            "coord.duplicate_responses_dropped"
        )

    def test_killed_worker_readmitted_after_restart(self, tmp_path,
                                                    healthy_workers):
        _, addrs = healthy_workers
        paths = _write_partitions(tmp_path)
        with socket.socket() as s:  # reserve a fixed port for the restart
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        victim, victim_addr = _spawn_worker(bind=f"127.0.0.1:{port}")
        # result_cache=False: this test re-runs the SAME query to assert
        # failover/readmission mechanics — a coordinator result-cache
        # hit would answer without dispatching anything
        dctx = _register(
            DistributedContext([victim_addr, *addrs], result_cache=False),
            paths,
        )
        want = _local_want(paths)
        try:
            assert _rows(dctx) == want
            victim.kill()
            victim.wait(timeout=10)
            assert _rows(dctx) == want  # survivors cover the fragments
            handle = dctx.workers[0]
            assert not handle.alive
            # restart on the same endpoint; one probation cycle of the
            # heartbeat loop re-admits it
            victim, _ = _spawn_worker(bind=f"127.0.0.1:{port}")
            mon = HeartbeatMonitor(dctx.workers, interval=0.05,
                                   probation_pings=1)
            mon.poll_once()
            assert handle.alive
            assert _rows(dctx) == want
            # the background thread form works too
            handle.mark_down()
            mon.start()
            try:
                deadline = time.monotonic() + 30
                while not handle.alive and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert handle.alive
            finally:
                mon.stop()
        finally:
            if victim.poll() is None:
                victim.terminate()
                victim.wait(timeout=10)

    def test_query_deadline_enforced(self, tmp_path, healthy_workers):
        _, addrs = healthy_workers
        paths = _write_partitions(tmp_path, n_parts=2, rows_per=50)
        dctx = _register(
            DistributedContext(addrs, query_deadline_s=1e-6), paths
        )
        with pytest.raises(QueryDeadlineError):
            _rows(dctx)
        # a sane budget flows through and succeeds
        dctx2 = _register(
            DistributedContext(addrs, query_deadline_s=120.0), paths
        )
        assert _rows(dctx2) == _local_want(paths)


class TestWireHardening:
    def test_unparseable_frame_raises_protocol_error(self):
        from datafusion_tpu.parallel.wire import ProtocolError, recv_msg

        a, b = socket.socketpair()
        try:
            garbage = b"\x02not json at all"
            a.sendall(len(garbage).to_bytes(8, "big") + garbage)
            with pytest.raises(ProtocolError):
                recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_protocol_error_is_connection_error(self):
        from datafusion_tpu.parallel.wire import ProtocolError

        assert issubclass(ProtocolError, ConnectionError)


class TestLinkRateCacheKey:
    def test_keyed_by_device_identity(self):
        from datafusion_tpu.exec.batch import _link_cache_key

        class Dev:
            def __init__(self, id):
                self.id = id

            def __repr__(self):
                return f"Dev({self.id})"

        assert _link_cache_key(None, "tpu") == "tpu"
        k0 = _link_cache_key(Dev(0), "tpu")
        k1 = _link_cache_key(Dev(1), "tpu")
        assert k0 != k1  # same platform, different chips: separate rates
        assert k0 == _link_cache_key(Dev(0), "tpu")
