"""Wire protocol: length-prefixed JSON frames with raw binary segments
for bulk arrays (parallel/wire.py).  The reference planned HTTP + Arrow
IPC (`README.md:33`); this is the TCP equivalent, round-tripped over a
real socketpair."""

import socket
import threading

import numpy as np
import pytest

from datafusion_tpu.parallel.wire import (
    INLINE_MAX,
    WIRE_VERSION,
    BinWriter,
    ProtocolError,
    crc_for_peer,
    dec_array,
    enc_array,
    recv_msg,
    send_msg,
)
from datafusion_tpu.testing import faults


def _roundtrip(obj, bw=None, crc=False):
    a, b = socket.socketpair()
    try:
        out = {}

        def rx():
            try:
                out["msg"] = recv_msg(b)
            except BaseException as e:  # surfaced after join
                out["err"] = e

        t = threading.Thread(target=rx)
        t.start()
        send_msg(a, obj, bw, crc=crc)
        t.join(timeout=10)
        assert not t.is_alive(), "receiver did not finish"
        if "err" in out:
            raise out["err"]
        return out["msg"]
    finally:
        a.close()
        b.close()


class TestWireFrames:
    def test_legacy_json_roundtrip(self):
        msg = _roundtrip({"type": "ping", "n": 7})
        assert msg == {"type": "ping", "n": 7}

    def test_inline_base64_small_array(self):
        bw = BinWriter()
        enc = enc_array(np.arange(4, dtype=np.int64), bw)
        assert "data" in enc and "bin" not in enc  # under INLINE_MAX
        assert not bw.chunks
        msg = _roundtrip({"a": enc}, bw)
        np.testing.assert_array_equal(dec_array(msg["a"]), np.arange(4))

    @pytest.mark.parametrize("dtype", [np.int64, np.float64, np.int32, np.bool_])
    def test_binary_segment_roundtrip(self, dtype):
        rng = np.random.default_rng(5)
        arr = (rng.uniform(0, 2, 10_000) * 100).astype(dtype)
        bw = BinWriter()
        enc = enc_array(arr, bw)
        assert enc["bin"] == 0 and len(bw.chunks) == 1
        msg = _roundtrip({"type": "rows", "col": enc}, bw)
        got = dec_array(msg["col"])
        np.testing.assert_array_equal(got, arr)
        got[:1] = got[:1]  # decoded arrays must be writable (combiners mutate)

    def test_mixed_nested_payload(self):
        bw = BinWriter()
        big = np.arange(5000, dtype=np.float64)
        small = np.arange(3, dtype=np.int32)
        obj = {
            "type": "partial_state",
            "slots": [enc_array(big, bw), enc_array(big * 2, bw)],
            "counts": enc_array(small, bw),
            "nested": {"key_rows": enc_array(big.reshape(100, 50), bw)},
            "plain": ["x", 1, None],
        }
        msg = _roundtrip(obj, bw)
        np.testing.assert_array_equal(dec_array(msg["slots"][0]), big)
        np.testing.assert_array_equal(dec_array(msg["slots"][1]), big * 2)
        np.testing.assert_array_equal(dec_array(msg["counts"]), small)
        np.testing.assert_array_equal(
            dec_array(msg["nested"]["key_rows"]), big.reshape(100, 50)
        )
        assert msg["plain"] == ["x", 1, None]

    def test_binary_beats_base64_on_bulk(self):
        # the point of the format: 1M rows ship in ~8 MB, not ~10.7 MB
        # of base64, with no json-parse of the payload
        import json

        arr = np.arange(1_000_000, dtype=np.float64)
        bw = BinWriter()
        enc = enc_array(arr, bw)
        binary_bytes = sum(len(c) for c in bw.chunks) + len(json.dumps(enc))
        legacy_bytes = len(json.dumps(enc_array(arr)))
        assert binary_bytes < 0.8 * legacy_bytes

    def test_threshold_boundary(self):
        bw = BinWriter()
        at = np.zeros(INLINE_MAX, np.uint8)
        over = np.zeros(INLINE_MAX + 1, np.uint8)
        assert "data" in enc_array(at, bw)
        assert "bin" in enc_array(over, bw)


class TestWireCrc:
    """Per-segment CRC32 (wire v2): a bit-flip inside a RAW segment —
    which parses fine and silently poisons the merge on v1 frames —
    fails loudly as ProtocolError, which subclasses ConnectionError so
    the coordinator's existing failover path replays the fragment."""

    def _payload(self):
        bw = BinWriter()
        arr = np.arange(10_000, dtype=np.int64)
        return {"type": "rows", "col": enc_array(arr, bw)}, bw, arr

    def test_crc_roundtrip(self):
        obj, bw, arr = self._payload()
        msg = _roundtrip(obj, bw, crc=True)
        assert len(msg["_crc32"]) == 1
        np.testing.assert_array_equal(dec_array(msg["col"]), arr)

    def test_raw_flip_without_crc_parses_silently(self):
        # documents the v1 hazard the CRC closes: offset 5000 lands deep
        # inside the 80 kB RAW segment, far past the JSON region
        obj, bw, arr = self._payload()
        with faults.scoped({"rules": [
            {"site": "wire.recv.payload", "op": "corrupt", "offset": 5000},
        ]}):
            msg = _roundtrip(obj, bw, crc=False)
        got = dec_array(msg["col"])
        assert not np.array_equal(got, arr)  # poisoned, no error raised

    def test_raw_flip_with_crc_raises_protocol_error(self):
        obj, bw, _ = self._payload()
        with faults.scoped({"rules": [
            {"site": "wire.recv.payload", "op": "corrupt", "offset": 5000},
        ]}):
            with pytest.raises(ProtocolError, match="CRC32 mismatch"):
                _roundtrip(obj, bw, crc=True)

    def test_crc_list_shape_mismatch_raises(self):
        obj, bw, _ = self._payload()
        obj["_crc32"] = [1, 2, 3]  # wrong length, spoofed by sender
        with pytest.raises(ProtocolError, match="CRC list shape"):
            _roundtrip(obj, bw, crc=False)

    def test_handshake_gating(self):
        assert crc_for_peer({"wire_version": WIRE_VERSION})
        assert crc_for_peer({"wire_version": 3})
        assert not crc_for_peer({"wire_version": 1})
        assert not crc_for_peer({})  # legacy peer never advertised
        assert not crc_for_peer({"wire_version": "junk"})

    def test_worker_responses_carry_crc_for_v2_peers(self):
        """End to end over a live in-process worker: a v2 request gets a
        CRC-protected binary response; a legacy request does not."""
        import json
        import threading as th

        from datafusion_tpu.parallel.worker import serve

        server = serve("127.0.0.1:0", device="cpu")
        t = th.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            host, port = server.server_address[:2]
            frag = json.dumps({
                "shard": 0, "num_shards": 1, "query_id": "q",
                "plan": _scan_plan_json(),
                "datasource": _csv_meta(),
            })
            for version, expect_crc in ((WIRE_VERSION, True), (None, False)):
                msg = {"type": "execute_fragment", "fragment": frag}
                if version is not None:
                    msg["wire_version"] = version
                with socket.create_connection((host, port), timeout=10) as s:
                    send_msg(s, msg)
                    resp = recv_msg(s)
                assert resp["type"] == "partial_state", resp
                assert ("_crc32" in resp) == expect_crc
        finally:
            server.shutdown()
            server.server_close()


_CSV_PATH = None


def _csv_meta():
    global _CSV_PATH
    if _CSV_PATH is None:
        import tempfile

        fd = tempfile.NamedTemporaryFile(
            "w", suffix=".csv", delete=False, encoding="utf-8"
        )
        fd.write("g,v\n")
        # enough rows that the accumulator arrays clear INLINE_MAX and
        # ship as RAW segments (the CRC-covered region)
        for i in range(2000):
            fd.write(f"{i % 200},{i}\n")
        fd.close()
        _CSV_PATH = fd.name
    from datafusion_tpu.datatypes import DataType, Field, Schema

    schema = Schema([
        Field("g", DataType.INT64, False),
        Field("v", DataType.INT64, False),
    ]).to_json()
    return {"CsvFile": {"filename": _CSV_PATH, "schema": schema,
                        "has_header": True, "projection": None}}


def _scan_plan_json():
    from datafusion_tpu.datatypes import DataType, Field, Schema
    from datafusion_tpu.plan.expr import AggregateFunction, Column
    from datafusion_tpu.plan.logical import Aggregate, TableScan

    schema = Schema([
        Field("g", DataType.INT64, False),
        Field("v", DataType.INT64, False),
    ])
    scan = TableScan("default", "t", schema)
    agg = Aggregate(
        scan,
        [Column(0)],
        [AggregateFunction("SUM", [Column(1)], DataType.INT64)],
        Schema([Field("g", DataType.INT64, False),
                Field("SUM", DataType.INT64, False)]),
    )
    return agg.to_json()
