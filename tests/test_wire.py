"""Wire protocol: length-prefixed JSON frames with raw binary segments
for bulk arrays (parallel/wire.py).  The reference planned HTTP + Arrow
IPC (`README.md:33`); this is the TCP equivalent, round-tripped over a
real socketpair."""

import socket
import threading

import numpy as np
import pytest

from datafusion_tpu.parallel.wire import (
    INLINE_MAX,
    BinWriter,
    dec_array,
    enc_array,
    recv_msg,
    send_msg,
)


def _roundtrip(obj, bw=None):
    a, b = socket.socketpair()
    try:
        out = {}

        def rx():
            try:
                out["msg"] = recv_msg(b)
            except BaseException as e:  # surfaced after join
                out["err"] = e

        t = threading.Thread(target=rx)
        t.start()
        send_msg(a, obj, bw)
        t.join(timeout=10)
        assert not t.is_alive(), "receiver did not finish"
        if "err" in out:
            raise out["err"]
        return out["msg"]
    finally:
        a.close()
        b.close()


class TestWireFrames:
    def test_legacy_json_roundtrip(self):
        msg = _roundtrip({"type": "ping", "n": 7})
        assert msg == {"type": "ping", "n": 7}

    def test_inline_base64_small_array(self):
        bw = BinWriter()
        enc = enc_array(np.arange(4, dtype=np.int64), bw)
        assert "data" in enc and "bin" not in enc  # under INLINE_MAX
        assert not bw.chunks
        msg = _roundtrip({"a": enc}, bw)
        np.testing.assert_array_equal(dec_array(msg["a"]), np.arange(4))

    @pytest.mark.parametrize("dtype", [np.int64, np.float64, np.int32, np.bool_])
    def test_binary_segment_roundtrip(self, dtype):
        rng = np.random.default_rng(5)
        arr = (rng.uniform(0, 2, 10_000) * 100).astype(dtype)
        bw = BinWriter()
        enc = enc_array(arr, bw)
        assert enc["bin"] == 0 and len(bw.chunks) == 1
        msg = _roundtrip({"type": "rows", "col": enc}, bw)
        got = dec_array(msg["col"])
        np.testing.assert_array_equal(got, arr)
        got[:1] = got[:1]  # decoded arrays must be writable (combiners mutate)

    def test_mixed_nested_payload(self):
        bw = BinWriter()
        big = np.arange(5000, dtype=np.float64)
        small = np.arange(3, dtype=np.int32)
        obj = {
            "type": "partial_state",
            "slots": [enc_array(big, bw), enc_array(big * 2, bw)],
            "counts": enc_array(small, bw),
            "nested": {"key_rows": enc_array(big.reshape(100, 50), bw)},
            "plain": ["x", 1, None],
        }
        msg = _roundtrip(obj, bw)
        np.testing.assert_array_equal(dec_array(msg["slots"][0]), big)
        np.testing.assert_array_equal(dec_array(msg["slots"][1]), big * 2)
        np.testing.assert_array_equal(dec_array(msg["counts"]), small)
        np.testing.assert_array_equal(
            dec_array(msg["nested"]["key_rows"]), big.reshape(100, 50)
        )
        assert msg["plain"] == ["x", 1, None]

    def test_binary_beats_base64_on_bulk(self):
        # the point of the format: 1M rows ship in ~8 MB, not ~10.7 MB
        # of base64, with no json-parse of the payload
        import json

        arr = np.arange(1_000_000, dtype=np.float64)
        bw = BinWriter()
        enc = enc_array(arr, bw)
        binary_bytes = sum(len(c) for c in bw.chunks) + len(json.dumps(enc))
        legacy_bytes = len(json.dumps(enc_array(arr)))
        assert binary_bytes < 0.8 * legacy_bytes

    def test_threshold_boundary(self):
        bw = BinWriter()
        at = np.zeros(INLINE_MAX, np.uint8)
        over = np.zeros(INLINE_MAX + 1, np.uint8)
        assert "data" in enc_array(at, bw)
        assert "bin" in enc_array(over, bw)
