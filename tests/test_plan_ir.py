"""Plan IR tests.

Ports the reference's IR-level tests:
- `serialize_plan` (`src/logicalplan.rs:609-648`) — exact JSON wire format.
- supertype/coercion table behavior (`src/logicalplan.rs:443-602`).
- Expr Debug formats asserted indirectly by the planner golden tests.
"""

import json

import pytest

from datafusion_tpu import (
    Cast,
    Column,
    DataType,
    Field,
    Literal,
    LogicalPlan,
    Operator,
    ScalarValue,
    Schema,
    SortExpr,
    StructType,
    TableScan,
    can_coerce_from,
    get_supertype,
)
from datafusion_tpu.plan.expr import AggregateFunction, BinaryExpr, ScalarFunction


def test_serialize_plan():
    # ported from reference logicalplan.rs:609-648 (the distributed-mode
    # wire-format contract)
    schema = Schema(
        [
            Field("first_name", DataType.UTF8, False),
            Field("last_name", DataType.UTF8, False),
            Field(
                "address",
                StructType(
                    [
                        Field("street", DataType.UTF8, False),
                        Field("zip", DataType.UINT16, False),
                    ]
                ),
                False,
            ),
        ]
    )
    plan = TableScan("", "people", schema, [0, 1, 4])
    expected = (
        '{"TableScan":{'
        '"schema_name":"",'
        '"table_name":"people",'
        '"schema":{"fields":['
        '{"name":"first_name","data_type":"Utf8","nullable":false},'
        '{"name":"last_name","data_type":"Utf8","nullable":false},'
        '{"name":"address","data_type":{"Struct":'
        "["
        '{"name":"street","data_type":"Utf8","nullable":false},'
        '{"name":"zip","data_type":"UInt16","nullable":false}]},"nullable":false}'
        "]},"
        '"projection":[0,1,4]}}'
    )
    assert plan.to_json_str() == expected


def test_plan_json_roundtrip():
    schema = Schema([Field("a", DataType.INT32, False), Field("b", DataType.FLOAT64, True)])
    plan = TableScan("", "t", schema, None)
    s = plan.to_json_str()
    back = LogicalPlan.from_json_str(s)
    assert back.to_json_str() == s
    assert back.schema == schema


def test_expr_json_roundtrip():
    from datafusion_tpu.plan.expr import Expr

    e = BinaryExpr(
        Cast(Column(3), DataType.INT64), Operator.GtEq, Literal(ScalarValue.int64(21))
    )
    s = json.dumps(e.to_json())
    back = Expr.from_json(json.loads(s))
    assert back == e
    assert repr(back) == "CAST(#3 AS Int64) GtEq Int64(21)"


class TestSupertype:
    # spot-checks against the reference's explicit pair table
    # (logicalplan.rs:443-551)
    @pytest.mark.parametrize(
        "l,r,expected",
        [
            (DataType.UINT8, DataType.INT8, DataType.INT8),
            (DataType.UINT8, DataType.INT64, DataType.INT64),
            (DataType.UINT32, DataType.INT32, DataType.INT32),
            (DataType.UINT64, DataType.INT64, DataType.INT64),
            (DataType.INT32, DataType.UINT16, DataType.INT32),
            (DataType.UINT8, DataType.UINT64, DataType.UINT64),
            (DataType.INT8, DataType.INT16, DataType.INT16),
            (DataType.INT64, DataType.FLOAT32, DataType.FLOAT32),
            (DataType.UINT64, DataType.FLOAT64, DataType.FLOAT64),
            (DataType.FLOAT32, DataType.FLOAT64, DataType.FLOAT64),
            (DataType.FLOAT32, DataType.INT8, DataType.FLOAT32),
            (DataType.UTF8, DataType.UTF8, DataType.UTF8),
            (DataType.BOOLEAN, DataType.BOOLEAN, DataType.BOOLEAN),
        ],
    )
    def test_pairs(self, l, r, expected):
        assert get_supertype(l, r) == expected
        assert get_supertype(r, l) == expected

    @pytest.mark.parametrize(
        "l,r",
        [
            # the reference table deliberately omits these
            (DataType.UINT16, DataType.INT8),
            (DataType.UINT64, DataType.INT32),
            (DataType.UTF8, DataType.INT32),
            (DataType.BOOLEAN, DataType.INT8),
        ],
    )
    def test_no_supertype(self, l, r):
        assert get_supertype(l, r) is None
        assert get_supertype(r, l) is None


class TestCoercion:
    def test_signed_accepts_narrower_signed_only(self):
        assert can_coerce_from(DataType.INT64, DataType.INT8)
        assert can_coerce_from(DataType.INT32, DataType.INT32)
        assert not can_coerce_from(DataType.INT64, DataType.UINT8)
        assert not can_coerce_from(DataType.INT8, DataType.INT16)

    def test_float_targets(self):
        assert can_coerce_from(DataType.FLOAT32, DataType.INT64)
        assert not can_coerce_from(DataType.FLOAT32, DataType.FLOAT64)
        assert can_coerce_from(DataType.FLOAT64, DataType.FLOAT32)
        assert can_coerce_from(DataType.FLOAT64, DataType.UINT64)

    def test_utf8_and_bool_targets(self):
        # reference logicalplan.rs:553-602 has no Utf8/Boolean arms at all:
        # even Utf8<-Utf8 is false (equal types never reach this check)
        assert not can_coerce_from(DataType.UTF8, DataType.INT32)
        assert not can_coerce_from(DataType.BOOLEAN, DataType.INT8)
        assert not can_coerce_from(DataType.UTF8, DataType.UTF8)
        assert not can_coerce_from(DataType.BOOLEAN, DataType.BOOLEAN)


class TestExprRepr:
    # the Debug formats the planner golden tests depend on
    def test_column(self):
        assert repr(Column(0)) == "#0"

    def test_literals(self):
        assert repr(Literal(ScalarValue.int64(1))) == "Int64(1)"
        assert repr(Literal(ScalarValue.utf8("CO"))) == 'Utf8("CO")'
        assert repr(Literal(ScalarValue.float64(9.0))) == "Float64(9.0)"
        assert repr(Literal(ScalarValue.boolean(True))) == "Boolean(true)"

    def test_binary(self):
        e = Column(4).eq(Literal(ScalarValue.utf8("CO")))
        assert repr(e) == '#4 Eq Utf8("CO")'

    def test_cast(self):
        assert repr(Cast(Column(3), DataType.INT64)) == "CAST(#3 AS Int64)"

    def test_sort(self):
        assert repr(SortExpr(Column(0), True)) == "#0 ASC"
        assert repr(SortExpr(Column(0), False)) == "#0 DESC"

    def test_functions(self):
        f = ScalarFunction("sqrt", [Cast(Column(3), DataType.FLOAT64)], DataType.FLOAT64)
        assert repr(f) == "sqrt(CAST(#3 AS Float64))"
        a = AggregateFunction("MIN", [Column(3)], DataType.INT32)
        assert repr(a) == "MIN(#3)"

    def test_is_null(self):
        assert repr(Column(1).is_null()) == "#1 IS NULL"
        assert repr(Column(1).is_not_null()) == "#1 IS NOT NULL"


def test_collect_columns():
    # ported from reference test_collect_expr (sqlplanner.rs:668-688)
    accum: set = set()
    Cast(Column(3), DataType.FLOAT64).collect_columns(accum)
    Cast(Column(3), DataType.FLOAT64).collect_columns(accum)
    assert accum == {3}


def test_cast_to():
    schema = Schema([Field("age", DataType.INT32, False)])
    # same type: no-op
    assert Column(0).cast_to(DataType.INT32, schema) == Column(0)
    # widening: wrapped in Cast
    assert Column(0).cast_to(DataType.INT64, schema) == Cast(Column(0), DataType.INT64)
    # illegal: raises
    from datafusion_tpu.errors import PlanError

    with pytest.raises(PlanError):
        Column(0).cast_to(DataType.UINT8, schema)
