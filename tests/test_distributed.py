"""Multi-process distributed execution: coordinator + worker nodes.

Spawns real `python -m datafusion_tpu.worker` OS processes (the worker
entry point the reference planned but never built, `Cargo.toml:25-27`)
and runs partitioned queries across >= 2 of them over the TCP
fragment-shipping protocol, asserting exact agreement with the
single-process engine on identical inputs.  Also exercises the
failure path: a killed worker's fragments reassign to the survivors.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.parallel.coordinator import DistributedContext

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = Schema(
    [
        Field("region", DataType.UTF8, False),
        Field("city", DataType.UTF8, True),
        Field("v", DataType.INT64, False),
        Field("x", DataType.FLOAT64, True),
    ]
)


def _write_partitions(tmp_path, n_parts=4, rows_per=500):
    rng = np.random.default_rng(17)
    regions = ["north", "south", "east", "west", "über"]  # unicode too
    cities = [f"city{i}" for i in range(40)]
    paths = []
    for p in range(n_parts):
        path = tmp_path / f"part{p}.csv"
        with open(path, "w", encoding="utf-8") as f:
            f.write("region,city,v,x\n")
            for _ in range(rows_per):
                r = regions[rng.integers(0, len(regions))]
                c = cities[rng.integers(0, len(cities))] if rng.random() > 0.05 else ""
                v = int(rng.integers(-1000, 1000))
                x = "" if rng.random() < 0.1 else f"{rng.uniform(-5, 5):.6f}"
                f.write(f"{r},{c},{v},{x}\n")
        paths.append(str(path))
    return paths


@pytest.fixture(scope="module")
def workers():
    """Two worker OS processes on ephemeral ports."""
    procs = []
    addrs = []
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    try:
        for _ in range(2):
            proc = subprocess.Popen(
                [sys.executable, "-m", "datafusion_tpu.worker",
                 "--bind", "127.0.0.1:0", "--device", "cpu"],
                cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            )
            procs.append(proc)
            line = proc.stdout.readline()  # "worker listening on host:port"
            assert "listening on" in line, line
            host_port = line.strip().rsplit(" ", 1)[1]
            host, port = host_port.rsplit(":", 1)
            addrs.append((host, int(port)))
        yield procs, addrs
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


def _contexts(addrs, paths):
    dctx = DistributedContext(addrs)
    from datafusion_tpu.exec.datasource import CsvDataSource
    from datafusion_tpu.parallel.partition import PartitionedDataSource

    pds = PartitionedDataSource(
        [CsvDataSource(p, SCHEMA, True, 131072) for p in paths]
    )
    dctx.register_datasource("t", pds)

    lctx = ExecutionContext(device="cpu")
    lctx.register_datasource(
        "t",
        PartitionedDataSource([CsvDataSource(p, SCHEMA, True, 131072) for p in paths]),
    )
    return dctx, lctx


def _rows(ctx, sql):
    def key(row):
        return tuple((v is None, 0 if v is None else v) for v in row)

    return sorted(collect(ctx.sql(sql)).to_rows(), key=key)


class TestDistributedAggregate:
    def test_grouped_aggregate_matches_local(self, tmp_path, workers):
        _, addrs = workers
        paths = _write_partitions(tmp_path)
        dctx, lctx = _contexts(addrs, paths)
        sql = (
            "SELECT region, SUM(v), COUNT(1), AVG(x), MIN(v), MAX(v), "
            "MIN(city), MAX(city) FROM t GROUP BY region"
        )
        got = _rows(dctx, sql)
        want = _rows(lctx, sql)
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert g[:3] == w[:3]
            np.testing.assert_allclose(float(g[3]), float(w[3]), rtol=1e-12)
            assert g[4:] == w[4:]

    def test_filtered_global_aggregate(self, tmp_path, workers):
        _, addrs = workers
        paths = _write_partitions(tmp_path, n_parts=3)
        dctx, lctx = _contexts(addrs, paths)
        sql = "SELECT COUNT(1), SUM(v), MIN(x) FROM t WHERE v > 0"
        assert _rows(dctx, sql) == _rows(lctx, sql)

    def test_distributed_filter_projection_rows(self, tmp_path, workers):
        _, addrs = workers
        paths = _write_partitions(tmp_path)
        dctx, lctx = _contexts(addrs, paths)
        sql = "SELECT region, v + 1, x FROM t WHERE v > 900"
        assert _rows(dctx, sql) == _rows(lctx, sql)

    def test_ping_and_failover(self, tmp_path, workers):
        procs, addrs = workers
        paths = _write_partitions(tmp_path, n_parts=2)
        # one dead endpoint + two live workers: fragments reassign
        dead = ("127.0.0.1", 1)  # port 1: connection refused
        dctx = DistributedContext([dead, *addrs])
        from datafusion_tpu.exec.datasource import CsvDataSource
        from datafusion_tpu.parallel.partition import PartitionedDataSource

        dctx.register_datasource(
            "t",
            PartitionedDataSource(
                [CsvDataSource(p, SCHEMA, True, 131072) for p in paths]
            ),
        )
        health = dctx.ping_workers()
        assert health[f"{dead[0]}:{dead[1]}"] is False
        assert sum(health.values()) == 2

        _, lctx = _contexts(addrs, paths)
        sql = "SELECT region, SUM(v) FROM t GROUP BY region"
        assert _rows(dctx, sql) == _rows(lctx, sql)

    def test_all_workers_down(self, tmp_path):
        from datafusion_tpu.errors import ExecutionError
        from datafusion_tpu.exec.datasource import CsvDataSource
        from datafusion_tpu.parallel.partition import PartitionedDataSource

        paths = _write_partitions(tmp_path, n_parts=1, rows_per=10)
        dctx = DistributedContext([("127.0.0.1", 1)])
        dctx.register_datasource(
            "t",
            PartitionedDataSource(
                [CsvDataSource(p, SCHEMA, True, 131072) for p in paths]
            ),
        )
        with pytest.raises(ExecutionError, match="workers"):
            collect(dctx.sql("SELECT region, SUM(v) FROM t GROUP BY region"))

    def test_global_string_minmax(self, tmp_path, workers):
        # ungrouped Utf8 MIN/MAX: the single-group best-string merge
        _, addrs = workers
        paths = _write_partitions(tmp_path, n_parts=3)
        dctx, lctx = _contexts(addrs, paths)
        sql = "SELECT MIN(region), MAX(region), MIN(city), MAX(city) FROM t"
        assert _rows(dctx, sql) == _rows(lctx, sql)

    def test_empty_partition(self, tmp_path, workers):
        # a header-only partition returns zero groups; the merge skips it
        _, addrs = workers
        paths = _write_partitions(tmp_path, n_parts=2)
        empty = tmp_path / "empty.csv"
        empty.write_text("region,city,v,x\n")
        paths.append(str(empty))
        dctx, lctx = _contexts(addrs, paths)
        sql = "SELECT region, SUM(v), MIN(city) FROM t GROUP BY region"
        assert _rows(dctx, sql) == _rows(lctx, sql)

    def test_parquet_partitions(self, tmp_path, workers):
        # fragment shipping + worker scan over Parquet partition files
        import pyarrow as pa
        import pyarrow.parquet as pq

        _, addrs = workers
        rng = np.random.default_rng(29)
        paths = []
        for p in range(3):
            path = str(tmp_path / f"part{p}.parquet")
            pq.write_table(
                pa.table(
                    {
                        "g": pa.array(rng.integers(0, 4, 400)),
                        "v": pa.array(rng.uniform(-1, 1, 400)),
                    }
                ),
                path,
            )
            paths.append(path)

        from datafusion_tpu.exec.datasource import ParquetDataSource
        from datafusion_tpu.parallel.partition import PartitionedDataSource

        def make_pds():
            return PartitionedDataSource([ParquetDataSource(p) for p in paths])

        dctx = DistributedContext(addrs)
        dctx.register_datasource("t", make_pds())
        lctx = ExecutionContext(device="cpu")
        lctx.register_datasource("t", make_pds())
        sql = "SELECT g, COUNT(1), SUM(v), AVG(v) FROM t GROUP BY g"
        got, want = _rows(dctx, sql), _rows(lctx, sql)
        assert len(got) == len(want) == 4
        for g, w in zip(got, want):
            assert g[:2] == w[:2]
            np.testing.assert_allclose(
                np.asarray(g[2:], float), np.asarray(w[2:], float), rtol=1e-12
            )


class TestInitializeDistributed:
    """`initialize_distributed` (parallel/mesh.py) — the etcd
    replacement — brought up for real across two OS processes on CPU
    (the hermetic analog of a two-host TPU pod bring-up)."""

    def test_two_process_bringup(self, tmp_path):
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        prog = (
            "import sys, jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from datafusion_tpu.parallel.mesh import initialize_distributed\n"
            f"initialize_distributed('127.0.0.1:{port}', 2, int(sys.argv[1]))\n"
            "print('proc', jax.process_index(), 'of', jax.process_count(),\n"
            "      'global_devices', jax.device_count(),\n"
            "      'local', jax.local_device_count(), flush=True)\n"
            "assert jax.process_count() == 2\n"
            "assert jax.device_count() == 2 * jax.local_device_count()\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        env.pop("XLA_FLAGS", None)  # 1 local device per process
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", prog, str(i)],
                cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for i in range(2)
        ]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
        assert all(p.returncode == 0 for p in procs), "\n".join(outs)
        assert "of 2" in outs[0] and "of 2" in outs[1]

    def test_worker_exposes_distributed_flags(self):
        # the worker binary is a real caller of initialize_distributed
        out = subprocess.run(
            [sys.executable, "-m", "datafusion_tpu.worker", "--help"],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO),
            cwd=REPO,
        )
        assert "--coordinator" in out.stdout
        assert "--num-processes" in out.stdout


@pytest.mark.skipif(
    os.environ.get("DATAFUSION_TPU_TEST_TPU_WORKER") != "1",
    reason="needs an attached accelerator; set DATAFUSION_TPU_TEST_TPU_WORKER=1",
)
class TestTpuWorker:
    """A worker OS process serving fragments ON THE REAL CHIP, driven
    by a CPU coordinator — the reference's remote-compute-node intent
    (`scripts/smoketest.sh:30-66`) on actual accelerator hardware.
    Run explicitly (scripts/tpu_worker_smoke.py wraps this)."""

    def test_tpu_worker_serves_fragments(self, tmp_path):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # let the accelerator register
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "datafusion_tpu.worker",
             "--bind", "127.0.0.1:0", "--device", "tpu"],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        try:
            line = proc.stdout.readline()
            assert "listening on" in line, line
            host, port = line.strip().rsplit(" ", 1)[1].rsplit(":", 1)
            info = proc.stdout.readline()
            assert "device=tpu" in info, info
            paths = _write_partitions(tmp_path, n_parts=3, rows_per=400)
            dctx = DistributedContext([(host, int(port))])
            from datafusion_tpu.exec.datasource import CsvDataSource
            from datafusion_tpu.parallel.partition import PartitionedDataSource

            dctx.register_datasource(
                "t",
                PartitionedDataSource(
                    [CsvDataSource(p, SCHEMA, True, 131072) for p in paths]
                ),
            )
            lctx = ExecutionContext(device="cpu")
            lctx.register_datasource(
                "t",
                PartitionedDataSource(
                    [CsvDataSource(p, SCHEMA, True, 131072) for p in paths]
                ),
            )
            sql = (
                "SELECT region, COUNT(1), SUM(v), MIN(v), MAX(v), AVG(x) "
                "FROM t WHERE v > -500 GROUP BY region"
            )
            got = sorted(collect(dctx.sql(sql)).to_rows())
            want = sorted(collect(lctx.sql(sql)).to_rows())
            assert len(got) == len(want)
            for g, w in zip(got, want):
                assert g[:2] == w[:2]
                np.testing.assert_allclose(
                    np.asarray(g[2:], float), np.asarray(w[2:], float),
                    rtol=1e-6,
                )
        finally:
            proc.terminate()
            proc.wait(timeout=10)


class TestWorkerSoak:
    """A worker must survive sustained query pressure from fresh handler
    threads.  Regression for the round-3 SIGSEGV: pyarrow scans issued
    from short-lived `ThreadingTCPServer` handler threads intermittently
    crashed the worker on its 2nd+ query; scans are now confined to one
    persistent IO thread (io/io_thread.py) and workers default to the
    C++ CSV reader.  The soak worker is pinned to the PYARROW reader leg
    on purpose — the worst case — and every request opens a fresh
    connection, so each of the 100 queries runs on a brand-new thread."""

    @pytest.fixture(scope="class")
    def soak_worker(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env["DATAFUSION_TPU_CSV_READER"] = "auto"  # force the pyarrow leg
        proc = subprocess.Popen(
            [sys.executable, "-m", "datafusion_tpu.worker",
             "--bind", "127.0.0.1:0", "--device", "cpu"],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        try:
            line = proc.stdout.readline()
            assert "listening on" in line, line
            host, port = line.strip().rsplit(" ", 1)[1].rsplit(":", 1)
            yield proc, (host, int(port))
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_100_query_soak(self, tmp_path, soak_worker):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from datafusion_tpu.exec.datasource import CsvDataSource, ParquetDataSource
        from datafusion_tpu.parallel.partition import PartitionedDataSource

        proc, addr = soak_worker
        csv_paths = _write_partitions(tmp_path, n_parts=2, rows_per=300)
        rng = np.random.default_rng(43)
        pq_path = str(tmp_path / "soak.parquet")
        pq.write_table(
            pa.table({"g": pa.array(rng.integers(0, 4, 300)),
                      "v": pa.array(rng.uniform(-1, 1, 300))}),
            pq_path,
        )

        def fresh_ctx():
            # a fresh context per query: no connection reuse, maximum
            # handler-thread churn on the worker
            dctx = DistributedContext([addr])
            dctx.register_datasource(
                "t",
                PartitionedDataSource(
                    [CsvDataSource(p, SCHEMA, True, 131072) for p in csv_paths]
                ),
            )
            dctx.register_datasource(
                "pq", PartitionedDataSource([ParquetDataSource(pq_path)])
            )
            return dctx

        queries = [
            "SELECT region, SUM(v), COUNT(1), MIN(city) FROM t GROUP BY region",
            "SELECT region, v, x FROM t WHERE v > 200",
            "SELECT g, COUNT(1), SUM(v) FROM pq GROUP BY g",
        ]
        baselines = [_rows(fresh_ctx(), q) for q in queries]
        for i in range(100):
            q = i % len(queries)
            assert _rows(fresh_ctx(), queries[q]) == baselines[q], (
                f"query #{i} diverged"
            )
            assert proc.poll() is None, f"worker died after query #{i}"
        assert proc.poll() is None


class TestWorkerStatus:
    def test_status_request(self, tmp_path, workers):
        # the reference's worker image EXPOSEd a status web UI that
        # never shipped; this is its working protocol equivalent
        _, addrs = workers
        paths = _write_partitions(tmp_path, n_parts=2, rows_per=100)
        dctx, _ = _contexts(addrs, paths)
        collect(dctx.sql("SELECT region, SUM(v) FROM t GROUP BY region"))
        status = dctx.worker_status()
        assert set(status) == {f"{h}:{p}" for h, p in addrs}
        served = 0
        for s in status.values():
            assert s is not None and s["type"] == "status"
            assert s["uptime_s"] >= 0
            assert "metrics" in s and "devices" in s
            served += s["queries"]
        assert served >= len(paths)  # the fragments we just ran

    def test_status_of_dead_worker_is_none(self):
        dctx = DistributedContext([("127.0.0.1", 1)])
        assert dctx.worker_status() == {"127.0.0.1:1": None}


class TestWorkerHttpStatus:
    """GET /status on the worker's HTTP port returns the same JSON the
    fragment protocol's status request does (the human/probe surface;
    reference worker image EXPOSEd 8080 for it)."""

    def test_http_status_roundtrip(self):
        import json
        import threading
        import urllib.request

        from datafusion_tpu.parallel.worker import serve

        server = serve("127.0.0.1:0", device="cpu", http_port=0)
        # pick a free HTTP port by binding port 0 through the helper
        from datafusion_tpu.parallel.worker import serve_http_status

        http = serve_http_status(server.worker_state, "127.0.0.1", 0)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            port = http.server_address[1]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status", timeout=10
            ) as resp:
                assert resp.status == 200
                body = json.loads(resp.read())
            assert body["type"] == "status"
            assert body["queries"] == 0
            assert "devices" in body and "metrics" in body
            # healthz alias answers too; unknown paths 404
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10
            ) as resp:
                assert resp.status == 200
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=10
                )
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            http.shutdown()
            server.shutdown()
            server.server_close()


class TestThreadedRuntimeStress:
    """Race-hammer the PYTHON-threaded host runtime — the layer the
    native TSan job cannot see (scripts/tsan_check.sh covers only the
    C++ reader/parser; ci.yml documents that scope).  Concurrently:
    prefetch producer threads (DATAFUSION_TPU_PREFETCH=1 forces the
    staged pipeline on CPU), the coordinator's dispatch pool, and the
    workers' socketserver handler threads — many queries in flight from
    many client threads, with faulthandler armed so a deadlock dumps
    stacks instead of hanging CI."""

    def test_concurrent_distributed_and_local_queries(
        self, tmp_path, workers, monkeypatch
    ):
        import faulthandler
        import threading

        faulthandler.dump_traceback_later(240, exit=True)
        try:
            monkeypatch.setenv("DATAFUSION_TPU_PREFETCH", "1")
            _, addrs = workers
            paths = _write_partitions(tmp_path, n_parts=3, rows_per=400)
            sqls = [
                "SELECT region, SUM(v), COUNT(1), AVG(x) FROM t GROUP BY region",
                "SELECT COUNT(1), SUM(v), MIN(x) FROM t WHERE v > 0",
                "SELECT region, v + 1, x FROM t WHERE v > 500",
                "SELECT MIN(city), MAX(city), COUNT(city) FROM t",
            ]
            # reference answers, computed single-threaded first
            lctx_ref = _contexts(addrs, paths)[1]
            want = {sql: _rows(lctx_ref, sql) for sql in sqls}

            errors: list = []

            def hammer(kind: str, rounds: int):
                try:
                    for i in range(rounds):
                        dctx, lctx = _contexts(addrs, paths)
                        ctx = dctx if kind == "dist" else lctx
                        sql = sqls[i % len(sqls)]
                        got = _rows(ctx, sql)
                        if got != want[sql]:
                            errors.append((kind, sql, "mismatch"))
                except Exception as e:  # noqa: BLE001 — collected for the assert
                    errors.append((kind, type(e).__name__, str(e)[:300]))

            threads = [
                threading.Thread(target=hammer, args=("dist", 6), daemon=True)
                for _ in range(3)
            ] + [
                threading.Thread(target=hammer, args=("local", 6), daemon=True)
                for _ in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=220)
                assert not t.is_alive(), "stress thread hung"
            assert not errors, errors
            # workers survived the barrage and still answer
            from datafusion_tpu.parallel.coordinator import WorkerHandle

            for host, port in addrs:
                assert WorkerHandle(host, port).ping()
        finally:
            faulthandler.cancel_dump_traceback_later()
