CREATE EXTERNAL TABLE uk_cities (city VARCHAR(100), lat DOUBLE, lng DOUBLE) STORED AS CSV WITHOUT HEADER ROW LOCATION '/test/data/uk_cities.csv';
SELECT ST_AsText(ST_Point(lat, lng)) FROM uk_cities WHERE lat < 53.0;
SELECT ST_AsText(ST_Point(lat, lng)) FROM uk_cities WHERE lat >= 53.0;