#!/usr/bin/env python
"""Headline benchmark: TPC-H Q1 (scan + filter + 8-way grouped aggregate).

Protocol (BASELINE.md): the reference publishes no numbers and cannot
run this query at all (aggregates are `unimplemented!()` there,
`context.rs:161`), so the baseline is this engine's own single-thread
CPU path on identical inputs; `vs_baseline` is the TPU speedup over it.
3 warm-up runs (covers XLA compile), then p50 of N timed runs.

Prints ONE JSON line on stdout; diagnostics go to stderr.
"""

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


ROWS = int(os.environ.get("BENCH_ROWS", 4_000_000))
BATCH = int(os.environ.get("BENCH_BATCH", 1 << 19))
N_RUNS = int(os.environ.get("BENCH_RUNS", 10))
WARMUP = 3

Q1 = (
    "SELECT l_returnflag, l_linestatus, "
    "SUM(l_quantity), SUM(l_extendedprice), "
    "SUM(l_extendedprice * (1 - l_discount)), "
    "SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)), "
    "AVG(l_quantity), AVG(l_extendedprice), AVG(l_discount), COUNT(1) "
    "FROM lineitem "
    "WHERE l_shipdate <= '1998-09-02' "
    "GROUP BY l_returnflag, l_linestatus"
)


def build_lineitem(rows: int, batch_rows: int):
    """Synthetic TPC-H lineitem columns (the Q1 subset), in-memory."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from datafusion_tpu.datatypes import DataType, Field, Schema
    from datafusion_tpu.exec.batch import StringDictionary, make_host_batch
    from datafusion_tpu.exec.datasource import MemoryDataSource

    schema = Schema(
        [
            Field("l_returnflag", DataType.UTF8, False),
            Field("l_linestatus", DataType.UTF8, False),
            Field("l_quantity", DataType.FLOAT64, False),
            Field("l_extendedprice", DataType.FLOAT64, False),
            Field("l_discount", DataType.FLOAT64, False),
            Field("l_tax", DataType.FLOAT64, False),
            Field("l_shipdate", DataType.UTF8, False),
        ]
    )
    rng = np.random.default_rng(42)

    flag_dict = StringDictionary()
    for s in ("A", "N", "R"):
        flag_dict.add(s)
    status_dict = StringDictionary()
    for s in ("F", "O"):
        status_dict.add(s)
    date_dict = StringDictionary()
    base = np.datetime64("1992-01-01")
    for i in range(2557):  # 1992-01-01 .. 1998-12-31
        date_dict.add(str(base + np.timedelta64(i, "D")))

    batches = []
    for start in range(0, rows, batch_rows):
        n = min(batch_rows, rows - start)
        cols = [
            rng.integers(0, 3, n).astype(np.int32),
            rng.integers(0, 2, n).astype(np.int32),
            np.floor(rng.uniform(1, 51, n)),
            rng.uniform(900.0, 105000.0, n),
            np.round(rng.uniform(0.0, 0.10, n), 2),
            np.round(rng.uniform(0.0, 0.08, n), 2),
            rng.integers(0, 2557, n).astype(np.int32),
        ]
        b = make_host_batch(
            schema, cols,
            [None] * 7,
            [flag_dict, status_dict, None, None, None, None, date_dict],
        )
        batches.append(b)
    return schema, MemoryDataSource(schema, batches)


def bench_device(device, src, rows):
    from datafusion_tpu.exec.context import ExecutionContext
    from datafusion_tpu.exec.materialize import collect

    ctx = ExecutionContext(device=device)
    ctx.register_datasource("lineitem", src)
    rel = ctx.sql(Q1)  # one operator tree -> jit caches persist across runs
    for _ in range(WARMUP):
        collect(rel)
    times = []
    for _ in range(N_RUNS):
        t0 = time.perf_counter()
        table = collect(rel)
        times.append(time.perf_counter() - t0)
    p50 = float(np.median(times))
    log(f"  {device or 'default'}: p50 {p50*1e3:.1f} ms, "
        f"{rows/p50/1e6:.1f} M rows/s, groups={table.num_rows}")
    return p50, table


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import jax

    platforms = {d.platform for d in jax.devices()}
    log(f"devices: {jax.devices()}")
    log(f"building {ROWS} rows of lineitem ...")
    _, src = build_lineitem(ROWS, BATCH)

    has_tpu = any(p != "cpu" for p in platforms)
    cpu_p50, cpu_table = bench_device("cpu", src, ROWS)
    if has_tpu:
        dev_p50, dev_table = bench_device("tpu", src, ROWS)
        got = sorted(dev_table.to_rows())
        want = sorted(cpu_table.to_rows())
        assert len(got) == len(want), f"group count differs: {len(got)} vs {len(want)}"
        for g, w in zip(got, want):
            assert g[:2] == w[:2], f"group keys differ: {g[:2]} vs {w[:2]}"
            np.testing.assert_allclose(
                np.asarray(g[2:], float), np.asarray(w[2:], float), rtol=1e-9,
                err_msg=f"TPU/CPU aggregate mismatch for group {g[:2]}",
            )
    else:
        dev_p50 = cpu_p50

    value = ROWS / dev_p50
    vs_baseline = cpu_p50 / dev_p50
    print(json.dumps({
        "metric": "tpch_q1_throughput",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    main()
