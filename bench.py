#!/usr/bin/env python
"""Benchmark driver: runs the five BASELINE.md configs and prints ONE
JSON line on stdout (diagnostics on stderr).

Headline metric = config 3, TPC-H Q1 over Parquet lineitem: `value` is
the warm (device-resident steady-state) rows/s, `vs_baseline` the TPU
speedup over this engine's own single-thread CPU path on identical
inputs (the reference publishes no numbers and functionally cannot run
the query — aggregates are `unimplemented!()` there, `context.rs:161`).
Cold (scan-inclusive: Parquet parse, dictionary encode, H2D, kernel,
D2H) is reported separately with a per-phase breakdown under
`configs.tpch_q1_parquet`.

Env knobs: BENCH_SF (lineitem scale factor for config 3, default 1),
BENCH_CONFIGS (comma list, default
"1,2,3,4,5,3sf10,worker,cache,conc,ingest,joins,adaptive" —
"3sf10" runs Q1 at the north-star SF-10 scale, "worker" runs the
coordinator->worker-on-chip parity smoke and writes
artifacts/TPU_WORKER_SMOKE.json, "cache" runs the result-cache
warm-repeat phase, "joins" runs the TPC-H Q3/Q5/Q10/Q12 join shapes
against a pandas-merge oracle, "adaptive" runs the cost-store
cold-vs-trained planning comparison), BENCH_RUNS / BENCH_COLD_RUNS.
"""

import json
import os
import sys


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import jax

    from benchmarks import suite

    platforms = {d.platform for d in jax.devices()}
    suite.log(f"devices: {jax.devices()}")
    device_kind = "cpu" if platforms == {"cpu"} else "tpu"

    wanted = os.environ.get(
        "BENCH_CONFIGS",
        "1,2,3,4,5,3sf10,worker,cache,conc,ingest,joins,adaptive",
    ).split(",")
    runners = {
        "1": suite.config1_csv_filter,
        "2": suite.config2_groupby,
        "3": suite.config3_tpch_q1,
        "4": suite.config4_sort_topk,
        "5": suite.config5_mesh,
        # the north-star metric is defined at SF-10 (BASELINE.json);
        # SF-1 stays in the run for round-over-round comparability
        "3sf10": lambda dk: suite.config3_tpch_q1(dk, sf=10),
        # coordinator -> worker-on-the-chip smoke: the remote-compute-
        # node seam (reference scripts/smoketest.sh:30-66) exercised on
        # real hardware as part of every bench run
        "worker": suite.config_worker_smoke,
        # warm-repeat phase: result-cache hit rate + warm/cold speedup
        "cache": suite.config_cache,
        # throughput under concurrency: the serving front door (async
        # admission + HBM-pinned tables + cross-query megabatching) vs
        # serialized back-to-back execution of the same workload
        "conc": suite.config_concurrency,
        # streaming ingestion: Q1 view incremental maintenance rate x
        # freshness vs recomputing the view from scratch per delta
        "ingest": suite.config_ingest,
        # multi-table TPC-H shapes (Q3/Q5/Q10/Q12) through the hash
        # join, gated on pandas-merge parity + a warm pinned-probe
        # launches-per-pass ceiling
        "joins": suite.config_joins,
        # feedback-driven planning: same workload cold vs trained
        # (persisted cost store), gated on >=2 decision flips,
        # bit-exact rows, >=1.2x on the mis-defaulted aggregate
        "adaptive": suite.config_adaptive,
    }
    if float(os.environ.get("BENCH_SF", 1)) == 10 and "3" in [
        w.strip() for w in wanted
    ]:
        # BENCH_SF=10 makes config "3" the SF-10 run already — don't
        # run the most expensive config twice under one output key
        wanted = [w for w in wanted if w.strip() != "3sf10"]
    configs = {}
    for key in wanted:
        key = key.strip()
        if key not in runners:
            continue
        result = runners[key](device_kind)
        configs[result["name"]] = result

    if not configs:
        print(json.dumps({
            "error": f"BENCH_CONFIGS={os.environ.get('BENCH_CONFIGS')!r} "
                     f"selected none of {sorted(runners)}"
        }))
        sys.exit(2)
    # headline = the north-star config: Q1 at SF-10, else SF-1
    headline = configs.get("tpch_q1_parquet_sf10") or configs.get(
        "tpch_q1_parquet"
    )
    if headline is None:  # driver ran a subset; promote the first config
        headline = next(iter(configs.values()))
    print(json.dumps({
        "metric": headline["name"] + "_throughput",
        "value": headline["value"],
        "unit": headline["unit"],
        "vs_baseline": headline["vs_baseline"],
        "device": device_kind,
        "configs": configs,
    }))


if __name__ == "__main__":
    main()
