#!/usr/bin/env python
"""Benchmark driver: runs the five BASELINE.md configs and prints ONE
JSON line on stdout (diagnostics on stderr).

Headline metric = config 3, TPC-H Q1 over Parquet lineitem: `value` is
the warm (device-resident steady-state) rows/s, `vs_baseline` the TPU
speedup over this engine's own single-thread CPU path on identical
inputs (the reference publishes no numbers and functionally cannot run
the query — aggregates are `unimplemented!()` there, `context.rs:161`).
Cold (scan-inclusive: Parquet parse, dictionary encode, H2D, kernel,
D2H) is reported separately with a per-phase breakdown under
`configs.tpch_q1_parquet`.

Env knobs: BENCH_SF (lineitem scale factor, default 1), BENCH_CONFIGS
(comma list, default "1,2,3,4,5"), BENCH_RUNS / BENCH_COLD_RUNS.
"""

import json
import os
import sys


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import jax

    from benchmarks import suite

    platforms = {d.platform for d in jax.devices()}
    suite.log(f"devices: {jax.devices()}")
    device_kind = "cpu" if platforms == {"cpu"} else "tpu"

    wanted = os.environ.get("BENCH_CONFIGS", "1,2,3,4,5").split(",")
    runners = {
        "1": suite.config1_csv_filter,
        "2": suite.config2_groupby,
        "3": suite.config3_tpch_q1,
        "4": suite.config4_sort_topk,
        "5": suite.config5_mesh,
    }
    configs = {}
    for key in wanted:
        key = key.strip()
        if key not in runners:
            continue
        result = runners[key](device_kind)
        configs[result["name"]] = result

    if not configs:
        print(json.dumps({
            "error": f"BENCH_CONFIGS={os.environ.get('BENCH_CONFIGS')!r} "
                     f"selected none of {sorted(runners)}"
        }))
        sys.exit(2)
    headline = configs.get("tpch_q1_parquet")
    if headline is None:  # driver ran a subset; promote the first config
        headline = next(iter(configs.values()))
    print(json.dumps({
        "metric": headline["name"] + "_throughput",
        "value": headline["value"],
        "unit": headline["unit"],
        "vs_baseline": headline["vs_baseline"],
        "device": device_kind,
        "configs": configs,
    }))


if __name__ == "__main__":
    main()
