#!/usr/bin/env python
"""Result & fragment caching worked example.

Runs one GROUP BY twice on a single context (cold fill, warm hit),
shows the EXPLAIN ANALYZE evidence, the per-fingerprint run history,
and the invalidation rule: re-registering the table makes the next run
cold again.

    JAX_PLATFORMS=cpu python examples/caching.py
"""

import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from datafusion_tpu.cache.result import CachedResultRelation
from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.exec.context import ExecutionContext
from datafusion_tpu.exec.materialize import collect


def make_csv(path: str, rows: int = 200_000) -> None:
    rng = np.random.default_rng(5)
    regions = ["north", "south", "east", "west"]
    with open(path, "w", encoding="utf-8") as f:
        f.write("region,v\n")
        for _ in range(rows):
            f.write(f"{regions[rng.integers(0, 4)]},"
                    f"{int(rng.integers(-1000, 1000))}\n")


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="df_tpu_caching_")
    path = os.path.join(tmp, "events.csv")
    make_csv(path)

    schema = Schema([
        Field("region", DataType.UTF8, False),
        Field("v", DataType.INT64, False),
    ])
    ctx = ExecutionContext()  # result cache on by default
    ctx.register_csv("events", path, schema)
    sql = ("SELECT region, SUM(v), COUNT(1), MIN(v), MAX(v) "
           "FROM events GROUP BY region")

    t0 = time.perf_counter()
    cold = collect(ctx.sql(sql))
    cold_s = time.perf_counter() - t0
    print(f"cold run: {cold.num_rows} groups in {cold_s * 1e3:.1f} ms")

    rel = ctx.sql(sql)  # identical SQL -> served from the result cache
    t0 = time.perf_counter()
    warm = collect(rel)
    warm_s = time.perf_counter() - t0
    print(f"warm run: {type(rel).__name__}, {warm.num_rows} groups in "
          f"{warm_s * 1e3:.2f} ms ({cold_s / warm_s:.0f}x)")
    assert isinstance(rel, CachedResultRelation)
    assert sorted(warm.to_rows()) == sorted(cold.to_rows())

    print("\nEXPLAIN ANALYZE on the warm query:")
    print(ctx.sql(f"EXPLAIN ANALYZE {sql}"))

    print("\nresult cache:", ctx.result_cache.stats())
    print("\nrun history for this fingerprint:")
    for run in ctx.stats_history(ctx.last_fingerprint):
        print(f"  cache_hit={run['cache_hit']} rows={run['rows']} "
              f"wall={run['wall_s'] * 1e3:.2f} ms")

    # invalidation: a re-registered table bumps its catalog version,
    # dropping (and un-matching) every dependent entry
    ctx.register_csv("events", path, schema)
    rel = ctx.sql(sql)
    print(f"\nafter re-registering the table: {type(rel).__name__} "
          "(cold again)")
    assert not isinstance(rel, CachedResultRelation)
    collect(rel)


if __name__ == "__main__":
    main()
