#!/usr/bin/env python
"""NDJSON example (the reference declared NDJSON in its DDL,
`dfparser.rs:33`, never implemented a reader, and its release script
expected an `ndjson_sql` example, `scripts/release.sh:18`)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from datafusion_tpu import DataType, ExecutionContext, Field, Schema

DATA = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "test", "data"
)


def main():
    ctx = ExecutionContext()
    schema = Schema(
        [
            Field("a", DataType.INT64, True),
            Field("b", DataType.UTF8, True),
            Field("c", DataType.FLOAT64, True),
        ]
    )
    ctx.register_ndjson("x", os.path.join(DATA, "example1.ndjson"), schema)
    table = ctx.sql_collect("SELECT a, b, c FROM x WHERE a IS NOT NULL ORDER BY c DESC")
    for row in table.to_rows():
        print(row)
    assert table.num_rows > 0


if __name__ == "__main__":
    main()
