#!/usr/bin/env python
"""End-to-end library example: register a CSV, run SQL, print rows.

Mirror of the reference's only executable full-pipeline proof,
`examples/csv_sql.rs:34-105` — same schema, same query, same printed
shape — running the hot path on the attached device (the TPU when one
is present).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from datafusion_tpu import DataType, ExecutionContext, Field, Schema

DATA = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "test", "data"
)


def main():
    # create execution context (reference csv_sql.rs:36)
    ctx = ExecutionContext()

    # define schema for the data source (csv_sql.rs:41-45)
    schema = Schema(
        [
            Field("city", DataType.UTF8, False),
            Field("lat", DataType.FLOAT64, False),
            Field("lng", DataType.FLOAT64, False),
        ]
    )

    # register the CSV data source (csv_sql.rs:47-53; uk_cities.csv has
    # no header row)
    ctx.register_csv("cities", os.path.join(DATA, "uk_cities.csv"), schema,
                     has_header=False)

    # the reference's SQL statement verbatim (csv_sql.rs:56)
    sql = "SELECT city, lat, lng, lat + lng FROM cities WHERE lat > 51.0 AND lat < 53"

    # execute and print each row (csv_sql.rs:59-101)
    table = ctx.sql_collect(sql)
    for city, lat, lng, summed in table.to_rows():
        print(f"City: {city}, Latitude: {lat}, Longitude: {lng}, Sum: {summed}")
    assert table.num_rows == 18, f"expected 18 rows, got {table.num_rows}"


if __name__ == "__main__":
    main()
