#!/usr/bin/env python
"""Cluster control plane worked example: a THREE-replica set with
quorum-acked writes, ranked succession, two coordinators, one shared
worker pool, a shared warm cache hit — and a primary kill the fleet
shrugs off with ZERO acknowledged state lost.

Everything runs in this one process (the in-process deployment shape —
`ClusterNode` + `LocalClusterClient`); swap the client for
`connect("h1:p1,h2:p2,h3:p3")` against three ``python -m
datafusion_tpu.cluster`` processes (`--standby-of`/`--peers`/
`--write-quorum 2`/`--rank N`) and nothing else changes.  The
walk-through:

1. start a PRIMARY and two ranked STANDBY replicas with write quorum 2:
   every client-visible mutation is pushed to the replicas and
   acknowledged only once 2 of the 3 nodes hold it — there is no
   async-replication loss window to "wait out" before a kill;
2. coordinator A discovers the workers from the shared membership
   (no worker list configured anywhere) and runs a GROUP BY;
3. coordinator B — a different context, as if behind a load balancer —
   submits the same SQL and is served from the SHARED result tier:
   no fragment dispatched, `cache.shared=True` on the replay;
4. KILL THE PRIMARY mid-fleet: rank 0's election polls its peers
   (quorum reachability + highest-revision catch-up), promotes with a
   term bump, and re-arms every lease with its SHIPPED remaining
   deadline — the workers keep their original leases, rank 1 observes
   the new term and follows instead of racing, and a coordinator born
   after the kill still gets the warm shared-tier hit;
5. a broadcast invalidation ON THE NEW PRIMARY drops every worker's
   fragment-cache entries on their next lease refresh (no TTL wait);
6. the revived old primary is FENCED: the term exchange demotes it,
   and a write stamped with its stale term is rejected;
7. partition BOTH surviving replicas away from the new primary: a
   write is refused with the transient ``quorum_unavailable`` — the
   cluster would rather fail an ack than lie about durability.

    JAX_PLATFORMS=cpu python examples/cluster.py
"""

import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from datafusion_tpu.cache.result import CachedResultRelation
from datafusion_tpu.cluster import ClusterNode, LocalClusterClient
from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.errors import ClusterQuorumError
from datafusion_tpu.exec.datasource import CsvDataSource
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.parallel.coordinator import DistributedContext
from datafusion_tpu.parallel.partition import PartitionedDataSource
from datafusion_tpu.parallel.worker import serve

SCHEMA = Schema([
    Field("region", DataType.UTF8, False),
    Field("v", DataType.INT64, False),
])
SQL = ("SELECT region, SUM(v), COUNT(1), MIN(v), MAX(v) "
       "FROM events GROUP BY region")
TTL_S = 2.0


def make_partitions(tmp: str, n: int = 4, rows: int = 50_000) -> list:
    rng = np.random.default_rng(5)
    regions = ["north", "south", "east", "west"]
    paths = []
    for p in range(n):
        path = os.path.join(tmp, f"events{p}.csv")
        with open(path, "w", encoding="utf-8") as f:
            f.write("region,v\n")
            for _ in range(rows):
                f.write(f"{regions[rng.integers(0, 4)]},"
                        f"{int(rng.integers(-1000, 1000))}\n")
        paths.append(path)
    return paths


def register(ctx, paths) -> None:
    ctx.register_datasource("events", PartitionedDataSource(
        [CsvDataSource(p, SCHEMA, True, 131072) for p in paths]
    ))


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="df_tpu_cluster_")
    paths = make_partitions(tmp)

    # -- 1. three-replica quorum control plane + two embedded workers --
    primary = ClusterNode(addr="replica:1", write_quorum=2)
    s0 = ClusterNode(addr="replica:2", standby_of=primary, write_quorum=2,
                     rank=0, election_timeout_s=1.0,
                     replicate_interval_s=0.2).start()
    s1 = ClusterNode(addr="replica:3", standby_of=primary, write_quorum=2,
                     rank=1, election_timeout_s=1.0,
                     replicate_interval_s=0.2).start()
    primary.peers = [s0, s1]
    s0.peers = [primary, s1]
    s1.peers = [primary, s0]
    client = LocalClusterClient([primary, s0, s1])
    servers = []
    for _ in range(2):
        server = serve("127.0.0.1:0", device="cpu", cluster=client,
                       lease_ttl_s=TTL_S)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        servers.append(server)
    view = client.membership()
    print(f"membership epoch {view['epoch']} (term {view['term']}, "
          f"write quorum {primary.write_quorum}/"
          f"{primary.cluster_size()}): {sorted(view['workers'])}")

    # -- 2. coordinator A: workers discovered, query executed --
    ca = DistributedContext(cluster=client)
    register(ca, paths)
    print(f"coordinator A discovered {len(ca.workers)} workers")
    t0 = time.perf_counter()
    rows_a = sorted(collect(ca.sql(SQL)).to_rows())
    cold_ms = (time.perf_counter() - t0) * 1e3
    ca._shared_tier.flush()  # write-behind made deterministic for the demo
    print(f"A cold run: {len(rows_a)} groups in {cold_ms:.1f} ms")

    # -- 3. coordinator B: shared-tier warm hit, zero dispatches --
    cb = DistributedContext(cluster=client)
    register(cb, paths)
    t0 = time.perf_counter()
    rel = cb.sql(SQL)
    assert isinstance(rel, CachedResultRelation) and rel.entry.shared
    rows_b = sorted(collect(rel).to_rows())
    warm_ms = (time.perf_counter() - t0) * 1e3
    assert rows_a == rows_b
    print(f"B warm run: shared-tier hit in {warm_ms:.2f} ms "
          f"({cold_ms / max(warm_ms, 1e-6):.0f}x); "
          f"attrs {rel.stats.attrs}")

    # -- 4. kill the PRIMARY: ranked election, zero acked loss --
    # NO "wait for replication" step here: with write quorum 2, every
    # acknowledged mutation (grants, joins, result publishes) already
    # sits on 2 of the 3 replicas — the loss window the old
    # cluster.replication_lag_revisions gauge measured is closed by
    # construction.
    leases = [s.worker_state.cluster_agent.lease for s in servers]
    primary.partitioned = True  # SIGKILL, in-process
    deadline = time.monotonic() + 15.0
    while s0.role != "primary" and s1.role != "primary":
        assert time.monotonic() < deadline, "no replica promoted"
        time.sleep(0.05)
    new_primary = s0 if s0.role == "primary" else s1
    print(f"primary killed -> rank {new_primary.rank} promoted: "
          f"term={new_primary.term}, elections deferred by the other "
          f"rank: {(s1 if new_primary is s0 else s0).elections_deferred}")
    for s, lease in zip(servers, leases):
        agent = s.worker_state.cluster_agent
        agent.poll_once()  # heartbeat fails over inside the client
        assert agent.lease == lease and agent.reregistrations == 0
    print("worker leases preserved across the failover "
          "(0 re-registrations — remaining deadlines shipped, "
          "not full-TTL re-armed)")
    cc = DistributedContext(cluster=client)  # born after the kill
    register(cc, paths)
    rel = cc.sql(SQL)
    assert isinstance(rel, CachedResultRelation) and rel.entry.shared
    assert sorted(collect(rel).to_rows()) == rows_a
    print(f"post-failover coordinator: warm shared hit still lands; "
          f"gauges {cc.membership.gauges()}")

    # -- 5. invalidation broadcast on the NEW primary beats the TTL --
    total = sum(s.worker_state.fragment_cache.entries for s in servers)
    ca.broadcast_invalidate("events")  # rides the failover client
    for s in servers:
        s.worker_state.cluster_agent.poll_once()  # the next heartbeat
    left = sum(s.worker_state.fragment_cache.entries for s in servers)
    print(f"invalidation broadcast (post-failover): fragment-cache "
          f"entries {total} -> {left}")

    # -- 6. the revived old primary is fenced --
    primary.partitioned = False
    out = new_primary.handle_request({"type": "kv_put", "key": "boom",
                                      "value": 1, "term": 1})
    print(f"stale-term write from the old primary: {out['code']!r}")
    primary.handle_request({"type": "peer_status",
                            "term": new_primary.term,
                            "role": "primary", "addr": new_primary.addr})
    print(f"old primary after the term exchange: role={primary.role}, "
          f"term={primary.term} (resyncs as a standby)")

    # -- 7. quorum loss refuses the ack instead of lying --
    other = s1 if new_primary is s0 else s0
    primary.partitioned = True
    other.partitioned = True
    try:
        LocalClusterClient(new_primary).put("config/x", 1)
        raise AssertionError("a quorumless write must not be acked")
    except ClusterQuorumError as e:
        print(f"write with both replicas gone: refused transiently "
              f"({e.acks}/{e.quorum} acks) — retry when the set heals")
    primary.partitioned = False
    other.partitioned = False

    s0.stop()
    s1.stop()
    ca.close()
    cb.close()
    cc.close()
    for s in servers:
        agent = s.worker_state.cluster_agent
        if agent is not None:
            agent.close()
        try:
            s.shutdown()
            s.server_close()
        except OSError:
            pass


if __name__ == "__main__":
    main()
