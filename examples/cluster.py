#!/usr/bin/env python
"""Cluster control plane worked example: two coordinators, one shared
worker pool, a shared warm cache hit.

Everything runs in this one process (the in-process deployment shape —
`ClusterState` + `LocalClusterClient`); swap the client for
`connect("host:port")` against ``python -m datafusion_tpu.cluster`` and
nothing else changes.  The walk-through:

1. start a cluster state, register two embedded workers under TTL
   leases;
2. coordinator A discovers the workers from the shared membership
   (no worker list configured anywhere) and runs a GROUP BY;
3. coordinator B — a different context, as if behind a load balancer —
   submits the same SQL and is served from the SHARED result tier:
   no fragment dispatched, `cache.shared=True` on the replay;
4. a broadcast invalidation drops every worker's fragment-cache
   entries on their next lease refresh (no TTL wait);
5. kill a worker abruptly: both coordinators converge to the same
   bumped membership epoch within one lease TTL.

    JAX_PLATFORMS=cpu python examples/cluster.py
"""

import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from datafusion_tpu.cache.result import CachedResultRelation
from datafusion_tpu.cluster import ClusterState, LocalClusterClient
from datafusion_tpu.datatypes import DataType, Field, Schema
from datafusion_tpu.exec.datasource import CsvDataSource
from datafusion_tpu.exec.materialize import collect
from datafusion_tpu.parallel.coordinator import DistributedContext
from datafusion_tpu.parallel.partition import PartitionedDataSource
from datafusion_tpu.parallel.worker import serve

SCHEMA = Schema([
    Field("region", DataType.UTF8, False),
    Field("v", DataType.INT64, False),
])
SQL = ("SELECT region, SUM(v), COUNT(1), MIN(v), MAX(v) "
       "FROM events GROUP BY region")
TTL_S = 1.0


def make_partitions(tmp: str, n: int = 4, rows: int = 50_000) -> list:
    rng = np.random.default_rng(5)
    regions = ["north", "south", "east", "west"]
    paths = []
    for p in range(n):
        path = os.path.join(tmp, f"events{p}.csv")
        with open(path, "w", encoding="utf-8") as f:
            f.write("region,v\n")
            for _ in range(rows):
                f.write(f"{regions[rng.integers(0, 4)]},"
                        f"{int(rng.integers(-1000, 1000))}\n")
        paths.append(path)
    return paths


def register(ctx, paths) -> None:
    ctx.register_datasource("events", PartitionedDataSource(
        [CsvDataSource(p, SCHEMA, True, 131072) for p in paths]
    ))


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="df_tpu_cluster_")
    paths = make_partitions(tmp)

    # -- 1. control plane + two embedded workers under 1s leases --
    client = LocalClusterClient(ClusterState())
    servers = []
    for _ in range(2):
        server = serve("127.0.0.1:0", device="cpu", cluster=client,
                       lease_ttl_s=TTL_S)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        servers.append(server)
    view = client.membership()
    print(f"membership epoch {view['epoch']}: "
          f"{sorted(view['workers'])}")

    # -- 2. coordinator A: workers discovered, query executed --
    ca = DistributedContext(cluster=client)
    register(ca, paths)
    print(f"coordinator A discovered {len(ca.workers)} workers")
    t0 = time.perf_counter()
    rows_a = sorted(collect(ca.sql(SQL)).to_rows())
    cold_ms = (time.perf_counter() - t0) * 1e3
    ca._shared_tier.flush()  # write-behind made deterministic for the demo
    print(f"A cold run: {len(rows_a)} groups in {cold_ms:.1f} ms")

    # -- 3. coordinator B: shared-tier warm hit, zero dispatches --
    cb = DistributedContext(cluster=client)
    register(cb, paths)
    t0 = time.perf_counter()
    rel = cb.sql(SQL)
    assert isinstance(rel, CachedResultRelation) and rel.entry.shared
    rows_b = sorted(collect(rel).to_rows())
    warm_ms = (time.perf_counter() - t0) * 1e3
    assert rows_a == rows_b
    print(f"B warm run: shared-tier hit in {warm_ms:.2f} ms "
          f"({cold_ms / max(warm_ms, 1e-6):.0f}x); "
          f"attrs {rel.stats.attrs}")

    # -- 4. invalidation broadcast beats the TTL --
    total = sum(s.worker_state.fragment_cache.entries for s in servers)
    ca.broadcast_invalidate("events")
    for s in servers:
        s.worker_state.cluster_agent.poll_once()  # the next heartbeat
    left = sum(s.worker_state.fragment_cache.entries for s in servers)
    print(f"invalidation broadcast: fragment-cache entries {total} -> {left}")

    # -- 5. abrupt worker death: shared epoch convergence --
    e0 = ca.cluster_epoch()
    servers[1].worker_state.cluster_agent.stop()  # no revoke: a crash
    servers[1].shutdown()
    deadline = time.monotonic() + 3 * TTL_S
    while ca.cluster_epoch() == e0 and time.monotonic() < deadline:
        time.sleep(0.1)
    print(f"after kill: epoch {e0} -> A={ca.cluster_epoch()}, "
          f"B={cb.cluster_epoch()} (one lease TTL)")
    print(f"coordinator gauges: {ca.membership.gauges()}")

    ca.close()
    cb.close()
    for s in servers:
        agent = s.worker_state.cluster_agent
        if agent is not None:
            agent.close()
        try:
            s.shutdown()
            s.server_close()
        except OSError:
            pass


if __name__ == "__main__":
    main()
