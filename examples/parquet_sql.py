#!/usr/bin/env python
"""Parquet + aggregate example (the reference declared Parquet in DDL
but never implemented a reader, `README.md:22`; its release script
expected a `parquet_sql` example, `scripts/release.sh:19`)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from datafusion_tpu import ExecutionContext

DATA = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "test", "data"
)


def main():
    ctx = ExecutionContext()
    # schema inferred from parquet file metadata
    ctx.register_parquet("cities", os.path.join(DATA, "uk_cities.parquet"))
    table = ctx.sql_collect(
        "SELECT COUNT(1), MIN(lat), MAX(lat), AVG(lng) FROM cities WHERE lat > 52"
    )
    (count, lo, hi, avg_lng) = table.to_rows()[0]
    print(f"{count} cities north of 52: lat range [{lo}, {hi}], mean lng {avg_lng}")
    assert count > 0


if __name__ == "__main__":
    main()
