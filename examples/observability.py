#!/usr/bin/env python
"""Worked observability example: spans, EXPLAIN ANALYZE, and the
exporters (datafusion_tpu/obs/).

Runs a query three ways over the repo's uk_cities fixture:

1. `EXPLAIN ANALYZE <sql>` — a real execution whose operator tree is
   annotated with measured rows, batches, device-execute vs XLA-compile
   time, and H2D/D2H bytes;
2. a manually-traced block (`obs.trace.session()` + `span(...)`) with a
   Chrome-trace export you can load in chrome://tracing or
   https://ui.perfetto.dev;
3. a Prometheus text dump of the engine counters.

Equivalent env knobs for production use: `DATAFUSION_TPU_TRACE=1`
enables span collection engine-wide and `DATAFUSION_TPU_TRACE_FILE=
/tmp/q.json` writes the Chrome trace at process exit.  In the console,
`\\explain SELECT ...` renders the same EXPLAIN ANALYZE report.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from datafusion_tpu import DataType, ExecutionContext, Field, Schema

DATA = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "test", "data"
)


def main():
    ctx = ExecutionContext()
    schema = Schema(
        [
            Field("city", DataType.UTF8, False),
            Field("lat", DataType.FLOAT64, False),
            Field("lng", DataType.FLOAT64, False),
        ]
    )
    ctx.register_csv("cities", os.path.join(DATA, "uk_cities.csv"), schema,
                     has_header=False)

    # 1. EXPLAIN ANALYZE: the annotated operator tree + span timeline
    res = ctx.sql_collect(
        "EXPLAIN ANALYZE SELECT city, lat, lng FROM cities "
        "WHERE lat > 52.0 ORDER BY lat DESC LIMIT 5"
    )
    print(res.report())
    print()

    # the analyzed run is a real run — its rows are right here
    for row in res.result.to_rows():
        print("Top city:", row)
    print()

    # 2. manual spans around library calls + Chrome-trace export
    from datafusion_tpu.obs import trace

    with trace.session() as tc:
        with trace.span("warm_and_query", note="observability example"):
            with trace.span("warm"):
                ctx.sql_collect("SELECT COUNT(1) FROM cities")
            with trace.span("query"):
                table = ctx.sql_collect(
                    "SELECT city, lat FROM cities WHERE lng < 0"
                )
    spans = trace.drain(tc.trace_id)
    out = os.path.join(tempfile.gettempdir(), "datafusion_tpu_example.json")
    from datafusion_tpu.obs.export import write_chrome_trace

    write_chrome_trace(out, spans)
    print(f"{len(spans)} spans from the manual session "
          f"({table.num_rows} rows); Chrome trace written to {out}")
    print("load it in chrome://tracing or https://ui.perfetto.dev")
    print()

    # 3. the engine counters, Prometheus-style
    print(ctx.metrics_text())
    return 0


if __name__ == "__main__":
    sys.exit(main())
