#!/usr/bin/env python
"""DataFrame-API twin of csv_sql.py (the reference's release script
expected a `csv_dataframe` example that never existed in its snapshot,
`scripts/release.sh:17` / `scripts/circle/build-examples.sh:8-9`)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from datafusion_tpu import DataType, ExecutionContext, Field, Schema, lit

DATA = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "test", "data"
)


def main():
    ctx = ExecutionContext()
    schema = Schema(
        [
            Field("city", DataType.UTF8, False),
            Field("lat", DataType.FLOAT64, False),
            Field("lng", DataType.FLOAT64, False),
        ]
    )
    ctx.register_csv("cities", os.path.join(DATA, "uk_cities.csv"), schema,
                     has_header=False)

    cities = ctx.table("cities")
    lat, lng = cities["lat"], cities["lng"]
    df = (
        cities
        .filter(lat.gt(lit(51.0)).and_(lat.lt(lit(53.0))))
        .select("city", lat, lng, lat + lng)
    )
    table = df.collect()
    for city, lat, lng, summed in table.to_rows():
        print(f"City: {city}, Latitude: {lat}, Longitude: {lng}, Sum: {summed}")
    assert table.num_rows == 18, f"expected 18 rows, got {table.num_rows}"


if __name__ == "__main__":
    main()
